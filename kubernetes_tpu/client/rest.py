"""HTTP REST client over aiohttp.

Reference: client-go ``rest/`` (request builder, error mapping) — the
transport every out-of-process component (node agent, CLI, kubemark
hollow nodes) uses to reach the apiserver. Watches consume the server's
chunked JSON-lines stream, surfacing BOOKMARK events so reflectors can
advance their resume revision without traffic.

Every request goes through :meth:`RESTClient._request`: explicit
connect/total timeouts (a dropped connection must never hang a
controller forever), capped exponential backoff with jitter for
idempotent reads, and Retry-After-honoring 429 handling for every verb
(client-go's rest.Request retry + the flowcontrol backoff, compressed).
The same seam is the ``rest`` chaos injection site (chaos/core.py).
"""
from __future__ import annotations

import asyncio
import json
import random
from typing import Any, Optional

import aiohttp

from .. import tracing
from ..analysis import loopsan
from ..api import errors
from ..api.scheme import DEFAULT_SCHEME, to_dict
from ..api.types import Binding
from ..chaos import core as chaos
from ..metrics.registry import Counter, Histogram
from .interface import Client, WatchStream

BOOKMARK = "BOOKMARK"
CLOSED = "CLOSED"

CLIENT_RETRIES = Counter(
    "client_retry_total",
    "REST client request retries by verb and reason",
    labels=("verb", "reason"))

CLIENT_BACKOFF = Histogram(
    "client_backoff_seconds",
    "Seconds the REST client slept backing off before a retry",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0))

CLIENT_REDIRECTS = Counter(
    "client_redirect_total",
    "Leader-hint redirects (307/308) the REST client followed, by verb",
    labels=("verb",))

CLIENT_FOLLOWER_READS = Counter(
    "client_follower_read_total",
    "Read-affinity traffic: reads/watches routed to follower "
    "endpoints, and bounded-staleness fallbacks to the leader",
    labels=("outcome",))

#: HTTP statuses a retryable (idempotent) request may retry on — the
#: server-side/transient family; 4xx client errors never retry.
_RETRYABLE_STATUS = (500, 502, 503, 504)


def decode_obj(data: dict):
    """Scheme decode with a CustomResource fallback: a client that has
    not locally registered a CRD's kind still gets a usable object."""
    try:
        return DEFAULT_SCHEME.decode(data)
    except KeyError:
        from ..api.extensions import CustomResource
        from ..api.scheme import from_dict
        obj = from_dict(CustomResource, data)
        obj.api_version = data.get("api_version", "")
        obj.kind = data.get("kind", "")
        return obj


def _parse_retry_after(raw: Optional[str]) -> Optional[float]:
    """Seconds from a Retry-After header (seconds form only; the
    HTTP-date form is not worth a date parser here), capped so a
    confused server cannot park a controller for minutes."""
    if not raw:
        return None
    try:
        return min(max(float(raw), 0.0), 30.0)
    except ValueError:
        return None


def _resource_tables() -> tuple[dict, dict]:
    from ..apiserver.registry import builtin_resources
    by_plural: dict[str, tuple[str, bool]] = {}
    by_kind: dict[str, str] = {}
    for spec in builtin_resources():
        by_plural[spec.plural] = (spec.api_version, spec.namespaced)
        by_kind[spec.kind] = spec.plural
    return by_plural, by_kind


_BY_PLURAL, _BY_KIND = _resource_tables()


class _RESTWatch(WatchStream):
    def __init__(self, session: aiohttp.ClientSession, url: str, params: dict,
                 timeout: aiohttp.ClientTimeout,
                 headers: Optional[dict] = None):
        self._session = session
        self._url = url
        self._params = params
        self._headers = headers
        #: total=None (streams live indefinitely) but connect and
        #: sock_read bounded (RESTClient.watch builds this from its
        #: connect_timeout/watch_idle_timeout): the server bookmarks
        #: idle streams every ~10s, so a silent socket means a dead
        #: peer — surface it so the informer relists instead of
        #: hanging forever.
        self._timeout = timeout
        self._resp: Optional[aiohttp.ClientResponse] = None
        self._task: Optional[asyncio.Task] = None
        self._queue: asyncio.Queue = asyncio.Queue()
        self._closed = False
        #: True once the server stream has ended (consumer must reconnect).
        self.closed = False
        #: Highest revision a BOOKMARK frame carried on this stream —
        #: the resume point a reconnect may watch from instead of
        #: relisting (WatchBookmarks); 0 until the first bookmark.
        self.bookmark_revision = 0

    async def _run(self) -> None:
        from ..util import compactcodec
        try:
            kw = {"headers": self._headers} if self._headers else {}
            async with self._session.get(self._url, params=self._params,
                                         timeout=self._timeout,
                                         **kw) as resp:
                if resp.status != 200:
                    body = await resp.json()
                    await self._queue.put(("ERROR", errors.StatusError.from_dict(body)))
                    return
                self._resp = resp
                if resp.content_type == compactcodec.CONTENT_TYPE:
                    # Negotiated compact stream: length-prefixed
                    # msgpack frames instead of JSON lines; the event
                    # handling below is shared.
                    frames = compactcodec.FrameDecoder()
                    async for chunk in resp.content.iter_any():
                        for payload in frames.feed(chunk):
                            if not await self._dispatch(
                                    compactcodec.decode_event(payload)):
                                return
                    return
                async for line in resp.content:
                    line = line.strip()
                    if not line:
                        continue
                    if not await self._dispatch(json.loads(line)):
                        return
        except (aiohttp.ClientError, asyncio.CancelledError,
                ConnectionResetError, asyncio.TimeoutError):
            pass
        finally:
            await self._queue.put(None)

    async def _dispatch(self, msg: dict) -> bool:
        """Queue one decoded wire event; False ends the stream (chaos
        drop — the consumer relists, as for a real broken stream)."""
        c = chaos.CONTROLLER
        if c is not None:
            fault = c.decide(chaos.SITE_WATCH_REST)
            if fault is not None and fault.kind == "drop":
                return False
        if msg["type"] == BOOKMARK:
            try:
                rv = int(msg["object"]["metadata"]["resource_version"])
                self.bookmark_revision = max(self.bookmark_revision, rv)
            except (KeyError, TypeError, ValueError):
                pass
            await self._queue.put((BOOKMARK, msg["object"]))
            return True
        # loopsan child seam: the typed decode of every watch event is
        # the informer-ingest cost the parent queue-stage share hid —
        # named for its dominant consumer, the scheduler's pod informer.
        with loopsan.seam("scheduler.queue.decode"):
            obj = decode_obj(msg["object"])
        await self._queue.put((msg["type"], obj))
        return True

    def start(self) -> "_RESTWatch":
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    def cancel(self) -> None:
        if not self._closed:
            self._closed = True
            if self._task:
                self._task.cancel()

    async def next(self, timeout: Optional[float] = None):
        """None on idle timeout; ("CLOSED", None) when the stream ended."""
        if self.closed:
            return (CLOSED, None)
        if timeout is None:
            ev = await self._queue.get()
        else:
            try:
                ev = await asyncio.wait_for(self._queue.get(), timeout)
            except asyncio.TimeoutError:
                return None
        if ev is None:
            self.closed = True
            return (CLOSED, None)
        if ev[0] == "ERROR":
            raise ev[1]
        return ev


class RESTClient(Client):
    def __init__(self, base_url, token: str = "",
                 ca_file: str = "", client_cert: str = "",
                 client_key: str = "", check_hostname: bool = True,
                 impersonate_user: str = "",
                 impersonate_groups: tuple = (),
                 read_affinity: bool = False,
                 session: Optional["aiohttp.ClientSession"] = None):
        """``base_url`` may name SEVERAL apiserver endpoints — a
        comma-separated string or a list — for a replicated control
        plane: requests pin to one endpoint and fail over to the next
        on connect errors and retryable 5xx, follow 307 leader hints
        (re-pinning to the leader's origin), and treat a follower's
        no-leader 503 as a backoff-able wait, so controllers and the
        scheduler ride a leader crash with no code changes.
        ``ca_file`` makes https URLs verify against the cluster CA;
        ``client_cert``/``client_key`` authenticate with an x509
        identity cert (CN=user, O=groups) instead of / beside a token.
        ``check_hostname=False`` only for callers that pinned the peer
        another way (the join flow's CA fingerprint — its --server
        address is routinely absent from the apiserver cert SANs).
        ``impersonate_user``/``impersonate_groups``: act as another
        identity (kubectl --as / --as-group; RBAC 'impersonate' verb
        required server-side).
        ``session``: a SHARED ``aiohttp.ClientSession`` this client
        rides instead of building its own session + connector. The
        hollow fleet multiplexes thousands of per-node clients onto one
        connector pool per event loop this way — N clients otherwise
        cost N connectors (and N keep-alive sockets minimum). A shared
        session is NOT owned: ``close()`` leaves it open (the fleet
        closes it once), and this client's auth headers attach per
        request instead of per session so sharing never mixes
        credentials (impersonation's repeated-header form is the one
        identity a shared session cannot carry — those clients keep
        their own session).
        ``read_affinity=True`` (multi-endpoint planes only): GETs,
        LISTs, and watches route to FOLLOWER endpoints round-robin —
        bounded-staleness reads carrying X-Ktpu-Max-Staleness
        (``self.max_staleness``) — so informer relist/watch fan-out
        stops competing with the write path on the leader. Writes keep
        the leader-routed 307 machinery unchanged. A follower that
        cannot meet the bound answers 503 + X-Ktpu-Stale; the client
        then retries the LEADER once — never counted against the
        mutation-failover rotation budget (a stale follower is not a
        dead endpoint)."""
        if isinstance(base_url, (list, tuple)):
            eps = [u.rstrip("/") for u in base_url if u]
        else:
            eps = [u.strip().rstrip("/")
                   for u in base_url.split(",") if u.strip()]
        if not eps:
            raise ValueError("RESTClient needs at least one endpoint")
        #: The failover ring; ``base_url`` is the currently pinned
        #: endpoint (possibly a redirect-learned leader origin outside
        #: the ring).
        self._endpoints = eps
        self.base_url = eps[0]
        #: Follower read/watch offload (see class docstring).
        self.read_affinity = read_affinity and len(eps) > 1
        #: Staleness bound follower reads tolerate before falling back
        #: to the leader (sent as X-Ktpu-Max-Staleness; the server
        #: caps it at its own follower_staleness_bound).
        self.max_staleness = 2.0
        self._read_rr = 0
        self._headers = {"Authorization": f"Bearer {token}"} if token else {}
        if impersonate_user:
            self._headers["Impersonate-User"] = impersonate_user
        # aiohttp headers dicts can't repeat keys; use a CIMultiDict.
        if impersonate_groups:
            from multidict import CIMultiDict
            h = CIMultiDict(self._headers)
            for g in impersonate_groups:
                h.add("Impersonate-Group", g)
            self._headers = h
        self._ssl = None
        if ca_file:
            from ..apiserver.certs import client_ssl_context
            self._ssl = client_ssl_context(ca_file, client_cert, client_key,
                                           check_hostname=check_hostname)
        self._session: Optional[aiohttp.ClientSession] = None
        #: Shared (unowned) session, if the composer provided one.
        self._shared_session = session
        if session is not None and impersonate_groups:
            raise ValueError(
                "shared sessions cannot carry repeated Impersonate-Group "
                "headers; give impersonating clients their own session")
        #: Per-request deadlines (client-go rest.Config.Timeout analog).
        #: The old default — ClientTimeout(total=None) — meant one
        #: dropped connection hung its controller forever; now every
        #: non-watch request has an explicit connect + total budget,
        #: overridable per call via ``_request(..., timeout=)``.
        self.connect_timeout = 5.0
        self.total_timeout = 30.0
        #: Idle bound for watch streams (sock_read): the server
        #: bookmarks every ~10s, so a quiet socket is a dead peer.
        self.watch_idle_timeout = 60.0
        #: Retry policy: idempotent reads retry transport errors and
        #: 5xx with capped exponential backoff + full jitter; 429
        #: retries for EVERY verb (the server refused before acting)
        #: honoring its Retry-After header.
        self.max_retries = 3
        self.backoff_base = 0.05
        self.backoff_cap = 2.0
        #: Leader-hint (307/308) hops one logical request may take.
        #: Repeated redirects past the first back off (capped
        #: exponential + full jitter, same knobs as retries) — a stale
        #: leader hint chasing its own tail must never hot-loop.
        self.max_redirects = 8
        #: Connector tuning for the ONE shared session every request
        #: rides (see _sess): high-rate single-host clients (the
        #: scheduler firing binds, loadgen firing creates) must reuse
        #: keep-alive connections instead of racing 100 sockets at one
        #: apiserver. Raise for clients that fan out across many hosts.
        self.conn_limit_per_host = 32
        #: Discovery-learned resources (CRDs): plural -> (gv, namespaced).
        #: TTL'd so CRD deletion/recreation is picked up (the static
        #: builtin table never goes stale and never expires).
        self._dynamic: dict[str, tuple[str, bool]] = {}
        self._dynamic_kinds: dict[str, str] = {}
        self._discovery_at = 0.0
        self.discovery_ttl = 15.0

    async def token_review(self, token: str) -> Optional[tuple[str, set]]:
        """Delegated authn (authentication/v1 TokenReview): resolve a
        SUBJECT's bearer token to (username, groups) using this
        client's own credential; None if not authenticated. The node
        server uses it for token-bearing callers (kubelet
        --authentication-token-webhook model)."""
        url = f"{self.base_url}/apis/authentication/v1/tokenreviews"
        try:
            # Side-effect free: safe to mark idempotent (retryable).
            body = await self._request("POST", url, idempotent=True,
                                       json={"spec": {"token": token}})
        except errors.StatusError:
            return None
        status = body.get("status") or {}
        if not status.get("authenticated"):
            return None
        user = status.get("user") or {}
        return user.get("username", ""), set(user.get("groups") or ())

    async def access_review(self, verb: str, resource: str,
                            namespace: str = "", name: str = "",
                            user: str = "",
                            groups: tuple = ()) -> tuple[bool, str]:
        """authorization/v1 access review -> (allowed, reason).

        Without ``user``: SelfSubjectAccessReview — "can *I* do this?"
        (``kubectl auth can-i``). With ``user``: SubjectAccessReview —
        asks about someone else; needs ``create subjectaccessreviews``.
        """
        which = ("subjectaccessreviews" if user
                 else "selfsubjectaccessreviews")
        spec: dict = {"resource_attributes": {
            "verb": verb, "resource": resource,
            "namespace": namespace, "name": name}}
        if user:
            spec["user"] = user
            spec["groups"] = list(groups)
        url = f"{self.base_url}/apis/authorization/v1/{which}"
        body = await self._request("POST", url, idempotent=True,
                                   json={"spec": spec})
        status = body.get("status") or {}
        return bool(status.get("allowed")), status.get("reason", "")

    @property
    def ssl_context(self):
        """The client TLS context (CA trust + identity cert), or None.
        Node-server consumers (ktl logs/exec/top) reuse it — same CA,
        same identity — for the kubelet-analog HTTPS endpoints."""
        return self._ssl

    #: Strong refs to in-flight old-session close tasks (asyncio keeps
    #: only weak refs; an unreferenced close task can be GC'd before
    #: running, leaking the connector's sockets).
    _close_tasks: set = set()

    def rebuild_ssl(self, ca_file: str, client_cert: str = "",
                    client_key: str = "",
                    check_hostname: bool = True) -> None:
        """Reload TLS material (cert rotation): the next request gets
        a fresh session/connector with the new identity. Closing the
        old session interrupts requests still using it — long-lived
        watches reconnect by design (reflector semantics), which is
        exactly the behavior rotation wants: streams move to the new
        credential."""
        from ..apiserver.certs import client_ssl_context
        self._ssl = client_ssl_context(ca_file, client_cert, client_key,
                                       check_hostname=check_hostname)
        if self._session is not None and not self._session.closed:
            session = self._session
            self._session = None
            try:
                task = asyncio.get_running_loop().create_task(
                    session.close())
                RESTClient._close_tasks.add(task)
                task.add_done_callback(RESTClient._close_tasks.discard)
            except RuntimeError:
                pass  # no loop: abandoned session is GC'd

    def _sess(self) -> aiohttp.ClientSession:
        """The ONE long-lived session (and connector) every request
        uses. Keep-alive assumption, stated: sequential requests to the
        same apiserver reuse a single pooled TCP connection — aiohttp
        returns the connection to the pool on response release and the
        server keeps it open (its keep-alive timeout far exceeds any
        request gap in a control loop). N sequential binds therefore
        cost one connection setup, not N — tested by
        tests/integration/test_http_api.py's connection-reuse test.
        ``conn_limit_per_host`` bounds the burst-parallelism fan-out to
        one host; beyond it requests queue on the pool rather than
        opening sockets the apiserver must accept/teardown."""
        if self._shared_session is not None \
                and not self._shared_session.closed:
            return self._shared_session
        if self._session is None or self._session.closed:
            kw = {"ssl": self._ssl} if self._ssl is not None else {}
            connector = aiohttp.TCPConnector(
                limit_per_host=self.conn_limit_per_host, **kw)
            self._session = aiohttp.ClientSession(headers=self._headers,
                                                  connector=connector)
        return self._session

    def _identity_kw(self, kw: dict) -> dict:
        """On a shared session, this client's identity headers ride the
        REQUEST (the session's defaults belong to whoever built it).
        Owned sessions already carry them as defaults — no-op."""
        if self._shared_session is not None and self._headers:
            headers = dict(kw.pop("headers", None) or {})
            for k, v in self._headers.items():
                headers.setdefault(k, v)
            kw["headers"] = headers
        return kw

    def _url_for(self, api_version: str, plural: str, namespace: str,
                 name: str = "", subresource: str = "") -> str:
        parts = [self.base_url, "api", api_version]
        if namespace:
            parts += ["namespaces", namespace]
        parts.append(plural)
        if name:
            parts.append(name)
        if subresource:
            parts.append(subresource)
        return "/".join(parts)

    async def _plural_info(self, plural: str) -> tuple[str, bool]:
        """Static mirror of the server's resource table (avoids discovery
        RTT); unknown plurals (CRDs installed at runtime) fall back to
        the /apis discovery document, cached per client."""
        try:
            return _BY_PLURAL[plural]
        except KeyError:
            pass
        await self._refresh_discovery()  # no-op within the TTL window
        try:
            return self._dynamic[plural]
        except KeyError:
            raise errors.NotFoundError(
                f"unknown resource type {plural!r}") from None

    async def _refresh_discovery(self) -> None:
        import time
        if time.monotonic() - self._discovery_at < self.discovery_ttl \
                and self._dynamic:
            return
        data = await self._request("GET", f"{self.base_url}/apis")
        self._dynamic.clear()
        self._dynamic_kinds.clear()
        for res in data.get("resources", []):
            self._dynamic[res["name"]] = (res["api_version"], res["namespaced"])
            self._dynamic_kinds[res["kind"]] = res["name"]
        self._discovery_at = time.monotonic()

    async def _check(self, resp: aiohttp.ClientResponse) -> Any:
        if resp.status >= 400:
            try:
                body = await resp.json()
            except Exception:  # noqa: BLE001
                raise errors.StatusError(f"HTTP {resp.status}") from None
            err = errors.StatusError.from_dict(body)
            err.retry_after = _parse_retry_after(resp.headers.get("Retry-After"))
            # A follower with no elected leader refuses BEFORE acting
            # (marked explicitly) — retryable for every verb, like 429.
            err.no_leader = resp.headers.get("X-Ktpu-No-Leader") == "1"
            # Bounded-staleness refusal of a follower read: retry the
            # leader (hinted when the follower knows it), never rotate.
            err.stale = resp.headers.get("X-Ktpu-Stale") == "1"
            err.leader_url = resp.headers.get("X-Ktpu-Leader", "")
            raise err
        from ..util import compactcodec
        if resp.content_type == compactcodec.CONTENT_TYPE:
            # Negotiated compact body (the server only answers compact
            # when this client asked via Accept): LIST envelopes,
            # BatchResult envelopes, and single created objects all
            # decode to the exact shape resp.json() yields on the
            # JSON path.
            body = await resp.read()
            compactcodec.count_request("compact", "response_decode",
                                       len(body))
            return compactcodec.decode_body(body)
        return await resp.json()

    def _read_endpoint(self) -> str:
        """The follower endpoint the next read routes to: round-robin
        over the ring EXCLUDING the pinned (write/leader) endpoint, so
        informer fan-out spreads across followers while the bind path
        keeps the leader to itself."""
        others = [ep for ep in self._endpoints if ep != self.base_url]
        if not others:
            return self.base_url
        self._read_rr = (self._read_rr + 1) % len(others)
        return others[self._read_rr]

    def _retry_endpoint(self, url: str, affinity_read: bool) -> str:
        """Where a failed request retries: an affinity READ advances to
        the NEXT follower and never touches ``base_url`` — a crashed
        or lagging follower must not rotate the write pin off a
        healthy leader (read failures don't charge the mutation-
        failover budget). Everything else rotates the ring as before."""
        if affinity_read and len(self._endpoints) > 1:
            return self._rebase(url, self._read_endpoint())
        return self._switch_endpoint(url)

    def _switch_endpoint(self, url: str) -> str:
        """Re-pin to the next endpoint in the failover ring and rebase
        ``url`` onto it; a single-endpoint client is a no-op."""
        if len(self._endpoints) <= 1:
            return url
        old = self.base_url
        try:
            i = self._endpoints.index(old)
        except ValueError:
            i = -1  # pinned to a redirect-learned origin: rejoin the ring
        self.base_url = self._endpoints[(i + 1) % len(self._endpoints)]
        return self._rebase(url, self.base_url)

    @staticmethod
    def _rebase(url: str, base: str) -> str:
        from urllib.parse import urlsplit, urlunsplit
        parts = urlsplit(url)
        origin = urlsplit(base)
        return urlunsplit((origin.scheme, origin.netloc, parts.path,
                           parts.query, ""))

    def _follow_redirect(self, url: str, location: str) -> str:
        """Absolute Location re-pins the client to the leader's origin;
        a relative one keeps the current origin."""
        from urllib.parse import urlsplit
        s = urlsplit(location)
        if s.scheme and s.netloc:
            self.base_url = f"{s.scheme}://{s.netloc}"
            return location
        return self._rebase(location, self.base_url)

    async def _chaos_fault(self) -> None:
        """The ``rest`` chaos injection site — consulted once per
        request ATTEMPT so retries face faults too. Injected failures
        are raised as the exact exception types the real transport
        produces, so they exercise the same handler paths."""
        c = chaos.CONTROLLER
        if c is None:
            return
        fault = c.decide(chaos.SITE_REST)
        if fault is None:
            return
        if fault.kind == "slow":
            await asyncio.sleep(fault.param or 0.01)
        elif fault.kind == "error":
            raise aiohttp.ClientConnectionError("chaos: injected connection reset")
        elif fault.kind == "hang":
            # A hung response consumes (a stand-in for) the deadline,
            # then surfaces the way aiohttp's timeout does.
            await asyncio.sleep(fault.param or 0.05)
            raise asyncio.TimeoutError("chaos: injected hung response")
        elif fault.kind == "http500":
            raise errors.StatusError("chaos: injected 500")

    async def _request(self, method: str, url: str,
                       idempotent: Optional[bool] = None,
                       timeout: Optional[float] = None,
                       retry_429: bool = True, **kw) -> Any:
        """One JSON request with deadlines, chaos, and retries.

        ``idempotent`` defaults by verb: GET retries transport errors
        and 5xx; mutating verbs do NOT (a replayed PUT/DELETE after a
        lost response flips a success into Conflict/NotFound — the
        caller owns that trade, and may opt in explicitly for calls
        with no side effects, e.g. access reviews). 429 retries for
        every verb — the server refused before acting — waiting out
        its Retry-After when present, the capped backoff otherwise.
        """
        if idempotent is None:
            idempotent = method == "GET"
        kw = self._identity_kw(kw)
        ct = aiohttp.ClientTimeout(
            total=self.total_timeout if timeout is None else timeout,
            connect=self.connect_timeout)
        affinity_read = method == "GET" and self.read_affinity
        if affinity_read:
            # Follower read offload: route to a follower endpoint with
            # the staleness bound attached; writes stay leader-routed.
            url = self._rebase(url, self._read_endpoint())
            headers = dict(kw.pop("headers", None) or {})
            headers.setdefault("X-Ktpu-Max-Staleness",
                               f"{self.max_staleness:.3f}")
            kw["headers"] = headers
            CLIENT_FOLLOWER_READS.inc(outcome="routed")
        if tracing.armed():
            # ktrace context propagation: requests issued inside a
            # sampled trace carry the W3C-style traceparent header so
            # the apiserver's server span joins the same trace.
            # Disarmed (the default), the whole seam is this one check.
            ctx = tracing.current()
            if ctx is not None and ctx.sampled:
                headers = dict(kw.pop("headers", None) or {})
                headers.setdefault(tracing.TRACEPARENT_HEADER,
                                   tracing.encode(ctx))
                kw["headers"] = headers
        backoff = self.backoff_base
        attempt = 0
        redirects = 0
        stale_used = False
        while True:
            delay = None
            try:
                await self._chaos_fault()
                # allow_redirects=False: 307 leader hints are handled
                # HERE — aiohttp's auto-follow would neither re-pin the
                # client to the leader nor back off a redirect loop.
                async with self._sess().request(method, url, timeout=ct,
                                                allow_redirects=False,
                                                **kw) as resp:
                    if resp.status in (307, 308):
                        location = resp.headers.get("Location", "")
                        redirects += 1
                        CLIENT_REDIRECTS.inc(verb=method)
                        if not location or redirects > self.max_redirects:
                            raise errors.ServiceUnavailableError(
                                f"leader redirect loop at {self.base_url} "
                                f"({redirects} hops)")
                        url = self._follow_redirect(url, location)
                        if redirects > 1:
                            # Stale hints chasing each other (the old
                            # leader not yet aware it lost): backoff-able
                            # condition, never a hot loop.
                            delay = backoff * (0.5 + random.random())
                            backoff = min(backoff * 2, self.backoff_cap)
                            CLIENT_BACKOFF.observe(delay)
                            await asyncio.sleep(delay)
                        continue
                    return await self._check(resp)
            except errors.StatusError as e:
                if e.code == 503 and getattr(e, "stale", False) \
                        and not stale_used:
                    # Bounded-staleness refusal: the follower is ALIVE
                    # but behind. Retry the leader exactly once —
                    # immediately, with no attempt charged and no
                    # endpoint rotation (rotating would walk the ring
                    # of equally stale followers forever while the
                    # leader sat reachable the whole time). A second
                    # stale 503 falls through to the normal retry
                    # budget below.
                    stale_used = True
                    leader = getattr(e, "leader_url", "") or self.base_url
                    url = self._rebase(url, leader)
                    CLIENT_FOLLOWER_READS.inc(outcome="stale_fallback")
                    CLIENT_RETRIES.inc(verb=method, reason="stale-follower")
                    continue
                if e.code == 429 and retry_429:
                    reason = "429"
                    delay = getattr(e, "retry_after", None)
                elif e.code == 503 and getattr(e, "no_leader", False):
                    # The follower refused BEFORE acting: safe to wait
                    # out the election and retry for EVERY verb; rotate
                    # in case this endpoint stays leaderless.
                    reason = "no-leader"
                    delay = getattr(e, "retry_after", None)
                    url = self._retry_endpoint(url, affinity_read)
                elif idempotent and e.code in _RETRYABLE_STATUS:
                    reason = f"http{e.code}"
                    # A 503 shedding load names its own retry clock
                    # too — honor it over our (much shorter) backoff.
                    delay = getattr(e, "retry_after", None)
                    url = self._retry_endpoint(url, affinity_read)
                else:
                    raise
                if attempt >= self.max_retries:
                    raise
            except (aiohttp.ClientError, ConnectionResetError,
                    asyncio.TimeoutError) as e:
                # A connect-phase failure means the request never
                # reached a server — replay-safe for every verb, and
                # the signature of a crashed endpoint: fail over.
                connect_failure = isinstance(e, aiohttp.ClientConnectorError)
                if not (idempotent or connect_failure) \
                        or attempt >= self.max_retries:
                    # Surface transport failures in the client's ONE
                    # error taxonomy (LocalClient parity): every caller
                    # already handling StatusError — scheduler requeue
                    # paths, controller backoff — now survives a
                    # dropped connection the same way it survives a
                    # 503, instead of dying on an aiohttp type it never
                    # imported.
                    from urllib.parse import urlsplit
                    target = urlsplit(url)
                    raise errors.ServiceUnavailableError(
                        f"transport to {target.scheme}://{target.netloc}:"
                        f" {e}") from e
                reason = type(e).__name__
                url = self._retry_endpoint(url, affinity_read)
            attempt += 1
            # Full jitter on the capped exponential (reference:
            # client-go flowcontrol.Backoff) — synchronized retry
            # storms from N controllers are the failure mode.
            if delay is None:
                delay = backoff * (0.5 + random.random())
                backoff = min(backoff * 2, self.backoff_cap)
            CLIENT_RETRIES.inc(verb=method, reason=reason)
            CLIENT_BACKOFF.observe(delay)
            await asyncio.sleep(delay)

    async def create(self, obj: Any) -> Any:
        try:
            gvk = DEFAULT_SCHEME.gvk_for(obj)
        except KeyError:
            # Generic CustomResource instance: TypeMeta carries the GVK.
            if not (obj.api_version and obj.kind):
                raise
            gvk = (obj.api_version, obj.kind)
        plural = await self._plural_for_kind(gvk[1])
        url = self._url_for(gvk[0], plural, obj.metadata.namespace)
        data = await self._request("POST", url,
                                   **self._write_body_kw(to_dict(obj)))
        return decode_obj(data)

    async def _plural_for_kind(self, kind: str) -> str:
        try:
            return _BY_KIND[kind]
        except KeyError:
            pass
        await self._refresh_discovery()  # no-op within the TTL window
        try:
            return self._dynamic_kinds[kind]
        except KeyError:
            raise errors.NotFoundError(f"unknown kind {kind!r}") from None

    async def get(self, plural: str, namespace: str, name: str) -> Any:
        av, namespaced = await self._plural_info(plural)
        url = self._url_for(av, plural, namespace if namespaced else "", name)
        data = await self._request("GET", url)
        return decode_obj(data)

    @staticmethod
    def _list_headers() -> Optional[dict]:
        """Accept header offering the compact codec when the gate is on
        (the server still answers JSON unless ITS gate is on too —
        negotiation, not assumption); None keeps the request bytes
        identical to the ungated client."""
        from ..util import compactcodec
        return compactcodec.accept_header()

    @staticmethod
    def _write_body_kw(payload: dict) -> dict:
        """Request kwargs for ONE write body (create, binding):
        framed msgpack with Content-Type/Accept negotiation when the
        CompactWireCodec gate is on in this process, byte-identical
        ``json=`` otherwise. A gate-off server from the write-path PR
        onward answers the compact form with a diagnosable 415; a
        PRE-codec server (no Content-Type negotiation at all) answers
        400 "invalid JSON body" — either way a refusal, never a
        guess."""
        from ..util import compactcodec
        headers = compactcodec.write_headers()
        if headers is None:
            return {"json": payload}
        return {"data": compactcodec.encode_obj_body(payload),
                "headers": headers}

    @staticmethod
    def _batch_body_kw(items: list) -> dict:
        """The multi-item twin of :meth:`_write_body_kw` for the
        ``:batchCreate`` / ``bindings:batch`` bodies."""
        from ..util import compactcodec
        headers = compactcodec.write_headers()
        if headers is None:
            return {"json": {"items": items}}
        return {"data": compactcodec.encode_batch_body(
                    [compactcodec.encode_obj(i) for i in items]),
                "headers": headers}

    async def list(self, plural: str, namespace: str = "", label_selector: str = "",
                   field_selector: str = "", chunk_size: int = 0) -> tuple[list, int]:
        """Full list. ``chunk_size`` > 0 fetches in pages under the
        hood (meta.v1 limit/continue — bounds each response's size at
        30k-object scale) but still returns the complete result."""
        av, namespaced = await self._plural_info(plural)
        url = self._url_for(av, plural, namespace if namespaced else "")
        params = {}
        if label_selector:
            params["label_selector"] = label_selector
        if field_selector:
            params["field_selector"] = field_selector
        if chunk_size:
            params["limit"] = str(chunk_size)
        headers = self._list_headers()
        items: list = []
        while True:
            data = await self._request("GET", url, params=params,
                                       **({"headers": headers}
                                          if headers else {}))
            items.extend(decode_obj(i) for i in data["items"])
            cont = data["metadata"].get("continue", "")
            if not cont:
                return items, int(data["metadata"]["resource_version"])
            params["continue"] = cont

    async def list_page(self, plural: str, namespace: str = "",
                        label_selector: str = "", field_selector: str = "",
                        limit: int = 0, continue_token: str = ""
                        ) -> tuple[list, int, str]:
        """One page + the continue token ('' on the last page)."""
        av, namespaced = await self._plural_info(plural)
        url = self._url_for(av, plural, namespace if namespaced else "")
        params = {"limit": str(limit)} if limit else {}
        if label_selector:
            params["label_selector"] = label_selector
        if field_selector:
            params["field_selector"] = field_selector
        if continue_token:
            params["continue"] = continue_token
        data = await self._request("GET", url, params=params)
        return ([decode_obj(i) for i in data["items"]],
                int(data["metadata"]["resource_version"]),
                data["metadata"].get("continue", ""))

    async def update(self, obj: Any, subresource: str = "") -> Any:
        gvk = DEFAULT_SCHEME.gvk_for(obj)
        plural = await self._plural_for_kind(gvk[1])
        url = self._url_for(gvk[0], plural, obj.metadata.namespace,
                            obj.metadata.name, subresource)
        data = await self._request("PUT", url, json=to_dict(obj))
        return decode_obj(data)

    async def patch(self, plural: str, namespace: str, name: str, patch,
                    subresource: str = "", strategic: bool = False) -> Any:
        """A dict patch is a JSON merge patch (or strategic merge with
        ``strategic=True``); a LIST patch is RFC 6902 JSON Patch and
        sets its content type automatically."""
        av, namespaced = await self._plural_info(plural)
        url = self._url_for(av, plural, namespace if namespaced else "", name, subresource)
        if isinstance(patch, list):
            from ..api.patch import JSON_PATCH
            kwargs = {"data": json.dumps(patch).encode(),
                      "headers": {"Content-Type": JSON_PATCH}}
        elif strategic:
            from ..api.patch import STRATEGIC_MERGE_PATCH
            kwargs = {"data": json.dumps(patch).encode(),
                      "headers": {"Content-Type": STRATEGIC_MERGE_PATCH}}
        else:
            kwargs = {"json": patch}
        data = await self._request("PATCH", url, **kwargs)
        return decode_obj(data)

    async def delete(self, plural: str, namespace: str, name: str,
                     grace_period_seconds: Optional[int] = None, uid: str = "",
                     propagation_policy: str = "") -> Any:
        av, namespaced = await self._plural_info(plural)
        url = self._url_for(av, plural, namespace if namespaced else "", name)
        params = {}
        if grace_period_seconds is not None:
            params["grace_period_seconds"] = str(grace_period_seconds)
        if uid:
            params["uid"] = uid
        if propagation_policy:
            params["propagation_policy"] = propagation_policy
        data = await self._request("DELETE", url, params=params)
        return decode_obj(data)

    async def watch(self, plural: str, namespace: str = "", resource_version: int = 0,
                    label_selector: str = "", field_selector: str = "") -> WatchStream:
        av, namespaced = await self._plural_info(plural)
        url = self._url_for(av, plural, namespace if namespaced else "")
        params = {"watch": "1", "resource_version": str(resource_version)}
        if label_selector:
            params["label_selector"] = label_selector
        if field_selector:
            params["field_selector"] = field_selector
        timeout = aiohttp.ClientTimeout(
            total=None, connect=self.connect_timeout,
            sock_read=self.watch_idle_timeout)
        headers = self._list_headers()  # compact-codec offer (gated)
        if self.read_affinity:
            # Watches ride followers too (follower stores are fully
            # watchable since PR 8); a stale/ended stream surfaces as
            # CLOSED and the informer relists — through the read
            # path's leader fallback when followers cannot serve.
            url = self._rebase(url, self._read_endpoint())
            headers = dict(headers or {})
            headers["X-Ktpu-Max-Staleness"] = f"{self.max_staleness:.3f}"
            CLIENT_FOLLOWER_READS.inc(outcome="watch_routed")
        if self._shared_session is not None and self._headers:
            headers = dict(headers or {})
            for k, v in self._headers.items():
                headers.setdefault(k, v)
        return _RESTWatch(self._sess(), url, params, timeout=timeout,
                          headers=headers).start()

    async def bind(self, namespace: str, name: str, binding: Binding,
                   decode: bool = True) -> Any:
        """``decode=False`` skips typing the response pod — the
        scheduler fires thousands of binds per second and reads the
        result only through its informer; decoding every response was
        measurable loop time at density scale. Rides the shared
        keep-alive session (_sess): sequential binds reuse ONE pooled
        connection, bounded by ``conn_limit_per_host`` under fan-out."""
        url = self._url_for("core/v1", "pods", namespace, name, "binding")
        data = await self._request("POST", url,
                                   **self._write_body_kw(to_dict(binding)))
        return decode_obj(data) if decode else None

    async def bind_many(self, namespace: str, bindings: list) -> list:
        """One ``pods/bindings:batch`` round trip for N binds; returns
        the positional per-item outcome list (None, or a StatusError
        instance for that item). A 16-pod gang is one request instead
        of 16 — the REST/local throughput gap was mostly this fan-out.
        Transport errors (and non-batch-aware servers) raise for the
        whole call; callers fall back per the interface contract.

        Singletons take the batch endpoint too: its response is a tiny
        per-item status, where the plain binding subresource echoes the
        whole bound pod — encode+parse work a high-rate caller always
        discards (it reads results through its informer)."""
        url = self._url_for("core/v1", "pods", namespace, "bindings:batch")
        items = [{"name": name, **to_dict(binding)}
                 for name, binding in bindings]
        data = await self._request("POST", url,
                                   **self._batch_body_kw(items))
        out: list = []
        for item in data.get("items", []):
            err = item.get("error")
            out.append(errors.StatusError.from_dict(err) if err else None)
        # Positional contract: a short server answer must not silently
        # mark trailing items bound.
        while len(out) < len(bindings):
            out.append(errors.StatusError("batch response truncated"))
        return out

    async def create_many(self, objs: list, decode: bool = True) -> list:
        """One ``{plural}:batchCreate`` round trip per kind; returns
        positional per-item outcomes (created object, or StatusError).
        Mixed lists are grouped into one request per (kind, namespace)
        — the URL namespace overrides item namespaces server-side, so
        grouping must never mix them. ``decode=False`` asks the server
        not to echo created objects (``?echo=0``) and reports plain
        None per success — bulk submitters skip N encodes + N parses
        per batch."""
        results: list = [None] * len(objs)
        groups: dict[tuple, list[int]] = {}
        for i, obj in enumerate(objs):
            try:
                gvk = DEFAULT_SCHEME.gvk_for(obj)
            except KeyError:
                if not (obj.api_version and obj.kind):
                    raise
                gvk = (obj.api_version, obj.kind)
            groups.setdefault(gvk + (obj.metadata.namespace,), []).append(i)
        for (gv, kind, ns), idxs in groups.items():
            plural = await self._plural_for_kind(kind)
            url = self._url_for(gv, f"{plural}:batchCreate", ns)
            if not decode:
                url += "?echo=0"
            data = await self._request(
                "POST", url,
                **self._batch_body_kw([to_dict(objs[i]) for i in idxs]))
            items = data.get("items", [])
            for pos, i in enumerate(idxs):
                if pos >= len(items):
                    results[i] = errors.StatusError("batch response truncated")
                elif items[pos].get("error"):
                    results[i] = errors.StatusError.from_dict(
                        items[pos]["error"])
                elif decode:
                    results[i] = decode_obj(items[pos]["object"])
        return results

    async def create_many_encoded(self, plural: str, namespace: str,
                                  item_payloads: list,
                                  api_version: str = "core/v1") -> list:
        """One ``{plural}:batchCreate`` round trip from PRE-ENCODED
        compact item payloads (``compactcodec.BodyTemplate`` renders) —
        the bulk submitter's zero-encode path: no ``to_dict`` walk, no
        per-object pack, no echoed objects (``?echo=0``). Requires the
        CompactWireCodec gate in this process; returns positional
        per-item outcomes (None, or StatusError) like
        :meth:`create_many`."""
        from ..util import compactcodec
        headers = compactcodec.write_headers()
        if headers is None:
            raise RuntimeError(
                "create_many_encoded needs the CompactWireCodec gate "
                "(and the msgpack wheel) in this process")
        url = (self._url_for(api_version, f"{plural}:batchCreate",
                             namespace) + "?echo=0")
        data = await self._request(
            "POST", url, data=compactcodec.encode_batch_body(item_payloads),
            headers=headers)
        out: list = []
        for item in data.get("items", []):
            err = item.get("error")
            out.append(errors.StatusError.from_dict(err) if err else None)
        # Positional contract, as in bind_many: a short answer must not
        # silently mark trailing items created.
        while len(out) < len(item_payloads):
            out.append(errors.StatusError("batch response truncated"))
        return out

    async def evict(self, namespace: str, name: str, eviction: Any) -> Any:
        url = self._url_for("core/v1", "pods", namespace, name, "eviction")
        # retry_429=False: the eviction subresource answers 429 when a
        # PodDisruptionBudget refuses — an APPLICATION verdict the
        # caller's policy handles (nodelifecycle's escalation clock),
        # not a transport condition to wait out here.
        return await self._request("POST", url, retry_429=False,
                                   json=to_dict(eviction))

    async def close(self) -> None:
        if self._session and not self._session.closed:
            await self._session.close()
