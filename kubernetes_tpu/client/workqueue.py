"""Rate-limited work queues — the controller backpressure primitive.

Reference: ``staging/src/k8s.io/client-go/util/workqueue`` (+
``apimachinery/pkg/util/workqueue`` consumer types): dedup while
queued, in-flight tracking with re-add coalescing, per-item exponential
backoff (5ms base, 1000s cap — the reference's DefaultControllerRateLimiter),
and delayed adds for requeue-after patterns.
"""
from __future__ import annotations

import asyncio
import heapq
from typing import Any, Hashable, Optional

from ..util.tasks import spawn


class WorkQueue:
    """FIFO with dedup + processing semantics, asyncio-native."""

    def __init__(self) -> None:
        self._queue: list[Hashable] = []
        self._dirty: set[Hashable] = set()
        self._processing: set[Hashable] = set()
        self._cond = asyncio.Condition()
        self._shutdown = False

    async def add(self, item: Hashable) -> None:
        async with self._cond:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return  # re-add while in flight: picked up on done()
            self._queue.append(item)
            self._cond.notify()

    def add_nowait(self, item: Hashable) -> None:
        """Enqueue from a sync context already on the event loop (informer
        handlers are invoked on-loop, so this is safe and lock-free)."""
        if self._shutdown or item in self._dirty:
            return
        self._dirty.add(item)
        if item in self._processing:
            return
        self._queue.append(item)
        spawn(self._notify(), name="workqueue-notify")

    async def _notify(self) -> None:
        async with self._cond:
            self._cond.notify()

    async def get(self) -> Optional[Hashable]:
        """Next item, or None after shutdown."""
        async with self._cond:
            while not self._queue and not self._shutdown:
                await self._cond.wait()
            if not self._queue:
                return None
            item = self._queue.pop(0)
            self._dirty.discard(item)
            self._processing.add(item)
            return item

    async def done(self, item: Hashable) -> None:
        async with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    async def shut_down(self) -> None:
        async with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self) -> int:
        return len(self._queue)


class RateLimitingQueue(WorkQueue):
    """WorkQueue + per-item exponential backoff + delayed adds."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        super().__init__()
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: dict[Hashable, int] = {}
        self._delayed: list[tuple[float, int, Hashable]] = []
        self._seq = 0
        self._delay_task: Optional[asyncio.Task] = None

    def num_requeues(self, item: Hashable) -> int:
        return self._failures.get(item, 0)

    def forget(self, item: Hashable) -> None:
        self._failures.pop(item, None)

    async def add_rate_limited(self, item: Hashable) -> None:
        n = self._failures.get(item, 0)
        self._failures[item] = n + 1
        delay = min(self.base_delay * (2 ** n), self.max_delay)
        await self.add_after(item, delay)

    async def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            await self.add(item)
            return
        loop = asyncio.get_running_loop()
        self._seq += 1
        heapq.heappush(self._delayed, (loop.time() + delay, self._seq, item))
        if self._delay_task is None or self._delay_task.done():
            self._delay_task = loop.create_task(self._drain_delayed())

    async def _drain_delayed(self) -> None:
        loop = asyncio.get_running_loop()
        while self._delayed and not self._shutdown:
            when, _, item = self._delayed[0]
            now = loop.time()
            if when > now:
                await asyncio.sleep(when - now)
                continue
            heapq.heappop(self._delayed)
            await self.add(item)

    async def shut_down(self) -> None:
        await super().shut_down()
        if self._delay_task and not self._delay_task.done():
            self._delay_task.cancel()
