"""Resolving a node's agent server (the kubelet :10250 analog).

ONE implementation of the DaemonEndpoints protocol — scheme from
``agent_tls``, address candidates (published address, then loopback),
credentials policy — shared by every consumer (``ktl logs/exec/top``,
the HPA metrics scraper). A TLS node with no cluster credentials is
REFUSED, never scraped over an unverified channel: fabricated metrics
or logs from a man-in-the-middle are worse than none.
"""
from __future__ import annotations

import logging
from typing import Any, Optional

from ..api import errors

log = logging.getLogger("nodeaccess")


def ssl_kw(ssl_ctx) -> dict:
    """aiohttp request kwargs for an optional TLS context."""
    return {"ssl": ssl_ctx} if ssl_ctx is not None else {}


async def resolve_node_agent(client, node_name: str, node: Any = None
                             ) -> Optional[tuple[str, Any]]:
    """(base URL, ssl context or None) for the node's agent server, or
    None when unreachable/unresolvable. ``client`` supplies both the
    Node object and (for TLS nodes) its own credentials
    (``client.ssl_context``). Candidates are PROBED (/healthz) so the
    loopback fallback actually engages when the published address is
    unreachable — a cheap GET that every consumer needs anyway.
    Callers that already hold the Node object (a sweep that just
    LISTed the fleet) pass it via ``node`` to skip the per-node GET."""
    if node is None:
        try:
            node = await client.get("nodes", "", node_name)
        except errors.StatusError:
            return None
    port = node.status.daemon_endpoints.get("agent")
    if not port:
        return None
    tls = bool(node.status.daemon_endpoints.get("agent_tls"))
    ssl_ctx = getattr(client, "ssl_context", None) if tls else None
    if tls and ssl_ctx is None:
        log.warning("node %s requires TLS but no cluster CA/client "
                    "credentials are configured; refusing to connect "
                    "unverified", node_name)
        return None
    scheme = "https" if tls else "http"
    addr = (node.status.addresses[0].address
            if node.status.addresses else "")
    import aiohttp
    for host in (addr, "127.0.0.1"):
        if not host:
            continue
        base = f"{scheme}://{host}:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(f"{base}/healthz",
                                 timeout=aiohttp.ClientTimeout(total=2),
                                 **ssl_kw(ssl_ctx)) as r:
                    if r.status == 200:
                        return base, ssl_ctx
        except Exception as e:  # noqa: BLE001 — unresolvable hostname etc.
            log.debug("node base %s not reachable, trying next: %s", base, e)
            continue
    return None
