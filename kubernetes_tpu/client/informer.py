"""Shared informers — the watch-cache every controller reads from.

Reference: client-go ``tools/cache``: ``Reflector.ListAndWatch``
(``reflector.go:239``), DeltaFIFO, shared informer + thread-safe store
with indexers. The contract reproduced here:

- LIST at revision R, then WATCH from R — no missed events, no gap;
- on watch failure or a 410 Gone (compaction), relist and *diff* the
  new state against the cache, synthesizing ADDED/MODIFIED/DELETED so
  handlers never observe a discontinuity (``replace`` semantics);
- handlers are notified after the cache is updated, so a handler
  reading the lister sees at-least-as-new state;
- optional periodic resync re-delivers the whole cache as updates
  (level-triggered controllers depend on this to self-heal).
"""
from __future__ import annotations

import asyncio
import logging
import random
from typing import Any, Callable, Optional

from .. import tracing
from ..analysis import loopsan
from ..api import errors
from ..metrics.registry import Counter, Gauge
from .interface import Client
from .mutation_detector import CacheMutationDetector

log = logging.getLogger("informer")

INFORMER_RELISTS = Counter(
    "informer_relists_total",
    "Full LIST+replace cycles (startup, reconnect without a usable "
    "bookmark, or 410 Gone after compaction)", labels=("plural",))
INFORMER_BOOKMARK_RESUMES = Counter(
    "informer_bookmark_resumes_total",
    "Reconnects resumed from the last bookmark revision, skipping the "
    "relist (WatchBookmarks gate)", labels=("plural",))
INFORMER_STORE_ENTRIES = Gauge(
    "informer_store_entries", "Objects held by informer caches",
    labels=("store",))
INFORMER_STORE_EVICTIONS = Counter(
    "informer_store_evictions_total",
    "Objects FIFO-evicted by an informer cache's opt-in max_entries "
    "ceiling", labels=("store",))

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"
CLOSED = "CLOSED"


def _key(obj: Any) -> str:
    return obj.key()


class Indexer:
    """Thread-unsafe (single-loop) keyed store with secondary indexes."""

    def __init__(self, indexers: Optional[dict[str, Callable[[Any], list[str]]]] = None,
                 name: str = "indexer", max_entries: int = 0):
        """``max_entries``: opt-in FIFO ceiling (0 = unbounded, the
        default). Control-loop informers MUST stay unbounded — evicting
        a live object would corrupt the controller's world view; the
        ceiling is for telemetry-class caches (event streams, ad-hoc
        watchers) whose keyspace grows with history, not with live
        cluster size."""
        self._name = name
        self.max_entries = max_entries
        self._items: dict[str, Any] = {}
        self._indexers = dict(indexers or {})
        self._indexes: dict[str, dict[str, set[str]]] = {n: {} for n in self._indexers}
        #: Env-gated (TPU_CACHE_MUTATION_DETECTOR): snapshots objects at
        #: upsert and asserts digest stability when they are read back.
        self.mutation_detector = CacheMutationDetector(name)

    def add_indexer(self, name: str, fn: Callable[[Any], list[str]]) -> None:
        """Register a new index, back-filling it over existing items (lets
        late controllers add indexes to a shared, already-running informer)."""
        if name in self._indexers:
            return
        self._indexers[name] = fn
        idx: dict[str, set[str]] = {}
        for key, obj in self._items.items():
            for v in fn(obj):
                idx.setdefault(v, set()).add(key)
        self._indexes[name] = idx

    def _update_index(self, key: str, old: Any, new: Any) -> None:
        for name, fn in self._indexers.items():
            idx = self._indexes[name]
            if old is not None:
                for v in fn(old):
                    bucket = idx.get(v)
                    if bucket:
                        bucket.discard(key)
                        if not bucket:
                            del idx[v]
            if new is not None:
                for v in fn(new):
                    idx.setdefault(v, set()).add(key)

    def upsert(self, obj: Any) -> Optional[Any]:
        key = _key(obj)
        old = self._items.get(key)
        self._items[key] = obj
        self._update_index(key, old, obj)
        if self.mutation_detector.enabled:
            self.mutation_detector.capture(key, obj)
        if self.max_entries and len(self._items) > self.max_entries:
            oldest = next(iter(self._items))
            if oldest != key:
                self.remove(oldest)
                INFORMER_STORE_EVICTIONS.inc(store=self._name)
        INFORMER_STORE_ENTRIES.set(float(len(self._items)), store=self._name)
        return old

    def remove(self, obj_or_key) -> Optional[Any]:
        key = obj_or_key if isinstance(obj_or_key, str) else _key(obj_or_key)
        old = self._items.pop(key, None)
        if old is not None:
            self._update_index(key, old, None)
            self.mutation_detector.forget(key)
        INFORMER_STORE_ENTRIES.set(float(len(self._items)), store=self._name)
        return old

    def get(self, key: str) -> Optional[Any]:
        obj = self._items.get(key)
        if self.mutation_detector.enabled and obj is not None:
            self.mutation_detector.verify(key, obj)
        return obj

    def list(self) -> list[Any]:
        if self.mutation_detector.enabled:
            self.mutation_detector.verify_all(self._items)
        return list(self._items.values())

    def keys(self) -> list[str]:
        return list(self._items.keys())

    def by_index(self, index_name: str, value: str) -> list[Any]:
        keys = self._indexes.get(index_name, {}).get(value, ())
        if self.mutation_detector.enabled:
            for k in keys:
                self.mutation_detector.verify(k, self._items[k])
        return [self._items[k] for k in keys]

    def __len__(self) -> int:
        return len(self._items)


class SharedInformer:
    def __init__(self, client: Client, plural: str, namespace: str = "",
                 label_selector: str = "", field_selector: str = "",
                 resync_period: float = 0.0,
                 indexers: Optional[dict[str, Callable[[Any], list[str]]]] = None,
                 max_entries: int = 0):
        self.client = client
        self.plural = plural
        self.namespace = namespace
        self.label_selector = label_selector
        self.field_selector = field_selector
        self.resync_period = resync_period
        self.store = Indexer(indexers, name=f"informer({plural})",
                             max_entries=max_entries)
        self._handlers: list[tuple[Callable, Callable, Callable]] = []
        self._synced = asyncio.Event()
        self._stopped = False
        #: Whether the current ListAndWatch cycle's LIST succeeded —
        #: the reflector's backoff resets only on that signal.
        self._list_ok = False
        self._task: Optional[asyncio.Task] = None
        self.last_sync_resource_version = 0

    # -- wiring -----------------------------------------------------------

    def add_handlers(self, on_add: Optional[Callable] = None,
                     on_update: Optional[Callable] = None,
                     on_delete: Optional[Callable] = None) -> None:
        noop = lambda *a: None  # noqa: E731
        self._handlers.append((on_add or noop, on_update or noop, on_delete or noop))

    @property
    def has_synced(self) -> bool:
        return self._synced.is_set()

    async def wait_for_sync(self, timeout: float = 30.0) -> None:
        await asyncio.wait_for(self._synced.wait(), timeout)

    def start(self) -> "SharedInformer":
        self._task = asyncio.get_running_loop().create_task(self.run())
        return self

    async def stop(self) -> None:
        self._stopped = True
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass

    # -- reflector --------------------------------------------------------

    async def run(self) -> None:
        backoff = 0.05
        while not self._stopped:
            self._list_ok = False
            try:
                await self._list_and_watch()
                backoff = 0.05
            except asyncio.CancelledError:
                raise
            except errors.GoneError:
                log.info("informer(%s): watch revision compacted; relisting", self.plural)
                continue
            except Exception as e:  # noqa: BLE001
                log.warning("informer(%s): ListAndWatch failed: %s", self.plural, e)
                # Reset the backoff only after a SUCCESSFUL list: a
                # long-lived watch dying is routine (reconnect fast),
                # but a crash-looping apiserver that fails every LIST
                # must see the full exponential climb, not a 50ms
                # hammer forever.
                if self._list_ok:
                    backoff = 0.05
                await asyncio.sleep(backoff + random.random() * backoff)
                backoff = min(backoff * 2, 5.0)

    async def _list_and_watch(self) -> None:
        from ..util.features import GATES
        if GATES.enabled("WatchBookmarks") and self._synced.is_set() \
                and self.last_sync_resource_version:
            # Bookmark resume: the cache is already populated and the
            # server has been advancing our resume point via BOOKMARK
            # frames — reconnect the watch from it instead of paying a
            # full LIST + decode + replace. A 410 (the store compacted
            # past our bookmark) falls through to the relist below —
            # the one answer to Gone.
            try:
                await self._watch_from(self.last_sync_resource_version,
                                       resumed=True)
                return
            except errors.GoneError:
                log.info("informer(%s): bookmark revision %d compacted; "
                         "relisting", self.plural,
                         self.last_sync_resource_version)
        items, rev = await self.client.list(
            self.plural, self.namespace, self.label_selector, self.field_selector)
        self._list_ok = True
        INFORMER_RELISTS.inc(plural=self.plural)
        self._replace(items)
        self.last_sync_resource_version = rev
        self._synced.set()
        await self._watch_from(rev, resumed=False)

    async def _watch_from(self, rev: int, resumed: bool) -> None:
        watch = await self.client.watch(
            self.plural, self.namespace, rev, self.label_selector, self.field_selector)
        if resumed:
            INFORMER_BOOKMARK_RESUMES.inc(plural=self.plural)
        resync_deadline = (asyncio.get_running_loop().time() + self.resync_period
                           if self.resync_period else None)
        try:
            while not self._stopped:
                timeout = 1.0
                ev = await watch.next(timeout=timeout)
                if resync_deadline and asyncio.get_running_loop().time() >= resync_deadline:
                    self._resync()
                    resync_deadline = asyncio.get_running_loop().time() + self.resync_period
                if ev is None:
                    continue
                # Anything the stream delivers proves the connection is
                # live — on a bookmark resume (no LIST happened) this is
                # the signal that resets run()'s backoff.
                self._list_ok = True
                etype, obj = ev
                if etype == CLOSED:
                    # Stream ended (server restart / connection drop):
                    # surface to run() so it relists and reconnects.
                    raise ConnectionResetError(
                        f"watch stream for {self.plural} closed")
                if etype == BOOKMARK:
                    rv = obj.get("metadata", {}).get("resource_version") if isinstance(obj, dict) else None
                    if rv:
                        self.last_sync_resource_version = int(rv)
                    continue
                self._apply(etype, obj)
        finally:
            watch.cancel()

    def _replace(self, items: list) -> None:
        """Replace cache contents, synthesizing deltas for handlers."""
        new_keys = {_key(o) for o in items}
        for key in self.store.keys():
            if key not in new_keys:
                old = self.store.remove(key)
                if old is not None:
                    self._notify(DELETED, old, None)
        for obj in items:
            old = self.store.upsert(obj)
            if old is None:
                self._notify(ADDED, None, obj)
            elif old.metadata.resource_version != obj.metadata.resource_version:
                self._notify(MODIFIED, old, obj)

    def _apply(self, etype: str, obj: Any) -> None:
        if etype == DELETED:
            old = self.store.remove(obj)
            self._notify(DELETED, old or obj, None)
            return
        old = self.store.upsert(obj)
        try:
            self.last_sync_resource_version = int(obj.metadata.resource_version)
        except (TypeError, ValueError):
            pass
        if etype == ADDED and old is None:
            self._notify(ADDED, None, obj)
        else:
            self._notify(MODIFIED, old, obj)

    def _resync(self) -> None:
        for obj in self.store.list():
            self._notify(MODIFIED, obj, obj)

    def _notify(self, etype: str, old: Any, new: Any) -> None:
        with loopsan.seam("informer.notify"):
            self._notify_inner(etype, old, new)

    def _notify_inner(self, etype: str, old: Any, new: Any) -> None:
        # ktrace re-attach: the delivered object's durable traceparent
        # annotation becomes the current context around its handlers,
        # so whatever they do (queue adds, status writes, container
        # starts) joins the pod's trace. Disarmed cost: one bool check
        # per event; armed-but-unsampled: one annotation get.
        token = None
        if tracing.armed():
            ctx = tracing.context_of(new if new is not None else old)
            if ctx is not None:
                token = tracing.attach(ctx)
        try:
            for on_add, on_update, on_delete in self._handlers:
                try:
                    if etype == ADDED:
                        on_add(new)
                    elif etype == MODIFIED:
                        on_update(old, new)
                    else:
                        on_delete(old)
                except Exception:  # noqa: BLE001
                    log.exception("informer(%s): handler error", self.plural)
        finally:
            if token is not None:
                tracing.detach(token)

    # -- lister -----------------------------------------------------------

    def get(self, key: str) -> Optional[Any]:
        return self.store.get(key)

    def list(self) -> list[Any]:
        return self.store.list()


class InformerFactory:
    """One informer per resource shared by all controllers (reference:
    SharedInformerFactory in controller-manager wiring)."""

    def __init__(self, client: Client, namespace: str = ""):
        self.client = client
        self.namespace = namespace
        self._informers: dict[str, SharedInformer] = {}

    def informer(self, plural: str,
                 indexers: Optional[dict[str, Callable]] = None,
                 resync_period: float = 0.0) -> SharedInformer:
        inf = self._informers.get(plural)
        if inf is None:
            inf = SharedInformer(self.client, plural, self.namespace,
                                 resync_period=resync_period, indexers=indexers)
            self._informers[plural] = inf
        elif indexers:
            # Late registrations merge into the shared informer's store
            # (back-filled), rather than being silently dropped.
            for name, fn in indexers.items():
                inf.store.add_indexer(name, fn)
        return inf

    def start_all(self) -> None:
        for inf in self._informers.values():
            if inf._task is None:
                inf.start()

    async def wait_for_sync(self, timeout: float = 30.0) -> None:
        for inf in self._informers.values():
            await inf.wait_for_sync(timeout)

    async def stop_all(self) -> None:
        for inf in self._informers.values():
            await inf.stop()


#: Common indexer: pods by spec.node_name (scheduler + node controllers).
def pods_by_node(pod) -> list[str]:
    return [pod.spec.node_name] if pod.spec.node_name else []
