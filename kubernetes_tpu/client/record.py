"""Event recorder — the 'kubectl describe' breadcrumb trail.

Reference: ``staging/src/k8s.io/client-go/tools/record`` (e.g.
FailedScheduling events posted at ``plugin/pkg/scheduler/scheduler.go:433``).
Repeated identical events are aggregated by bumping ``count`` instead of
flooding the store.
"""
from __future__ import annotations

import asyncio
import hashlib
import logging
from typing import Any

from .. import tracing
from ..api import errors
from ..api.meta import ObjectMeta, now
from ..api.scheme import DEFAULT_SCHEME
from ..api.types import Event, EventSource, ObjectReference
from ..metrics.registry import Counter, Gauge
from ..util.tasks import spawn
from .interface import Client

log = logging.getLogger("events")

RECORDER_SEEN_ENTRIES = Gauge(
    "event_recorder_seen_entries",
    "Keys in the event recorder's dedup (correlation) map")
RECORDER_SEEN_EVICTIONS = Counter(
    "event_recorder_seen_evictions_total",
    "Dedup keys FIFO-pruned by the recorder's seen_limit ceiling")


class EventRecorder:
    def __init__(self, client: Client, component: str, host: str = "",
                 qps: float = 50.0, burst: int = 100,
                 batch_limit: int = 128, seen_limit: int = 4096):
        """``seen_limit``: ceiling on the dedup map (the memory bound
        that keeps a week of event churn from growing this process —
        a pruned key just pays one extra round trip on its next
        occurrence)."""
        self.client = client
        self.source = EventSource(component=component, host=host)
        #: First-occurrence events SPOOL and flush as one
        #: ``events:batchCreate`` request, completion-clocked like the
        #: scheduler's bind coalescer: an isolated event dispatches on
        #: the next loop tick (zero added latency), and everything
        #: arriving during that request's round trip rides the next
        #: batch. At density scale the per-pod Scheduled events were
        #: one HTTP request EACH — telemetry request count rivaled the
        #: bind path's on the shared apiserver loop.
        self.batch_limit = batch_limit
        self._spool: list[Event] = []
        self._flush_task = None
        # Client-side correlation (reference: EventCorrelator LRU):
        # remembers which event names this process already created so
        # first-occurrence events cost ONE create (the common case —
        # e.g. per-pod Scheduled at density scale) and repeats go
        # straight to update without a probing GET.
        self._seen: dict[str, None] = {}
        self._seen_limit = seen_limit
        # Normal-event rate limit (reference: kubelet --event-qps /
        # --event-burst + client-go's sink rate limiter). At 30k-pod
        # density the per-pod Scheduled events alone were a third of
        # all apiserver requests — telemetry must not compete with the
        # control path. Warnings always pass (they carry diagnosis).
        self._qps = qps
        self._burst = float(burst)
        self._tokens = float(burst)
        self._last_refill = 0.0
        self.dropped = 0

    def _allow(self, event_type: str) -> bool:
        if event_type != "Normal" or self._qps <= 0:
            return True
        import time
        now_m = time.monotonic()
        if self._last_refill:
            self._tokens = min(
                self._burst,
                self._tokens + (now_m - self._last_refill) * self._qps)
        self._last_refill = now_m
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        self.dropped += 1
        # Dropping silently would make "where did my events go" a
        # mystery: log the first drop and every 1000th after (the
        # reference's client-go logs each dropped event — at the rates
        # this limiter exists for, that would itself be spam).
        if self.dropped == 1 or self.dropped % 1000 == 0:
            log.info("event rate limit: dropped %d Normal events from "
                     "%s (qps=%g burst=%g)", self.dropped,
                     self.source.component, self._qps, self._burst)
        return False

    def _ref(self, obj: Any) -> ObjectReference:
        try:
            av, kind = DEFAULT_SCHEME.gvk_for(obj)
        except KeyError:
            av, kind = obj.api_version, obj.kind
        return ObjectReference(api_version=av, kind=kind,
                               namespace=obj.metadata.namespace,
                               name=obj.metadata.name, uid=obj.metadata.uid)

    def event(self, obj: Any, event_type: str, reason: str, message: str) -> None:
        """Fire-and-forget (never let event failures break controllers)."""
        if not self._allow(event_type):
            return
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return
        try:
            ref = self._ref(obj)
            # Stable name per (object, reason, message) for aggregation.
            sig = hashlib.sha1(
                f"{ref.uid}/{reason}/{message}".encode()).hexdigest()[:10]
            name = f"{ref.name}.{sig}"
            ns = ref.namespace or "default"
            key = f"{ns}/{name}"
        except Exception as e:  # noqa: BLE001
            log.debug("event build failed: %s", e)
            return
        ev = Event(
            metadata=ObjectMeta(name=name, namespace=ns),
            involved_object=ref, reason=reason, message=message,
            type=event_type, count=1, source=self.source,
            first_timestamp=now(), last_timestamp=now())
        if tracing.armed():
            # ktrace breadcrumb: the originating trace id rides the
            # event (annotation), so ``ktl trace pod`` interleaves the
            # pod's Events with its spans. The batched spool path
            # carries the annotation unchanged — a flushed batch item
            # is this exact object.
            ctx = tracing.current() or tracing.context_of(obj)
            if ctx is not None and ctx.sampled:
                ev.metadata.annotations[tracing.TRACE_ID_ANNOTATION] = \
                    ctx.trace_id
        if key in self._seen:
            spawn(self._bump_seen(ev, key), name="event-bump")
            return
        self._enqueue(ev, key)

    def _enqueue(self, ev: Event, key: str) -> None:
        self._spool.append(ev)
        self._note_seen(key)
        if self._flush_task is None or self._flush_task.done():
            self._flush_task = spawn(self._flush_soon(), name="event-flush")

    async def _flush_soon(self) -> None:
        """Drain the spool as ``events:batchCreate`` requests —
        completion-clocked: each request's round trip is the batching
        window for the events that arrive during it. LocalClient and
        test doubles fall back to the interface's looping
        ``create_many`` default — same semantics, no batching."""
        try:
            await asyncio.sleep(0)  # coalesce same-tick bursts
            while self._spool:
                batch, self._spool = (self._spool[:self.batch_limit],
                                      self._spool[self.batch_limit:])
                try:
                    outcomes = await self.client.create_many(
                        batch, decode=False)
                except Exception as e:  # noqa: BLE001 — whole batch lost
                    log.debug("event flush failed: %s", e)
                    continue
                for ev, res in zip(batch, outcomes):
                    if isinstance(res, errors.AlreadyExistsError):
                        # Another component got there first: aggregate.
                        ns = ev.metadata.namespace
                        await self._bump_seen(
                            ev, f"{ns}/{ev.metadata.name}")
                    elif isinstance(res, Exception):
                        log.debug("event create failed: %s", res)
        except Exception as e:  # noqa: BLE001 — telemetry must not crash
            log.debug("event flush task failed: %s", e)

    def _note_seen(self, key: str) -> None:
        if len(self._seen) >= self._seen_limit:
            # FIFO prune (dict preserves insertion order) — a miss
            # just pays one extra round trip.
            stale_keys = list(self._seen)[: self._seen_limit // 2]
            for stale in stale_keys:
                del self._seen[stale]
            RECORDER_SEEN_EVICTIONS.inc(float(len(stale_keys)))
        self._seen[key] = None
        RECORDER_SEEN_ENTRIES.set(float(len(self._seen)))

    async def _bump_seen(self, ev: Event, key: str) -> None:
        """count++ on an event this process already created; a
        server-side prune (NotFound) RECREATES it through the spool —
        the triggering occurrence must not be silently dropped."""
        ns, name = ev.metadata.namespace, ev.metadata.name
        try:
            try:
                cur = await self.client.get("events", ns, name)
            except errors.NotFoundError:
                self._seen.pop(key, None)
                self._enqueue(ev, key)
                return
            cur.count += 1
            cur.last_timestamp = now()
            await self.client.update(cur)
        except Exception as e:  # noqa: BLE001
            log.debug("event bump failed: %s", e)
