"""CRI gRPC server + remote-runtime client.

Same plumbing style as deviceplugin/service.py: grpc_tools is absent,
so handlers are registered through grpc's generic handler API with
protoc-generated messages; method paths follow /package.Service/Method,
interoperable with foreign gRPC stacks (a containerd shim could serve
this socket).

Threading: grpc.server runs handlers on its own thread pool while the
runtime lives on the agent's asyncio loop — handlers bridge with
``asyncio.run_coroutine_threadsafe``; the client is blocking and the
agent-side RemoteRuntime wraps calls in ``asyncio.to_thread``.
"""
from __future__ import annotations

import asyncio
import logging
import os
from concurrent import futures
from typing import Optional

import grpc

from ..node.runtime import (ContainerConfig, ContainerRuntime,
                            ContainerStatus, SandboxStatus)
from . import cri_pb2 as pb

log = logging.getLogger("cri")

SERVICE = "cri.v1.RuntimeService"
IMAGE_SERVICE = "cri.v1.ImageService"
RUNTIME_VERSION = "0.1"


def _to_pb_status(st: ContainerStatus) -> pb.ContainerStatus:
    return pb.ContainerStatus(
        id=st.id, name=st.name, pod_uid=st.pod_uid, state=st.state,
        exit_code=st.exit_code, started_at=st.started_at or 0.0,
        finished_at=st.finished_at or 0.0, message=st.message,
        pid=st.pid or 0)


def _from_pb_status(m: pb.ContainerStatus) -> ContainerStatus:
    return ContainerStatus(
        id=m.id, name=m.name, pod_uid=m.pod_uid, state=m.state,
        exit_code=m.exit_code, started_at=m.started_at,
        finished_at=m.finished_at, message=m.message, pid=m.pid)


class CRIServer:
    """Serves a ContainerRuntime over a unix socket. The runtime's
    coroutines execute on ``loop`` (the loop that owns the runtime)."""

    def __init__(self, runtime: ContainerRuntime,
                 loop: Optional[asyncio.AbstractEventLoop] = None):
        self.runtime = runtime
        self.loop = loop
        self._server: Optional[grpc.Server] = None
        self.socket_path = ""

    def _call(self, coro, timeout: float = 120.0):
        future = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return future.result(timeout=timeout)

    # -- handlers (run on grpc's thread pool) -----------------------------

    def Version(self, request, context):
        return pb.VersionResponse(
            runtime_name=type(self.runtime).__name__,
            runtime_version=RUNTIME_VERSION,
            root_dir=getattr(self.runtime, "root_dir", ""))

    def CreateContainer(self, request, context):
        c = request.config
        # HasField: an absent linux block must not read as uid 0.
        lin = c.linux if c.HasField("linux") else pb.LinuxSecurity(
            run_as_user=-1, run_as_group=-1)
        config = ContainerConfig(
            pod_namespace=c.pod_namespace, pod_name=c.pod_name,
            pod_uid=c.pod_uid, name=c.name, image=c.image,
            sandbox_id=c.sandbox_id,
            command=list(c.command), args=list(c.args),
            env={e.key: e.value for e in c.envs},
            working_dir=c.working_dir,
            mounts=[(m.host_path, m.container_path, m.readonly)
                    for m in c.mounts],
            devices=list(c.devices),
            run_as_user=None if lin.run_as_user < 0 else lin.run_as_user,
            run_as_group=None if lin.run_as_group < 0 else lin.run_as_group,
            rlimits=[(r.resource, r.soft, r.hard) for r in lin.rlimits],
            oom_score_adj=int(lin.oom_score_adj))
        try:
            cid = self._call(self.runtime.start_container(config))
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        return pb.CreateContainerResponse(container_id=cid)

    def StopContainer(self, request, context):
        self._call(self.runtime.stop_container(
            request.container_id, grace_seconds=request.grace_seconds or 1.0))
        return pb.Empty()

    def RemoveContainer(self, request, context):
        self._call(self.runtime.remove_container(request.container_id))
        return pb.Empty()

    def ListContainers(self, request, context):
        statuses = self._call(self.runtime.list_containers())
        return pb.ListContainersResponse(
            containers=[_to_pb_status(st) for st in statuses])

    def ExecSync(self, request, context):
        exec_timeout = request.timeout or 30.0
        try:
            code, output = self._call(
                self.runtime.exec_in_container(
                    request.container_id, list(request.command),
                    timeout=exec_timeout),
                # The bridge deadline must outlast the exec's own
                # timeout or long execs abort mid-flight server-side.
                timeout=exec_timeout + 30.0)
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except NotImplementedError:
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "runtime does not support exec")
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        return pb.ExecSyncResponse(exit_code=code, output=output)

    def ContainerLogs(self, request, context):
        try:
            content = self._call(self.runtime.container_logs(
                request.container_id,
                tail=request.tail if request.tail else None))
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return pb.ContainerLogsResponse(content=content)

    # -- sandbox handlers --------------------------------------------------

    def RunPodSandbox(self, request, context):
        try:
            sid = self._call(self.runtime.run_pod_sandbox(
                request.pod_namespace, request.pod_name, request.pod_uid))
        except NotImplementedError:
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "runtime has no sandbox support")
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        return pb.RunPodSandboxResponse(sandbox_id=sid)

    def StopPodSandbox(self, request, context):
        try:
            self._call(self.runtime.stop_pod_sandbox(request.sandbox_id))
        except NotImplementedError:
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "runtime has no sandbox support")
        return pb.Empty()

    def RemovePodSandbox(self, request, context):
        try:
            self._call(self.runtime.remove_pod_sandbox(request.sandbox_id))
        except NotImplementedError:
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "runtime has no sandbox support")
        return pb.Empty()

    def ListPodSandboxes(self, request, context):
        try:
            sbs = self._call(self.runtime.list_pod_sandboxes())
        except NotImplementedError:
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "runtime has no sandbox support")
        return pb.ListPodSandboxesResponse(sandboxes=[
            pb.SandboxStatus(id=s.id, pod_namespace=s.pod_namespace,
                             pod_name=s.pod_name, pod_uid=s.pod_uid,
                             state=s.state, created_at=s.created_at)
            for s in sbs])

    # -- image handlers ----------------------------------------------------

    def PullImage(self, request, context):
        try:
            digest = self._call(self.runtime.pull_image(request.ref),
                                timeout=300.0)
        except NotImplementedError:
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "runtime has no image support")
        except FileNotFoundError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except ValueError as e:  # digest mismatch
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INTERNAL, str(e))
        return pb.PullImageResponse(digest=digest)

    @staticmethod
    def _to_pb_image(i) -> pb.Image:
        return pb.Image(ref=i.ref, digest=i.digest,
                        size_bytes=i.size_bytes, path=i.path,
                        last_used_at=i.last_used_at, builtin=i.builtin)

    def ImageStatus(self, request, context):
        try:
            info = self._call(self.runtime.image_status(request.ref))
        except NotImplementedError:
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "runtime has no image support")
        if info is None:
            return pb.ImageStatusResponse(present=False)
        return pb.ImageStatusResponse(present=True,
                                      image=self._to_pb_image(info))

    def RemoveImage(self, request, context):
        try:
            self._call(self.runtime.remove_image(request.ref))
        except NotImplementedError:
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "runtime has no image support")
        return pb.Empty()

    def ListImages(self, request, context):
        try:
            infos = self._call(self.runtime.list_images())
        except NotImplementedError:
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "runtime has no image support")
        return pb.ListImagesResponse(
            images=[self._to_pb_image(i) for i in infos])

    # -- lifecycle ---------------------------------------------------------

    def serve(self, socket_path: str) -> None:
        """Start serving (call from the loop that owns the runtime)."""
        if self.loop is None:
            self.loop = asyncio.get_running_loop()
        os.makedirs(os.path.dirname(socket_path), exist_ok=True)
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        handlers = {
            "Version": grpc.unary_unary_rpc_method_handler(
                self.Version, request_deserializer=pb.VersionRequest.FromString,
                response_serializer=pb.VersionResponse.SerializeToString),
            "CreateContainer": grpc.unary_unary_rpc_method_handler(
                self.CreateContainer,
                request_deserializer=pb.CreateContainerRequest.FromString,
                response_serializer=pb.CreateContainerResponse.SerializeToString),
            "StopContainer": grpc.unary_unary_rpc_method_handler(
                self.StopContainer,
                request_deserializer=pb.StopContainerRequest.FromString,
                response_serializer=pb.Empty.SerializeToString),
            "RemoveContainer": grpc.unary_unary_rpc_method_handler(
                self.RemoveContainer,
                request_deserializer=pb.RemoveContainerRequest.FromString,
                response_serializer=pb.Empty.SerializeToString),
            "ListContainers": grpc.unary_unary_rpc_method_handler(
                self.ListContainers,
                request_deserializer=pb.ListContainersRequest.FromString,
                response_serializer=pb.ListContainersResponse.SerializeToString),
            "ExecSync": grpc.unary_unary_rpc_method_handler(
                self.ExecSync,
                request_deserializer=pb.ExecSyncRequest.FromString,
                response_serializer=pb.ExecSyncResponse.SerializeToString),
            "ContainerLogs": grpc.unary_unary_rpc_method_handler(
                self.ContainerLogs,
                request_deserializer=pb.ContainerLogsRequest.FromString,
                response_serializer=pb.ContainerLogsResponse.SerializeToString),
            "RunPodSandbox": grpc.unary_unary_rpc_method_handler(
                self.RunPodSandbox,
                request_deserializer=pb.RunPodSandboxRequest.FromString,
                response_serializer=pb.RunPodSandboxResponse.SerializeToString),
            "StopPodSandbox": grpc.unary_unary_rpc_method_handler(
                self.StopPodSandbox,
                request_deserializer=pb.PodSandboxIdRequest.FromString,
                response_serializer=pb.Empty.SerializeToString),
            "RemovePodSandbox": grpc.unary_unary_rpc_method_handler(
                self.RemovePodSandbox,
                request_deserializer=pb.PodSandboxIdRequest.FromString,
                response_serializer=pb.Empty.SerializeToString),
            "ListPodSandboxes": grpc.unary_unary_rpc_method_handler(
                self.ListPodSandboxes,
                request_deserializer=pb.ListPodSandboxesRequest.FromString,
                response_serializer=pb.ListPodSandboxesResponse.SerializeToString),
        }
        image_handlers = {
            "PullImage": grpc.unary_unary_rpc_method_handler(
                self.PullImage,
                request_deserializer=pb.PullImageRequest.FromString,
                response_serializer=pb.PullImageResponse.SerializeToString),
            "ImageStatus": grpc.unary_unary_rpc_method_handler(
                self.ImageStatus,
                request_deserializer=pb.ImageRefRequest.FromString,
                response_serializer=pb.ImageStatusResponse.SerializeToString),
            "RemoveImage": grpc.unary_unary_rpc_method_handler(
                self.RemoveImage,
                request_deserializer=pb.ImageRefRequest.FromString,
                response_serializer=pb.Empty.SerializeToString),
            "ListImages": grpc.unary_unary_rpc_method_handler(
                self.ListImages,
                request_deserializer=pb.ListImagesRequest.FromString,
                response_serializer=pb.ListImagesResponse.SerializeToString),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),
             grpc.method_handlers_generic_handler(IMAGE_SERVICE,
                                                  image_handlers)))
        self._server.add_insecure_port(f"unix://{socket_path}")
        self._server.start()
        self.socket_path = socket_path
        log.info("CRI server on unix://%s", socket_path)

    def stop(self) -> None:
        if self._server:
            self._server.stop(grace=1.0)
            self._server = None


class RemoteRuntime(ContainerRuntime):
    """ContainerRuntime over the CRI socket — the agent plugs this in
    exactly like an in-proc runtime (remote_runtime.go analog)."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._channel = grpc.insecure_channel(f"unix://{socket_path}")
        self.root_dir: str = ""
        p = f"/{SERVICE}/"

        def u(method, req_cls, resp_cls):
            return self._channel.unary_unary(
                p + method, request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString)
        self._version = u("Version", pb.VersionRequest, pb.VersionResponse)
        self._create = u("CreateContainer", pb.CreateContainerRequest,
                         pb.CreateContainerResponse)
        self._stop = u("StopContainer", pb.StopContainerRequest, pb.Empty)
        self._remove = u("RemoveContainer", pb.RemoveContainerRequest,
                         pb.Empty)
        self._list = u("ListContainers", pb.ListContainersRequest,
                       pb.ListContainersResponse)
        self._logs = u("ContainerLogs", pb.ContainerLogsRequest,
                       pb.ContainerLogsResponse)
        self._exec = u("ExecSync", pb.ExecSyncRequest, pb.ExecSyncResponse)
        self._run_sandbox = u("RunPodSandbox", pb.RunPodSandboxRequest,
                              pb.RunPodSandboxResponse)
        self._stop_sandbox = u("StopPodSandbox", pb.PodSandboxIdRequest,
                               pb.Empty)
        self._remove_sandbox = u("RemovePodSandbox", pb.PodSandboxIdRequest,
                                 pb.Empty)
        self._list_sandboxes = u("ListPodSandboxes",
                                 pb.ListPodSandboxesRequest,
                                 pb.ListPodSandboxesResponse)
        pi = f"/{IMAGE_SERVICE}/"

        def iu(method, req_cls, resp_cls):
            return self._channel.unary_unary(
                pi + method, request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString)
        self._pull = iu("PullImage", pb.PullImageRequest,
                        pb.PullImageResponse)
        self._image_status = iu("ImageStatus", pb.ImageRefRequest,
                                pb.ImageStatusResponse)
        self._remove_image = iu("RemoveImage", pb.ImageRefRequest, pb.Empty)
        self._list_images = iu("ListImages", pb.ListImagesRequest,
                               pb.ListImagesResponse)
        try:
            self.version()  # learn the runtime's state root (if served)
        except grpc.RpcError:
            pass  # server not up yet; callers may retry version() later

    def version(self) -> tuple[str, str]:
        resp = self._version(pb.VersionRequest(version=RUNTIME_VERSION),
                             timeout=10)
        # Same-host runtimes advertise their state root so the agent's
        # stats collector can read workload-published metrics files.
        if resp.root_dir:
            self.root_dir = resp.root_dir
        return resp.runtime_name, resp.runtime_version

    async def start_container(self, config: ContainerConfig) -> str:
        req = pb.CreateContainerRequest(config=pb.ContainerConfig(
            pod_namespace=config.pod_namespace, pod_name=config.pod_name,
            pod_uid=config.pod_uid, name=config.name, image=config.image,
            sandbox_id=config.sandbox_id,
            command=list(config.command), args=list(config.args),
            envs=[pb.KeyValue(key=k, value=v) for k, v in config.env.items()],
            working_dir=config.working_dir,
            mounts=[pb.Mount(host_path=h, container_path=c, readonly=ro)
                    for h, c, ro in config.mounts],
            devices=list(config.devices),
            linux=pb.LinuxSecurity(
                run_as_user=(-1 if config.run_as_user is None
                             else config.run_as_user),
                run_as_group=(-1 if config.run_as_group is None
                              else config.run_as_group),
                rlimits=[pb.Rlimit(resource=r, soft=int(s), hard=int(h))
                         for r, s, h in config.rlimits],
                oom_score_adj=config.oom_score_adj)))
        resp = await asyncio.to_thread(self._create, req, timeout=120)
        return resp.container_id

    async def stop_container(self, container_id: str,
                             grace_seconds: float = 30.0) -> None:
        await asyncio.to_thread(
            self._stop, pb.StopContainerRequest(
                container_id=container_id, grace_seconds=grace_seconds),
            timeout=max(30.0, grace_seconds + 10))

    async def remove_container(self, container_id: str) -> None:
        await asyncio.to_thread(
            self._remove, pb.RemoveContainerRequest(container_id=container_id),
            timeout=60)

    async def list_containers(self) -> list[ContainerStatus]:
        resp = await asyncio.to_thread(
            self._list, pb.ListContainersRequest(), timeout=30)
        return [_from_pb_status(m) for m in resp.containers]

    async def container_logs(self, container_id: str,
                             tail: Optional[int] = None) -> str:
        resp = await asyncio.to_thread(
            self._logs, pb.ContainerLogsRequest(
                container_id=container_id, tail=tail or 0), timeout=30)
        return resp.content

    async def exec_in_container(self, container_id: str, argv: list[str],
                                timeout: float = 30.0) -> tuple[int, str]:
        try:
            resp = await asyncio.to_thread(
                self._exec, pb.ExecSyncRequest(
                    container_id=container_id, command=argv, timeout=timeout),
                timeout=timeout + 45)
        except grpc.RpcError as e:
            # Round-trip the seam contract: callers (the agent's /exec
            # route) map NotImplementedError->501 and KeyError->404,
            # same as the in-process runtime raises.
            if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                raise NotImplementedError(e.details()) from None
            if e.code() == grpc.StatusCode.NOT_FOUND:
                raise KeyError(e.details()) from None
            raise
        return resp.exit_code, resp.output

    @staticmethod
    def _unimpl(e: "grpc.RpcError"):
        """A server predating an RPC answers UNIMPLEMENTED — surface it
        as NotImplementedError so agent compat paths treat an old
        remote runtime exactly like an old in-proc one."""
        if e.code() == grpc.StatusCode.UNIMPLEMENTED:
            raise NotImplementedError(e.details()) from None
        raise e

    # -- sandbox -----------------------------------------------------------

    async def run_pod_sandbox(self, namespace: str, name: str,
                              uid: str) -> str:
        try:
            resp = await asyncio.to_thread(
                self._run_sandbox, pb.RunPodSandboxRequest(
                    pod_namespace=namespace, pod_name=name, pod_uid=uid),
                timeout=60)
        except grpc.RpcError as e:
            self._unimpl(e)
        return resp.sandbox_id

    async def stop_pod_sandbox(self, sandbox_id: str) -> None:
        try:
            await asyncio.to_thread(
                self._stop_sandbox,
                pb.PodSandboxIdRequest(sandbox_id=sandbox_id), timeout=60)
        except grpc.RpcError as e:
            self._unimpl(e)

    async def remove_pod_sandbox(self, sandbox_id: str) -> None:
        try:
            await asyncio.to_thread(
                self._remove_sandbox,
                pb.PodSandboxIdRequest(sandbox_id=sandbox_id), timeout=60)
        except grpc.RpcError as e:
            self._unimpl(e)

    async def list_pod_sandboxes(self) -> list[SandboxStatus]:
        try:
            resp = await asyncio.to_thread(
                self._list_sandboxes, pb.ListPodSandboxesRequest(),
                timeout=30)
        except grpc.RpcError as e:
            self._unimpl(e)
        return [SandboxStatus(id=s.id, pod_namespace=s.pod_namespace,
                              pod_name=s.pod_name, pod_uid=s.pod_uid,
                              state=s.state, created_at=s.created_at)
                for s in resp.sandboxes]

    # -- images ------------------------------------------------------------

    async def pull_image(self, ref: str) -> str:
        try:
            resp = await asyncio.to_thread(
                self._pull, pb.PullImageRequest(ref=ref), timeout=300)
        except grpc.RpcError as e:
            # Round-trip the store's exception contract over the seam.
            if e.code() == grpc.StatusCode.NOT_FOUND:
                raise FileNotFoundError(e.details()) from None
            if e.code() == grpc.StatusCode.FAILED_PRECONDITION:
                raise ValueError(e.details()) from None
            self._unimpl(e)
        return resp.digest

    async def image_status(self, ref: str):
        try:
            resp = await asyncio.to_thread(
                self._image_status, pb.ImageRefRequest(ref=ref), timeout=30)
        except grpc.RpcError as e:
            self._unimpl(e)
        if not resp.present:
            return None
        from ..node.images import ImageInfo
        i = resp.image
        return ImageInfo(ref=i.ref, digest=i.digest, size_bytes=i.size_bytes,
                         path=i.path, last_used_at=i.last_used_at,
                         builtin=i.builtin)

    async def remove_image(self, ref: str) -> None:
        try:
            await asyncio.to_thread(
                self._remove_image, pb.ImageRefRequest(ref=ref), timeout=60)
        except grpc.RpcError as e:
            self._unimpl(e)

    async def list_images(self) -> list:
        from ..node.images import ImageInfo
        try:
            resp = await asyncio.to_thread(
                self._list_images, pb.ListImagesRequest(), timeout=30)
        except grpc.RpcError as e:
            self._unimpl(e)
        return [ImageInfo(ref=i.ref, digest=i.digest,
                          size_bytes=i.size_bytes, path=i.path,
                          last_used_at=i.last_used_at, builtin=i.builtin)
                for i in resp.images]

    def close(self) -> None:
        self._channel.close()
