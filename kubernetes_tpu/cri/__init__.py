"""CRI — container-runtime gRPC seam (see api.proto).

``CRIServer`` exposes any in-proc :class:`~kubernetes_tpu.node.runtime.
ContainerRuntime` over a unix socket; ``RemoteRuntime`` is the node
agent's client side (``pkg/kubelet/remote/remote_runtime.go`` analog),
itself a ContainerRuntime — so the agent is transport-agnostic.
"""
from .service import CRIServer, RemoteRuntime

__all__ = ["CRIServer", "RemoteRuntime"]
