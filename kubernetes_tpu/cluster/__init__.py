from .local import LocalCluster, LocalNode  # noqa: F401
