"""Single-process cluster — the hyperkube / local-up-cluster analog.

Reference: ``cmd/hyperkube/`` (all components in one binary) and
``hack/local-up-cluster.sh`` (compose apiserver + controller-manager +
scheduler + kubelet on one machine). Here one asyncio process runs:

- MVCC store (optionally durable under ``data_dir``) + registry +
  HTTP apiserver;
- scheduler and controller-manager over the in-process client (same
  trick as hyperkube: co-located components skip the network);
- N node agents over the **REST** client (they are logically remote,
  so they exercise the real HTTP/watch path), each with a
  ProcessRuntime (pods are real OS processes) or FakeRuntime, a
  device manager, and a TPU device plugin (stub mesh, or the real
  hardware plugin probing via jax/libtpu).

This is what ``ktl up`` runs, what the real-TPU e2e drives, and the
node half is what kubemark-style hollow fleets reuse.
"""
from __future__ import annotations

import asyncio
import logging
import os
import tempfile
from dataclasses import dataclass, field
from typing import Optional

from ..api import errors, types as t
from ..api.meta import ObjectMeta
from ..apiserver.admission import default_chain
from ..apiserver.registry import Registry
from ..apiserver.server import APIServer
from ..client.local import LocalClient
from ..client.rest import RESTClient
from ..controllers.manager import ControllerManager
from ..deviceplugin.stub import StubTpuPlugin, make_topology
from ..net.proxy import ServiceProxy
from ..node.agent import NodeAgent
from ..node.devicemanager import DeviceManager
from ..node.eviction import EvictionManager, Thresholds
from ..node.runtime import FakeRuntime, ProcessRuntime
from ..scheduler.scheduler import Scheduler
from ..storage.mvcc import MVCCStore

log = logging.getLogger("cluster")


@dataclass
class LocalNode:
    """One node agent + its runtime + device plugin, inside the cluster
    process."""
    name: str
    agent: NodeAgent
    runtime: object
    client: RESTClient
    plugin: Optional[StubTpuPlugin] = None
    device_manager: Optional[DeviceManager] = None
    proxy: Optional[ServiceProxy] = None
    cri_server: Optional[object] = None

    async def stop(self) -> None:
        await self.agent.stop()
        if self.proxy is not None:
            await self.proxy.stop()
        if self.plugin is not None:
            self.plugin.stop()
        if self.cri_server is not None:
            from ..cri import RemoteRuntime
            if isinstance(self.runtime, RemoteRuntime):
                self.runtime.close()
            inner = self.cri_server.runtime
            self.cri_server.stop()
            if isinstance(inner, ProcessRuntime):
                await inner.shutdown()
        elif isinstance(self.runtime, ProcessRuntime):
            await self.runtime.shutdown()
        await self.client.close()


@dataclass
class NodeSpec:
    """How to build one node. ``tpu_chips > 0`` serves a stub plugin
    with that many chips; ``real_tpu`` probes the actual hardware."""
    name: str = ""
    tpu_chips: int = 0
    mesh_shape: Optional[tuple] = None
    real_tpu: bool = False
    #: Fail container starts when chips are assigned but no local TPU
    #: device nodes exist (real device-node deployments; tunneled
    #: TPU-VMs keep this off).
    strict_devices: bool = False
    fake_runtime: bool = False
    #: Interpose the CRI gRPC seam: the agent talks to its runtime over
    #: a unix-socket RemoteRuntime instead of in-proc calls.
    via_cri: bool = False
    capacity: dict = field(default_factory=dict)
    labels: dict = field(default_factory=dict)


class LocalCluster:
    def __init__(self, data_dir: Optional[str] = None,
                 nodes: Optional[list[NodeSpec]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 tokens: Optional[dict[str, str]] = None,
                 durable: bool = False,
                 status_interval: float = 10.0,
                 heartbeat_interval: float = 5.0,
                 monitor_interval: float = 10.0,
                 autoscale_interval: float = 2.0,
                 metrics_interval: float = 5.0,
                 migration_interval: float = 5.0,
                 authorization_mode: str = "AlwaysAllow",
                 user_groups: Optional[dict] = None,
                 audit_log: str = "",
                 audit_policy: str = "",
                 audit_webhook: str = "",
                 scheduler_policy: str = "",
                 encryption_provider_config: str = "",
                 tls: bool = True):
        """``tls=True`` (default): the apiserver serves HTTPS only from
        a cluster CA minted under ``<data_dir>/pki`` — plaintext
        connections are refused by the handshake itself; pass
        ``tls=False`` for the reference's insecure-port mode."""
        self.data_dir = data_dir or tempfile.mkdtemp(prefix="ktpu-cluster-")
        self.node_specs = nodes if nodes is not None else [NodeSpec(name="node-0")]
        self.host = host
        self._port = port
        self.tokens = tokens
        self.durable = durable
        self.status_interval = status_interval
        self.heartbeat_interval = heartbeat_interval
        #: Cluster-monitor sweep + inference-autoscaler cadence
        #: (serving smokes shorten these to act inside their budget).
        self.monitor_interval = monitor_interval
        self.autoscale_interval = autoscale_interval
        #: kmon scrape/rule cadence (mon_smoke shortens it); only read
        #: when the ClusterMetricsPipeline gate is on.
        self.metrics_interval = metrics_interval
        #: Migration-controller sweep cadence (migrate smokes shorten
        #: it); only acted on when the GangLiveMigration gate is on.
        self.migration_interval = migration_interval
        self.authorization_mode = authorization_mode
        self.user_groups = user_groups
        self.audit_log = audit_log
        self.audit_policy = audit_policy
        self.audit_webhook = audit_webhook
        #: Scheduler Policy file (scheduler/policy.py; reference
        #: kube-scheduler --policy-config-file).
        self.scheduler_policy = scheduler_policy
        #: EncryptionConfig file (storage/encryption.py; reference
        #: --experimental-encryption-provider-config).
        self.encryption_provider_config = encryption_provider_config
        self.tls = tls
        self.ca = None
        self.ca_file = ""
        self.admin_cert = None  # CertPair (CN=admin, O=system:masters)

        self.registry: Optional[Registry] = None
        self.server: Optional[APIServer] = None
        self.scheduler: Optional[Scheduler] = None
        self.controller_manager: Optional[ControllerManager] = None
        self.dns = None
        self.nodes: list[LocalNode] = []
        self.base_url = ""

    # -- composition -------------------------------------------------------

    async def start(self) -> str:
        from ..util.gctune import tune_control_plane_gc
        tune_control_plane_gc()
        transformers = None
        if self.encryption_provider_config:
            from ..storage.encryption import load_encryption_config
            transformers = load_encryption_config(
                self.encryption_provider_config)
        store = MVCCStore(os.path.join(self.data_dir, "state")
                          if self.durable else None,
                          transformers=transformers)
        self.registry = Registry(store=store)
        # Loopback pod-IP space: every 127/8 address is bindable and
        # routable on one host with zero configuration, so the pod IPs
        # the framework assigns (and cluster DNS serves) are REAL for
        # this single-host runtime — a rank-0 pod can listen on its pod
        # IP and peers can dial what DNS returns (the CNI-bridge role).
        # Multi-host joins route over the apiserver, not pod IPs.
        self.registry.cluster_cidr = "127.64.0.0/12"
        self.registry.admission = default_chain(self.registry)
        local = LocalClient(self.registry)
        for ns in ("default", "kube-system"):
            try:
                self.registry.create(t.Namespace(metadata=ObjectMeta(name=ns)))
            except errors.AlreadyExistsError:
                pass  # durable restart
        try:
            # Default StorageClass (what real clusters ship): classless
            # PVCs — e.g. StatefulSet volumeClaimTemplates — provision
            # host-path volumes out of the box via the DefaultStorage-
            # Class admission stamp + the PV binder's provisioner.
            self.registry.create(t.StorageClass(
                metadata=ObjectMeta(
                    name="standard",
                    annotations={
                        "storageclass.tpu/is-default-class": "true"}),
                provisioner=t.PROVISIONER_HOSTPATH,
                parameters={"base_dir": os.path.join(self.data_dir, "pv")}))
        except errors.AlreadyExistsError:
            pass

        from ..apiserver.audit import (AuditLogger, AuditPolicy,
                                       AuditWebhookBackend)
        from ..apiserver.authz import make_authorizer
        from ..util.features import GATES
        audit = self._audit = None
        if self.audit_policy and not (self.audit_log or self.audit_webhook):
            raise ValueError(
                "--audit-policy needs a backend: pass --audit-log "
                "and/or --audit-webhook (a policy with nowhere to "
                "write would silently audit nothing)")
        if GATES.enabled("AuditLogging") and (
                self.audit_log or self.audit_webhook):
            audit = self._audit = AuditLogger(
                path=self.audit_log,
                policy=(AuditPolicy.from_file(self.audit_policy)
                        if self.audit_policy else None),
                webhook=(AuditWebhookBackend(self.audit_webhook)
                         if self.audit_webhook else None))
            audit.start()
        self.server = APIServer(
            self.registry, tokens=self.tokens,
            authorizer=make_authorizer(self.authorization_mode, self.registry),
            user_groups=self.user_groups, audit=audit)
        ssl_ctx = None
        if self.tls:
            from ..apiserver.certs import (CertAuthority,
                                           server_ssl_context)
            from ..apiserver.certs import local_host_sans
            pki = os.path.join(self.data_dir, "pki")
            self.ca = CertAuthority(pki).ensure()
            # Clients verify hostnames against SANs (certs.py), so the
            # cert must cover every address this apiserver answers on —
            # including the routable ones multi-host joiners dial.
            pair = self.ca.issue_server_cert(
                "apiserver", local_host_sans([self.host]))
            self.admin_cert = self.ca.issue_client_cert(
                "admin", ["system:masters"], out_dir=pki)
            self.ca_file = self.ca.ca_cert_path
            self.server.cert_authority = self.ca
            ssl_ctx = server_ssl_context(pair, self.ca.ca_cert_path)
        port = await self.server.start(self.host, self._port,
                                       ssl_context=ssl_ctx)
        scheme = "https" if self.tls else "http"
        self.base_url = f"{scheme}://{self.host}:{port}"

        sched_policy = None
        if self.scheduler_policy:
            from ..scheduler.policy import load_policy
            sched_policy = load_policy(self.scheduler_policy)
        # kmon (ClusterMetricsPipeline, default off): the scheduler and
        # controller-manager expose /metrics listeners for the scrape
        # manager, and the apiserver's /debug/v1/query reads the
        # co-located pipeline. Gate off: no listeners, no provider —
        # byte-identical.
        kmon_on = GATES.enabled("ClusterMetricsPipeline")
        self.scheduler = Scheduler(local, policy=sched_policy,
                                   metrics_port=0 if kmon_on else None)
        await self.scheduler.start()
        scrape_ssl = None
        if self.ca is not None:
            # The HPA's real metrics pipeline scrapes TLS node servers
            # with the cluster admin identity (check_hostname off: node
            # serving certs are dialed by published address with a
            # loopback fallback; trust is the CA chain + client cert).
            from ..apiserver.certs import client_ssl_context
            scrape_ssl = client_ssl_context(
                self.ca.ca_cert_path, self.admin_cert.cert_path,
                self.admin_cert.key_path, check_hostname=False)
        component_urls = []
        if kmon_on and self.scheduler.metrics_listener is not None:
            component_urls.append(
                ("scheduler", self.scheduler.metrics_listener.url))
        self.controller_manager = ControllerManager(
            local, node_scrape_ssl=scrape_ssl,
            queueing_fits_probe=self._queueing_fits_probe,
            # Migration needs the LIVE scheduler cache (reservations +
            # slice geometry) — same single-binary wiring as backfill.
            migration_cache_probe=lambda: self.scheduler.cache,
            migration_interval=self.migration_interval,
            monitor_interval=self.monitor_interval,
            autoscale_interval=self.autoscale_interval,
            metrics_interval=self.metrics_interval,
            apiserver_urls=[self.base_url],
            component_urls=component_urls)
        await self.controller_manager.start()
        if kmon_on:
            cm = self.controller_manager
            self.server.metrics_pipeline_provider = \
                lambda: cm.get_controller("metrics-pipeline")

        # Cluster DNS (kube-dns addon analog): A records for services +
        # headless per-pod rank hostnames; agents inject
        # KTPU_DNS_SERVER into every pod env.
        from ..net.dns import ClusterDNS
        self.dns = ClusterDNS(local, host=self.host)
        await self.dns.start()
        # Joining nodes learn the DNS address with their credential, so
        # pods on joined hosts get KTPU_DNS_SERVER into every pod env.
        self.server.dns_address = self.dns.address

        # Kernel NAT dataplane (opt-in, root-only): renders + applies
        # the same iptables rulesets kube-proxy's iptables mode would.
        # The userspace proxy stays on either way — it carries traffic
        # wherever the kernel path can't.
        self.iptables_syncer = None
        self.ipvs_syncer = None
        self.netpolicy_syncer = None
        if GATES.enabled("NetworkPolicy"):
            from ..net.netpolicy import NetworkPolicySyncer
            self.netpolicy_syncer = NetworkPolicySyncer(local)
            await self.netpolicy_syncer.start()
        if GATES.enabled("IpvsProxier"):
            # IPVS mode wins when both gates are on (it subsumes the
            # iptables mode's job and the two fight over KUBE-SERVICES).
            from ..net.ipvs import IpvsSyncer
            # NodePort virtual servers need concrete node addresses
            # (IPVS has no --dst-type LOCAL analog; the reference binds
            # node IPs to kube-ipvs0). Every node of a local cluster is
            # this host.
            self.ipvs_syncer = IpvsSyncer(
                local, cluster_cidr=self.registry.cluster_cidr,
                node_ips=(self.host,))
            await self.ipvs_syncer.start()
        elif GATES.enabled("IptablesProxier"):
            from ..net.iptables import IptablesSyncer
            self.iptables_syncer = IptablesSyncer(
                local, cluster_cidr=self.registry.cluster_cidr)
            await self.iptables_syncer.start()

        for i, spec in enumerate(self.node_specs):
            self.nodes.append(await self._start_node(spec, i))

        # Fault injection (TPU_CHAOS, chaos/core.py): call-driven sites
        # arm themselves; the driver covers the time-driven one — stub
        # TPU chips going unhealthy on the seeded schedule. Real-TPU
        # plugins are excluded by the driver itself.
        from ..chaos import core as chaos_core
        from ..chaos.driver import ChaosDriver
        self.chaos_driver = None
        if chaos_core.CONTROLLER is not None:
            self.chaos_driver = ChaosDriver(
                [n.plugin for n in self.nodes if n.plugin is not None]).start()
        log.info("cluster up at %s with %d nodes", self.base_url, len(self.nodes))
        return self.base_url

    async def _start_node(self, spec: NodeSpec, index: int) -> LocalNode:
        name = spec.name or f"node-{index}"
        node_dir = os.path.join(self.data_dir, "nodes", name)
        token = next(iter(self.tokens), "") if self.tokens else ""
        client = RESTClient(self.base_url, token=token,
                            ca_file=self.ca_file)

        plugin: Optional[StubTpuPlugin] = None
        device_manager: Optional[DeviceManager] = None
        if spec.real_tpu or spec.tpu_chips:
            plugin_dir = os.path.join(node_dir, "device-plugins")
            if spec.real_tpu:
                from ..deviceplugin.tpu_plugin import TpuDevicePlugin
                plugin = TpuDevicePlugin(slice_id=f"slice-{name}")
            else:
                chips = spec.tpu_chips
                shape = spec.mesh_shape or (
                    (2, 2, chips // 4) if chips % 4 == 0 else (chips, 1, 1))
                plugin = StubTpuPlugin(make_topology(
                    mesh_shape=tuple(shape), slice_id=f"slice-{name}",
                    id_prefix=f"{name}-chip"))
            plugin.serve(os.path.join(plugin_dir, "tpu.sock"))
            device_manager = DeviceManager(plugin_dir, poll_interval=0.2)

        runtime = (FakeRuntime() if spec.fake_runtime
                   else ProcessRuntime(node_dir))
        cri_server = None
        if spec.via_cri:
            from ..cri import CRIServer, RemoteRuntime
            cri_server = CRIServer(runtime)
            cri_server.serve(os.path.join(node_dir, "cri.sock"))
            runtime = RemoteRuntime(cri_server.socket_path)
        # Per-node service proxy (kube-proxy analog) on the dataplane
        # nodes; fake-runtime (hollow) nodes skip it — no real sockets.
        from ..util.features import GATES
        proxy: Optional[ServiceProxy] = None
        eviction: Optional[EvictionManager] = None
        if not spec.fake_runtime and GATES.enabled("ServiceProxy"):
            proxy = ServiceProxy(client)
            await proxy.start()
        if not spec.fake_runtime and GATES.enabled("NodePressureEviction"):
            # Conservative thresholds: dev boxes legitimately run with
            # fuller disks than production nodes.
            eviction = EvictionManager(Thresholds(
                memory_available_bytes=50 * 2**20,
                fs_available_fraction=0.02))
        # Runtime hook injecting TPU device nodes + libtpu env.
        # Strictness (fail starts without device access) is opt-in via
        # NodeSpec.strict_devices: TPU-VMs reached through a tunnel
        # (this environment) legitimately have no local /dev/accel*.
        hook = None
        if spec.real_tpu or spec.tpu_chips:
            from ..node.runtimehook import TpuRuntimeHook
            hook = TpuRuntimeHook(
                allow_missing_devices=not spec.strict_devices)
        agent = NodeAgent(
            client, name, runtime, device_manager=device_manager,
            capacity=dict(spec.capacity) or None, labels=dict(spec.labels),
            status_interval=self.status_interval,
            heartbeat_interval=self.heartbeat_interval,
            proxy=proxy, eviction=eviction, runtime_hook=hook,
            # Stub plugins now carry the driver sim (duty cycle / HBM /
            # ICI counters), so every TPU node feeds the tpu_* gauges —
            # the DCGM-exporter analog — not just real hardware.
            chip_metrics=plugin.chip_metrics if plugin is not None else None,
            # Static pods (reference --pod-manifest-path): drop a Pod
            # YAML into <data>/nodes/<name>/manifests and the agent
            # runs it kubelet-owned, mirror posted for observability.
            pod_manifest_path=os.path.join(node_dir, "manifests"))
        if self.ca is not None:
            # Node serving cert (kubelet :10250 TLS): clients verify
            # the node's address against SANs; the handshake requires a
            # cluster client cert (exec = code execution on this host).
            from ..apiserver.certs import local_host_sans, server_ssl_context
            node_pki = os.path.join(node_dir, "pki")
            pair = self.ca.issue_server_cert(
                f"system:node:{name}", local_host_sans([self.host]),
                out_dir=node_pki)
            # CERT_OPTIONAL + TokenReview: cert clients authenticate at
            # the handshake, token clients per-request (the kubelet's
            # authenticator union). When the apiserver itself runs
            # authn-disabled (tokens=None, dev mode), the node server
            # admits anonymous the same way.
            agent.server_tls = server_ssl_context(
                pair, self.ca.ca_cert_path)
            agent.server_allow_anonymous = self.tokens is None
        if self.dns is not None:
            agent.dns_server = self.dns.address
        await agent.start()
        return LocalNode(name=name, agent=agent, runtime=runtime,
                         client=client, plugin=plugin,
                         device_manager=device_manager, proxy=proxy,
                         cri_server=cri_server)

    async def add_node(self, spec: NodeSpec) -> LocalNode:
        node = await self._start_node(spec, len(self.nodes))
        self.nodes.append(node)
        return node

    def _queueing_fits_probe(self, group) -> bool:
        """Backfill placement probe for the queue controller: does a
        free contiguous box of the gang's shape exist in the live
        scheduler cache right now? Single-binary only — a remote
        controller-manager falls back to quota-only backfill."""
        if self.scheduler is None or not group.spec.slice_shape:
            return True
        from ..scheduler.submesh import find_box
        cache = self.scheduler.cache
        for sl in cache.slices.values():
            if find_box(set(sl.free(cache)), sl.mesh_shape,
                        group.spec.slice_shape) is not None:
                return True
        return False

    async def stop(self) -> None:
        if getattr(self, "chaos_driver", None) is not None:
            await self.chaos_driver.stop()
            self.chaos_driver = None
        for node in self.nodes:
            try:
                await node.stop()
            except Exception:  # noqa: BLE001
                log.exception("node %s stop failed", node.name)
        self.nodes = []
        if getattr(self, "iptables_syncer", None) is not None:
            await self.iptables_syncer.stop()
        if getattr(self, "ipvs_syncer", None) is not None:
            await self.ipvs_syncer.stop()
        if getattr(self, "netpolicy_syncer", None) is not None:
            await self.netpolicy_syncer.stop()
        if self.dns is not None:
            await self.dns.stop()
        if self.controller_manager:
            await self.controller_manager.stop()
        if self.scheduler:
            await self.scheduler.stop()
        if self.server:
            await self.server.stop()
        if getattr(self, "_audit", None):
            await self._audit.aclose()
        if self.registry and self.durable:
            self.registry.store.snapshot()

    # -- conveniences ------------------------------------------------------

    def make_client(self, token: str = "") -> RESTClient:
        """A RESTClient wired for this cluster's transport: CA-trusting
        HTTPS + the admin identity cert under TLS (kubeadm admin.conf
        analog), plain HTTP otherwise."""
        if not self.tls:
            return RESTClient(self.base_url, token=token)
        return RESTClient(
            self.base_url, token=token, ca_file=self.ca_file,
            client_cert=self.admin_cert.cert_path if not token else "",
            client_key=self.admin_cert.key_path if not token else "")

    def local_client(self) -> LocalClient:
        assert self.registry is not None
        return LocalClient(self.registry)

    async def wait_for_nodes_ready(self, timeout: float = 30.0) -> None:
        """Block until every node object is Ready with its TPU capacity
        (if any) published."""
        client = self.local_client()
        deadline = asyncio.get_running_loop().time() + timeout
        want_tpu = {self.node_specs[i].name or f"node-{i}"
                    for i in range(len(self.node_specs))
                    if self.node_specs[i].real_tpu or self.node_specs[i].tpu_chips}
        while True:
            nodes, _ = await client.list("nodes")
            ready = {}
            for node in nodes:
                cond = t.get_node_condition(node.status, t.NODE_READY)
                ok = cond is not None and cond.status == "True"
                if ok and node.metadata.name in want_tpu:
                    ok = node.status.capacity.get(t.RESOURCE_TPU, 0) > 0
                ready[node.metadata.name] = ok
            if len(ready) >= len(self.node_specs) and all(ready.values()):
                return
            if asyncio.get_running_loop().time() > deadline:
                raise TimeoutError(f"nodes not ready after {timeout}s: {ready}")
            await asyncio.sleep(0.2)
