"""Cluster component config — declarative `ktl up` configuration.

Reference: ``pkg/apis/componentconfig`` (serializable component configs
as API-shaped objects, loadable from files) — the flags-versus-config
duality the reference components share. One YAML document configures
the whole single-process cluster:

    kind: ClusterConfig
    port: 7070
    durable: true
    feature_gates: "PodPriority=true"
    authorization_mode: RBAC
    audit_log: /tmp/audit.jsonl
    nodes:
      - {name: tpu-0, real_tpu: true, via_cri: true}
      - {name: cpu-0}
      - {name: hollow-0, fake_runtime: true, tpu_chips: 4}

Scalar CLI flags override file values (the reference's precedence);
node-shape flags (--nodes/--tpu-chips/--real-tpu) conflict loudly with
a file `nodes:` list instead of silently replacing it.
"""
from __future__ import annotations

import dataclasses

from .local import NodeSpec


@dataclasses.dataclass
class ClusterConfig:
    host: str = "127.0.0.1"
    port: int = 7070
    data_dir: str = ""
    durable: bool = False
    feature_gates: str = ""
    authorization_mode: str = "AlwaysAllow"
    audit_log: str = ""
    audit_policy: str = ""
    audit_webhook: str = ""
    scheduler_policy: str = ""
    encryption_provider_config: str = ""
    nodes: list = dataclasses.field(default_factory=list)


_NODE_FIELDS = {f.name for f in dataclasses.fields(NodeSpec)}
_CLUSTER_FIELDS = {f.name for f in dataclasses.fields(ClusterConfig)}


def load_cluster_config(path: str) -> ClusterConfig:
    import yaml
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: document must be a mapping")
    if raw.get("kind", "ClusterConfig") != "ClusterConfig":
        raise ValueError(f"{path}: kind must be ClusterConfig")
    unknown = set(raw) - _CLUSTER_FIELDS - {"kind", "api_version"}
    if unknown:
        raise ValueError(f"{path}: unknown fields {sorted(unknown)}")
    cfg = ClusterConfig(**{k: v for k, v in raw.items()
                           if k in _CLUSTER_FIELDS and k != "nodes"})
    for i, n in enumerate(raw.get("nodes") or []):
        if not isinstance(n, dict):
            raise ValueError(f"{path}: nodes[{i}] must be a mapping")
        bad = set(n) - _NODE_FIELDS
        if bad:
            raise ValueError(f"{path}: nodes[{i}]: unknown fields "
                             f"{sorted(bad)}")
        if n.get("mesh_shape"):
            n = {**n, "mesh_shape": tuple(n["mesh_shape"])}
        cfg.nodes.append(NodeSpec(**n))
    return cfg


def config_from_args(args) -> ClusterConfig:
    """THE single merge point for ``ktl up``: file config (if any) as
    the base, every scalar flag the user actually passed on top (flags
    use argparse.SUPPRESS defaults, so presence == explicitly passed),
    and a default node set when neither defines nodes. Node-shape flags
    combined with a file `nodes:` list raise (no silent replacement)."""
    path = getattr(args, "config", "")
    cfg = load_cluster_config(path) if path else ClusterConfig()
    for name in ("host", "port", "data_dir", "durable", "feature_gates",
                 "authorization_mode", "audit_log", "audit_policy",
                 "audit_webhook", "scheduler_policy",
                 "encryption_provider_config"):
        if hasattr(args, name):
            setattr(cfg, name, getattr(args, name))
    node_flags = any(hasattr(args, k)
                     for k in ("nodes", "tpu_chips", "real_tpu"))
    if node_flags and cfg.nodes:
        # Silently discarding the file's typed node list for a rebuilt
        # default one would lose configuration; make the conflict loud.
        raise ValueError(
            "--nodes/--tpu-chips/--real-tpu conflict with the config "
            "file's `nodes:` list; edit the file or drop the flags")
    if node_flags or not cfg.nodes:
        count = getattr(args, "nodes", 1)
        chips = getattr(args, "tpu_chips", 0)
        real = getattr(args, "real_tpu", False)
        cfg.nodes = [NodeSpec(name=f"node-{i}",
                              tpu_chips=chips if not real else 0,
                              real_tpu=real and i == 0)
                     for i in range(count)]
    return cfg
