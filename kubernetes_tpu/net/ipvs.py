"""IPVS virtual-server renderer — the second kernel dataplane mode.

Reference: ``pkg/proxy/ipvs/proxier.go`` (2.2k ln). Where iptables mode
rewrites O(services x endpoints) NAT rules every sync, IPVS mode keeps
one kernel virtual server per service port (with real servers as
members) plus an O(1) static iptables ruleset driven by ipsets — so a
sync is an incremental delta against kernel state, not a full-table
restore. That incremental property is the reason the mode exists, and
it is modeled here explicitly: :func:`diff` computes the exact
``ipvsadm`` command list that turns the current kernel state into the
desired one, and is what the syncer applies.

Same split as ``net/iptables.py``: *computing* the desired state and
the deltas is pure and golden-file testable anywhere; *applying*
(``ipvsadm`` / ``ipset restore`` / ``iptables-restore``) is thin and
root-gated. The userspace proxy (``net/proxy.py``) stays the default
dataplane on unprivileged hosts.

Wire formats follow the real tools so outputs are comparable against a
kube-proxy ipvs node: ``ipvsadm -S -n`` save/restore syntax,
``ipset restore`` syntax, and the reference's ipset names
(``KUBE-CLUSTER-IP``, ``KUBE-NODE-PORT-TCP``, ``KUBE-LOOP-BACK``).
"""
from __future__ import annotations

import logging
from dataclasses import dataclass, field

from ..api import types as t
from .iptables import MARK_MASQ_CHAIN, MASQ_MARK, POSTROUTING_CHAIN

log = logging.getLogger("ipvs")

#: The dummy link that owns every cluster IP so the kernel accepts
#: them locally (reference: DefaultDummyDevice "kube-ipvs0").
DUMMY_DEVICE = "kube-ipvs0"

SERVICES_CHAIN = "KUBE-SERVICES"  # ipvs mode's own (static) version

SET_CLUSTER_IP = "KUBE-CLUSTER-IP"
SET_LOOP_BACK = "KUBE-LOOP-BACK"
SET_NODE_PORT_TCP = "KUBE-NODE-PORT-TCP"
SET_NODE_PORT_UDP = "KUBE-NODE-PORT-UDP"


@dataclass(frozen=True)
class RealServer:
    ip: str
    port: int
    weight: int = 1


@dataclass
class VirtualServer:
    address: str
    port: int
    protocol: str = "tcp"          # lowercase
    scheduler: str = "rr"
    persistent_seconds: int = 0    # >0 = ClientIP session affinity
    real_servers: list[RealServer] = field(default_factory=list)

    @property
    def key(self) -> str:
        return f"{self.protocol}:{self.address}:{self.port}"

    @property
    def flag(self) -> str:
        return "-t" if self.protocol == "tcp" else "-u"


@dataclass
class IpvsState:
    """Everything the ipvs dataplane programs for one sync."""
    virtual_servers: list[VirtualServer] = field(default_factory=list)
    #: Addresses the dummy device must hold (cluster IPs).
    dummy_addresses: list[str] = field(default_factory=list)
    #: (ip, protocol, port) cluster-IP tuples for KUBE-CLUSTER-IP.
    cluster_ip_entries: list[tuple[str, str, int]] = field(
        default_factory=list)
    #: (pod_ip, protocol, port) hairpin tuples for KUBE-LOOP-BACK.
    loopback_entries: list[tuple[str, str, int]] = field(
        default_factory=list)
    #: NodePort numbers per protocol.
    node_ports: dict[str, list[int]] = field(default_factory=dict)


def compute_state(services: list[t.Service],
                  endpoints_by_svc: dict[str, t.Endpoints],
                  node_ips: tuple[str, ...] = ()) -> IpvsState:
    """Desired IPVS state for these Services/Endpoints — pure.

    One virtual server per (cluster IP, port); one more per (node IP,
    node port) when ``node_ips`` are supplied (the reference binds
    NodePorts on every local address). Services with no ready
    endpoints keep an EMPTY virtual server — members return when
    endpoints do, without re-creating the service (and its affinity
    state) in the kernel."""
    state = IpvsState()
    dummy: set[str] = set()
    for svc in sorted(services, key=lambda s: (s.metadata.namespace,
                                               s.metadata.name)):
        if not svc.spec.cluster_ip or svc.spec.cluster_ip == "None":
            continue  # headless: DNS-only
        eps = endpoints_by_svc.get(
            f"{svc.metadata.namespace}/{svc.metadata.name}")
        sticky = 0
        if svc.spec.session_affinity == "ClientIP":
            sticky = svc.spec.session_affinity_timeout_seconds
        dummy.add(svc.spec.cluster_ip)
        for p in svc.spec.ports:
            proto = p.protocol.lower()
            reals = []
            if eps is not None:
                for ss in eps.subsets:
                    for ep_port in ss.ports:
                        if (ep_port.name or "") != (p.name or ""):
                            continue
                        for addr in ss.addresses:
                            reals.append(RealServer(addr.ip, ep_port.port))
            reals.sort(key=lambda r: (r.ip, r.port))
            state.virtual_servers.append(VirtualServer(
                address=svc.spec.cluster_ip, port=p.port, protocol=proto,
                persistent_seconds=sticky, real_servers=list(reals)))
            state.cluster_ip_entries.append(
                (svc.spec.cluster_ip, proto, p.port))
            for r in reals:
                state.loopback_entries.append((r.ip, proto, r.port))
            if p.node_port:
                state.node_ports.setdefault(proto, []).append(p.node_port)
                for nip in node_ips:
                    state.virtual_servers.append(VirtualServer(
                        address=nip, port=p.node_port, protocol=proto,
                        persistent_seconds=sticky,
                        real_servers=list(reals)))
    state.virtual_servers.sort(key=lambda v: v.key)
    state.dummy_addresses = sorted(dummy)
    state.cluster_ip_entries.sort()
    state.loopback_entries = sorted(set(state.loopback_entries))
    for proto in state.node_ports:
        state.node_ports[proto] = sorted(set(state.node_ports[proto]))
    return state


# ---------------------------------------------------------------------------
# Rendering (ipvsadm / ipset / iptables wire formats)
# ---------------------------------------------------------------------------


def render_ipvsadm(state: IpvsState) -> str:
    """``ipvsadm -S -n`` syntax (accepted by ``ipvsadm -R``) —
    deterministic, for golden-file equivalence tests."""
    lines = []
    for vs in state.virtual_servers:
        line = f"-A {vs.flag} {vs.address}:{vs.port} -s {vs.scheduler}"
        if vs.persistent_seconds:
            line += f" -p {vs.persistent_seconds}"
        lines.append(line)
        for r in vs.real_servers:
            # -m = masquerade (NAT) forwarding, the kube-proxy mode.
            lines.append(f"-a {vs.flag} {vs.address}:{vs.port} "
                         f"-r {r.ip}:{r.port} -m -w {r.weight}")
    return "\n".join(lines) + ("\n" if lines else "")


def parse_ipvsadm_save(text: str) -> list[VirtualServer]:
    """Inverse of :func:`render_ipvsadm` — also reads real
    ``ipvsadm -S -n`` output, which is how the syncer learns current
    kernel state for the diff."""
    by_key: dict[str, VirtualServer] = {}
    for line in text.splitlines():
        parts = line.split()
        if not parts:
            continue
        if parts[0] == "-A":
            proto = "tcp" if parts[1] == "-t" else "udp"
            addr, _, port = parts[2].rpartition(":")
            vs = VirtualServer(address=addr, port=int(port), protocol=proto)
            rest = parts[3:]
            if "-s" in rest:
                vs.scheduler = rest[rest.index("-s") + 1]
            if "-p" in rest:
                at = rest.index("-p") + 1
                # `ipvsadm -S` may omit the timeout (default 360).
                vs.persistent_seconds = (
                    int(rest[at]) if at < len(rest)
                    and rest[at].isdigit() else 360)
            by_key[vs.key] = vs
        elif parts[0] == "-a":
            proto = "tcp" if parts[1] == "-t" else "udp"
            addr, _, port = parts[2].rpartition(":")
            key = f"{proto}:{addr}:{port}"
            rip, _, rport = parts[parts.index("-r") + 1].rpartition(":")
            weight = 1
            if "-w" in parts:
                weight = int(parts[parts.index("-w") + 1])
            if key in by_key:
                by_key[key].real_servers.append(
                    RealServer(rip, int(rport), weight))
    out = sorted(by_key.values(), key=lambda v: v.key)
    for vs in out:
        vs.real_servers.sort(key=lambda r: (r.ip, r.port))
    return out


def render_ipsets(state: IpvsState) -> str:
    """``ipset restore`` input for the three reference sets. The
    static iptables ruleset matches against these sets, which is what
    keeps the iptables side O(1) in services.

    Build-and-swap, not flush-in-place: each set's entries are loaded
    into a same-typed ``<name>-tmp`` set and atomically ``swap``ped in,
    so no packet ever races a half-populated set (a flush-then-add
    window would drop the hairpin SNAT mark mid-sync; the reference
    avoids the window by syncing per-entry deltas)."""
    specs = [
        (SET_CLUSTER_IP, "hash:ip,port",
         [f"{ip},{proto}:{port}"
          for ip, proto, port in state.cluster_ip_entries]),
        # src ip == real-server ip and dst == itself: hairpin, must SNAT.
        (SET_LOOP_BACK, "hash:ip,port,ip",
         [f"{ip},{proto}:{port},{ip}"
          for ip, proto, port in state.loopback_entries]),
        (SET_NODE_PORT_TCP, "bitmap:port range 0-65535",
         [str(p) for p in state.node_ports.get("tcp", ())]),
        (SET_NODE_PORT_UDP, "bitmap:port range 0-65535",
         [str(p) for p in state.node_ports.get("udp", ())]),
    ]
    lines = []
    for name, settype, entries in specs:
        tmp = f"{name}-tmp"
        lines.append(f"create {name} {settype} -exist")
        lines.append(f"create {tmp} {settype} -exist")
        lines.append(f"flush {tmp}")
        lines.extend(f"add {tmp} {e} -exist" for e in entries)
        lines.append(f"swap {tmp} {name}")
        lines.append(f"destroy {tmp}")
    return "\n".join(lines) + "\n"


def render_iptables(cluster_cidr: str = "",
                    masquerade_all: bool = False) -> str:
    """The STATIC nat ruleset for ipvs mode — size-independent of the
    service count (reference: writeIptablesRules). All service
    awareness lives in the ipsets; these rules only decide what to
    masquerade before IPVS picks a real server."""
    chains = [f":{SERVICES_CHAIN} - [0:0]",
              f":{POSTROUTING_CHAIN} - [0:0]",
              f":{MARK_MASQ_CHAIN} - [0:0]"]
    rules = [
        f'-A {POSTROUTING_CHAIN} -m comment --comment '
        f'"kubernetes service traffic requiring SNAT" '
        f"-m mark --mark {MASQ_MARK} -j MASQUERADE",
        f"-A {MARK_MASQ_CHAIN} -j MARK --set-xmark {MASQ_MARK}",
        # Hairpin: pod reaching itself through a VIP.
        f'-A {SERVICES_CHAIN} -m comment --comment '
        f'"Kubernetes endpoints dst ip:port, source ip for solving '
        f'hairpin purpose" -m set --match-set {SET_LOOP_BACK} '
        f"dst,dst,src -j {MARK_MASQ_CHAIN}",
    ]
    if masquerade_all:
        rules.append(
            f'-A {SERVICES_CHAIN} -m comment --comment '
            f'"Kubernetes service cluster ip + port for masquerade" '
            f"-m set --match-set {SET_CLUSTER_IP} dst,dst "
            f"-j {MARK_MASQ_CHAIN}")
    elif cluster_cidr:
        rules.append(
            f'-A {SERVICES_CHAIN} -m comment --comment '
            f'"Kubernetes service cluster ip + port for masquerade" '
            f"-m set --match-set {SET_CLUSTER_IP} dst,dst "
            f"! -s {cluster_cidr} -j {MARK_MASQ_CHAIN}")
    rules.append(
        f"-A {SERVICES_CHAIN} -m addrtype --dst-type LOCAL "
        f"-m set --match-set {SET_NODE_PORT_TCP} dst "
        f"-m tcp -p tcp -j {MARK_MASQ_CHAIN}")
    rules.append(
        f"-A {SERVICES_CHAIN} -m addrtype --dst-type LOCAL "
        f"-m set --match-set {SET_NODE_PORT_UDP} dst "
        f"-m udp -p udp -j {MARK_MASQ_CHAIN}")
    return "\n".join(["*nat", *chains, *rules, "COMMIT", ""])


def dummy_address_commands(current: set[str],
                           desired: list[str]) -> list[list[str]]:
    """``ip addr`` deltas for the kube-ipvs0 dummy device."""
    want = set(desired)
    cmds = [["ip", "link", "add", DUMMY_DEVICE, "type", "dummy"]] \
        if want and not current else []
    for addr in sorted(want - current):
        cmds.append(["ip", "addr", "add", f"{addr}/32",
                     "dev", DUMMY_DEVICE])
    for addr in sorted(current - want):
        cmds.append(["ip", "addr", "del", f"{addr}/32",
                     "dev", DUMMY_DEVICE])
    return cmds


def parse_addr_show(text: str) -> set[str]:
    """Addresses from ``ip -o addr show dev kube-ipvs0`` output —
    reading kernel truth each sync (instead of trusting process
    memory) is what reconciles VIPs left by a previous run."""
    out = set()
    for line in text.splitlines():
        parts = line.split()
        if "inet" in parts:
            cidr = parts[parts.index("inet") + 1]
            out.add(cidr.split("/")[0])
    return out


def jump_rule_specs() -> list[tuple[str, str, list[str]]]:
    """Built-in-chain hooks for ipvs mode's STATIC ruleset. Differs
    from iptables mode's set: no filter-table KUBE-SERVICES exists
    here (no per-service REJECTs — IPVS owns dispatch), so only the
    nat-side hooks apply. Without these the restored chains are
    inert (see iptables.jump_rule_specs)."""
    portal = ["-m", "comment", "--comment", "kubernetes service portals",
              "-j", SERVICES_CHAIN]
    return [
        ("nat", "PREROUTING", portal),
        ("nat", "OUTPUT", portal),
        ("nat", "POSTROUTING",
         ["-m", "comment", "--comment", "kubernetes postrouting rules",
          "-j", POSTROUTING_CHAIN]),
    ]


# ---------------------------------------------------------------------------
# Incremental sync — the property that makes ipvs mode scale
# ---------------------------------------------------------------------------


def diff(current: list[VirtualServer],
         desired: list[VirtualServer]) -> list[list[str]]:
    """The exact ``ipvsadm`` argv list turning ``current`` into
    ``desired``. O(changes), not O(services): an untouched service
    contributes nothing (reference: syncService/syncEndpoint editing
    in place, vs iptables mode's full-table restore)."""
    cmds: list[list[str]] = []
    cur = {v.key: v for v in current}
    want = {v.key: v for v in desired}
    for key in sorted(cur.keys() - want.keys()):
        v = cur[key]
        cmds.append(["ipvsadm", "-D", v.flag, f"{v.address}:{v.port}"])
    for key in sorted(want.keys()):
        w = want[key]
        have = cur.get(key)
        vs_args = [w.flag, f"{w.address}:{w.port}", "-s", w.scheduler]
        if w.persistent_seconds:
            vs_args += ["-p", str(w.persistent_seconds)]
        if have is None:
            cmds.append(["ipvsadm", "-A", *vs_args])
            have_reals: dict[tuple, RealServer] = {}
        else:
            if (have.scheduler != w.scheduler
                    or bool(have.persistent_seconds)
                    != bool(w.persistent_seconds)
                    or (w.persistent_seconds
                        and have.persistent_seconds
                        != w.persistent_seconds)):
                cmds.append(["ipvsadm", "-E", *vs_args])
            have_reals = {(r.ip, r.port): r for r in have.real_servers}
        want_reals = {(r.ip, r.port): r for r in w.real_servers}
        for rk in sorted(have_reals.keys() - want_reals.keys()):
            cmds.append(["ipvsadm", "-d", w.flag,
                         f"{w.address}:{w.port}", "-r", f"{rk[0]}:{rk[1]}"])
        for rk in sorted(want_reals.keys()):
            r = want_reals[rk]
            base = [w.flag, f"{w.address}:{w.port}",
                    "-r", f"{r.ip}:{r.port}", "-m", "-w", str(r.weight)]
            if rk not in have_reals:
                cmds.append(["ipvsadm", "-a", *base])
            elif have_reals[rk].weight != r.weight:
                cmds.append(["ipvsadm", "-e", *base])
    return cmds


def can_apply() -> bool:
    import os
    import shutil
    return (os.geteuid() == 0 and shutil.which("ipvsadm") is not None
            and shutil.which("ipset") is not None)


class IpvsSyncer:
    """Watch Services + Endpoints and keep kernel IPVS state matching —
    the ipvs-mode counterpart of ``IptablesSyncer``. Each sync reads
    current state (``ipvsadm -S -n``), computes the desired state, and
    applies only the delta; ``last_diff`` exposes exactly what a
    privileged host would have run, so unprivileged environments still
    prove the computation."""

    def __init__(self, client, cluster_cidr: str = "",
                 node_ips: tuple[str, ...] = (),
                 min_sync_interval: float = 1.0):
        import asyncio
        from ..client.informer import SharedInformer
        self.client = client
        self.cluster_cidr = cluster_cidr
        self.node_ips = node_ips
        self.min_sync_interval = min_sync_interval
        self._svc = SharedInformer(client, "services")
        self._eps = SharedInformer(client, "endpoints")
        self._dirty = asyncio.Event()
        self._task = None
        self.last_state: IpvsState = IpvsState()
        self.last_rendered = ""
        self.last_diff: list[list[str]] = []
        self.applied = False
        self.syncs = 0

    async def start(self) -> None:
        import asyncio
        for inf in (self._svc, self._eps):
            inf.add_handlers(on_add=lambda o: self._dirty.set(),
                             on_update=lambda o, n: self._dirty.set(),
                             on_delete=lambda o: self._dirty.set())
            inf.start()
        for inf in (self._svc, self._eps):
            await inf.wait_for_sync()
        self._dirty.set()
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        import asyncio
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        for inf in (self._svc, self._eps):
            await inf.stop()

    async def _loop(self) -> None:
        import asyncio
        while True:
            await self._dirty.wait()
            self._dirty.clear()
            try:
                await asyncio.to_thread(self.sync)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one bad sync must not
                log.exception("ipvs sync failed; will retry on next "
                              "change")  # kill the loop for good
            await asyncio.sleep(self.min_sync_interval)  # debounce

    def sync(self) -> None:
        eps_by_svc = {e.metadata.namespace + "/" + e.metadata.name: e
                      for e in self._eps.list()}
        self.last_state = compute_state(self._svc.list(), eps_by_svc,
                                        node_ips=self.node_ips)
        self.last_rendered = render_ipvsadm(self.last_state)
        current = (self._read_kernel_state() if can_apply()
                   else parse_ipvsadm_save(""))
        self.last_diff = diff(current, self.last_state.virtual_servers)
        self.applied = self._apply() if can_apply() else False
        self.syncs += 1

    def _read_kernel_state(self) -> list[VirtualServer]:
        import subprocess
        try:
            out = subprocess.run(["ipvsadm", "-S", "-n"],
                                 capture_output=True, timeout=10)
            return parse_ipvsadm_save(out.stdout.decode())
        except Exception as e:  # noqa: BLE001
            log.error("reading ipvs state: %s", e)
            return []

    def _read_dummy_addrs(self) -> set[str]:
        import subprocess
        try:
            out = subprocess.run(
                ["ip", "-o", "addr", "show", "dev", DUMMY_DEVICE],
                capture_output=True, timeout=10)
            # rc != 0 = device absent: genuinely no addresses.
            return parse_addr_show(out.stdout.decode())
        except Exception as e:  # noqa: BLE001
            log.error("reading %s addrs: %s", DUMMY_DEVICE, e)
            return set()

    def _apply(self) -> bool:
        import subprocess
        ok = True
        try:
            proc = subprocess.run(
                ["ipset", "restore"],
                input=render_ipsets(self.last_state).encode(),
                capture_output=True, timeout=15)
            if proc.returncode != 0:
                log.error("ipset restore failed: %s", proc.stderr.decode())
                ok = False
            # Kernel truth, not process memory: reconciles VIPs left by
            # a previous run and retries adds that failed last sync.
            for cmd in dummy_address_commands(
                    self._read_dummy_addrs(),
                    self.last_state.dummy_addresses):
                proc = subprocess.run(cmd, capture_output=True, timeout=10)
                if proc.returncode != 0 and cmd[1] != "link":
                    # `ip link add` on an existing device is expected
                    # to fail (EEXIST) — only addr deltas are errors.
                    log.error("%s failed: %s", " ".join(cmd),
                              proc.stderr.decode())
                    ok = False
            for cmd in self.last_diff:
                proc = subprocess.run(cmd, capture_output=True, timeout=10)
                if proc.returncode != 0:
                    log.error("%s failed: %s", " ".join(cmd),
                              proc.stderr.decode())
                    ok = False
            from .iptables import apply_rules, ensure_jump_rules
            if apply_rules(render_iptables(self.cluster_cidr)):
                # Hook the static chains into the built-ins — without
                # this the whole nat ruleset is inert (ipvs-specific
                # spec set: no filter-table chains in this mode).
                if not ensure_jump_rules(specs=jump_rule_specs()):
                    ok = False
            else:
                ok = False
        except Exception as e:  # noqa: BLE001
            log.error("ipvs apply: %s", e)
            return False
        return ok
