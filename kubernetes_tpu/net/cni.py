"""CNI plugin seam — out-of-process pod network setup.

Reference: the Container Network Interface the kubelet drives through
``pkg/kubelet/network/cni`` — plugins are EXECUTABLES, invoked with
``CNI_COMMAND=ADD|DEL``, ``CNI_CONTAINERID``, ``CNI_NETNS``,
``CNI_IFNAME``, ``CNI_PATH`` in the environment and the network
configuration JSON on stdin; ADD answers a result JSON carrying the
assigned IPs. This module implements exactly that contract (spec
version 0.4.0 fields), so real CNI-shaped plugins drop in.

Discovery mirrors the kubelet: the lexicographically-first ``.conf`` /
``.conflist`` file in the conf dir names the plugin (``type``), which
must exist in the bin dir. No conf file = no CNI; the agent falls back
to its built-in loopback IPAM (this runtime's noop-networking mode,
like a kubelet before its CNI conf arrives — except pods still get
usable loopback IPs, so single-host clusters work out of the box).
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
from typing import Optional

log = logging.getLogger("cni")


class CNIError(Exception):
    """Plugin invocation failed; pod start retries (transient by
    contract, like every other sync-path failure)."""


class CNIInvoker:
    def __init__(self, conf_dir: str, bin_dir: str):
        self.conf_dir = conf_dir
        self.bin_dir = bin_dir
        self._conf_cache: tuple[float, Optional[dict]] = (0.0, None)
        #: pod uid -> (args, last ADD result): chained DEL passes the
        #: cached ADD result as prevResult (spec conflist DEL; a
        #: portmap-style meta-plugin cannot tear down without it).
        #: In-memory: after an agent restart DEL runs bare, best-effort.
        self._add_state: dict[str, tuple[dict, dict]] = {}

    def load_config(self) -> Optional[dict]:
        """First network config by filename, or None (no CNI). A short
        TTL cache keeps the disk scan off the per-container hot path
        while conf changes still apply within a second, no restart
        (kubelet re-reads the same way)."""
        import time
        ts, cached = self._conf_cache
        now = time.monotonic()
        if now - ts < 1.0:
            return cached
        conf = self._read_config()
        self._conf_cache = (now, conf)
        return conf

    def _read_config(self) -> Optional[dict]:
        """Normalized network config: {"name", "cniVersion",
        "plugins": [plugin conf, ...]} — a bare ``.conf`` becomes a
        one-element chain, a ``.conflist`` keeps its full chain (the
        spec's conflist semantics: every plugin runs in order on ADD
        with ``prevResult`` threading through, reverse order on DEL)."""
        try:
            names = sorted(os.listdir(self.conf_dir))
        except OSError:
            return None
        for name in names:
            if not name.endswith((".conf", ".conflist")):
                continue
            path = os.path.join(self.conf_dir, name)
            try:
                with open(path) as f:
                    conf = json.load(f)
            except (OSError, ValueError) as e:
                log.warning("skipping CNI conf %s: %s", path, e)
                continue
            if not isinstance(conf, dict):
                log.warning("skipping CNI conf %s: not an object", path)
                continue
            net_name = conf.get("name", "")
            version = conf.get("cniVersion", "0.4.0")
            if name.endswith(".conflist"):
                raw = conf.get("plugins") or []
                plugins = []
                for pl in raw:
                    if isinstance(pl, dict) and pl.get("type"):
                        plugins.append(dict(pl))
                    else:
                        # An invalid entry must be VISIBLE — silently
                        # running a partial chain (say, minus the
                        # firewall step) is worse than failing.
                        log.warning("CNI conf %s: dropping invalid "
                                    "plugin entry %r", path, pl)
                if not plugins or len(plugins) != len(raw):
                    continue  # invalid network config: try the next file
            else:
                if not conf.get("type"):
                    continue
                plugins = [conf]
            for pl in plugins:
                pl.setdefault("name", net_name)
                pl.setdefault("cniVersion", version)
            return {"name": net_name, "cniVersion": version,
                    "plugins": plugins}
        return None

    @property
    def enabled(self) -> bool:
        return self.load_config() is not None

    async def _invoke(self, command: str, conf: dict, container_id: str,
                      netns: str) -> dict:
        plugin = os.path.join(self.bin_dir, conf["type"])
        if not os.path.exists(plugin):
            raise CNIError(f"CNI plugin binary {plugin!r} not found")
        env = {**os.environ,
               "CNI_COMMAND": command,
               "CNI_CONTAINERID": container_id,
               "CNI_NETNS": netns,
               "CNI_IFNAME": "eth0",
               "CNI_PATH": self.bin_dir}
        proc = await asyncio.create_subprocess_exec(
            plugin, env=env,
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE)
        try:
            out, err = await asyncio.wait_for(
                proc.communicate(json.dumps(conf).encode()), 30.0)
        except asyncio.TimeoutError:
            proc.kill()
            raise CNIError(f"CNI {command} timed out") from None
        if proc.returncode != 0:
            # Spec: errors are JSON {code, msg} on stdout.
            detail = (out or err).decode(errors="replace")[:300]
            raise CNIError(f"CNI {command} failed "
                           f"(rc={proc.returncode}): {detail}")
        if command == "DEL" or not out.strip():
            return {}
        try:
            return json.loads(out)
        except ValueError as e:
            raise CNIError(f"CNI {command}: bad result JSON: {e}") from None

    async def add(self, pod_uid: str, pod_namespace: str,
                  pod_name: str) -> str:
        """ADD the pod to the network; returns its IP. The sandbox id
        is the pod uid (process runtime: no real netns — the plugin
        receives a pod-scoped placeholder path, exactly what it would
        get from a sandbox runtime)."""
        net = self.load_config()
        if net is None:
            raise CNIError("no CNI configuration present")
        args = {"K8S_POD_NAMESPACE": pod_namespace,
                "K8S_POD_NAME": pod_name,
                "K8S_POD_UID": pod_uid}
        result: dict = {}
        # Chain semantics: every plugin runs in order; each sees the
        # previous plugin's result as prevResult; the LAST result is
        # the network's outcome (spec conflist ADD). A mid-chain
        # failure tears the chain back DOWN before raising (the
        # kubelet's teardown-on-setup-failure) — otherwise the
        # caller's retry re-ADDs into plugins still holding the first
        # attempt's state.
        try:
            for plugin_conf in net["plugins"]:
                conf = {**plugin_conf, "runtimeConfig": {}, "args": args}
                if result:
                    conf["prevResult"] = result
                out = await self._invoke("ADD", conf, pod_uid,
                                         f"/var/run/netns/{pod_uid}")
                # A chained plugin that answers nothing passes the
                # previous result through unchanged (meta-plugins).
                if out:
                    result = out
        except CNIError:
            self._add_state[pod_uid] = (args, result)
            await self.delete(pod_uid)
            raise
        self._add_state[pod_uid] = (args, result)
        ips = result.get("ips") or []
        if not ips or "address" not in ips[0]:
            await self.delete(pod_uid)
            raise CNIError(f"CNI ADD returned no ips: {result}")
        return ips[0]["address"].split("/", 1)[0]

    async def delete(self, pod_uid: str) -> None:
        """DEL is best-effort and idempotent per spec; chained plugins
        tear down in REVERSE order with the cached ADD result as
        prevResult (spec conflist DEL) — bare after an agent restart,
        when the in-memory cache is gone."""
        net = self.load_config()
        if net is None:
            self._add_state.pop(pod_uid, None)
            return
        args, prev = self._add_state.pop(pod_uid, ({}, {}))
        for plugin_conf in reversed(net["plugins"]):
            conf = {**plugin_conf, "runtimeConfig": {}}
            if args:
                conf["args"] = args
            if prev:
                conf["prevResult"] = prev
            try:
                await self._invoke("DEL", conf, pod_uid,
                                   f"/var/run/netns/{pod_uid}")
            except CNIError as e:
                log.warning("CNI DEL (%s) for %s failed (continuing): %s",
                            plugin_conf.get("type"), pod_uid, e)
