"""iptables ruleset renderer — the kernel-dataplane analog.

Reference: ``pkg/proxy/iptables/proxier.go:973 syncProxyRules`` (1.7k
lines) and ``pkg/kubelet/network/hostport/hostport_syncer.go``. The
reference's core Service mechanism is kernel NAT programming; this
module computes the SAME iptables-restore rulesets — chain structure,
statistic-module load balancing, NodePort capture, ClientIP session
affinity, no-endpoint REJECTs, hostport DNAT — as pure functions of
(Services, Endpoints) / pod port mappings.

Split deliberately differs from the reference: *rendering* is a
deterministic pure function (golden-file testable anywhere, no root,
no kernel), *applying* is a thin ``iptables-restore --noflush`` call
gated on privilege. On the TPU dev hosts this framework targets there
is usually no root and no bridge CNI; the userspace forwarder
(``net/proxy.py``) stays the default dataplane, and these rulesets are
what a privileged deployment applies instead.

Chain-name convention matches the reference exactly (sha256 ->
base32 -> 16 chars) so rulesets are comparable against a real
kube-proxy's output for the same inputs.
"""
from __future__ import annotations

import base64
import hashlib
import logging
from dataclasses import dataclass, field

from ..api import types as t
from ..util.lockdep import make_lock

log = logging.getLogger("iptables")

SERVICES_CHAIN = "KUBE-SERVICES"
NODEPORTS_CHAIN = "KUBE-NODEPORTS"
POSTROUTING_CHAIN = "KUBE-POSTROUTING"
MARK_MASQ_CHAIN = "KUBE-MARK-MASQ"
FORWARD_CHAIN = "KUBE-FORWARD"
HOSTPORTS_CHAIN = "KUBE-HOSTPORTS"

#: The reference's default masquerade mark (proxier.go masqueradeMark,
#: --iptables-masquerade-bit 14).
MASQ_MARK = "0x4000/0x4000"


def _hash16(payload: str) -> str:
    digest = hashlib.sha256(payload.encode()).digest()
    return base64.b32encode(digest).decode()[:16]


def svc_chain(svc_port_name: str, protocol: str) -> str:
    """``KUBE-SVC-<hash>`` (reference: servicePortChainName)."""
    return "KUBE-SVC-" + _hash16(svc_port_name + protocol)


def sep_chain(svc_port_name: str, protocol: str, endpoint: str) -> str:
    """``KUBE-SEP-<hash>`` (reference: servicePortEndpointChainName)."""
    return "KUBE-SEP-" + _hash16(svc_port_name + protocol + endpoint)


def hostport_chain(host_port: int, protocol: str, pod_full_name: str) -> str:
    """``KUBE-HP-<hash>`` (reference: hostportChainName)."""
    return "KUBE-HP-" + _hash16(str(host_port) + protocol + pod_full_name)


def probability(n: int) -> str:
    """statistic-module probability for the i-th of n remaining
    endpoints (reference: computeProbability)."""
    return f"{1.0 / n:0.5f}"


@dataclass
class _PortProgram:
    """One service port resolved against its ready endpoints."""
    svc_port_name: str   # "<ns>/<name>:<port-name>"
    protocol: str        # lowercase
    cluster_ip: str
    port: int
    node_port: int
    endpoints: list[str]           # "ip:port"
    affinity_seconds: int = 0      # 0 = no ClientIP affinity


def _programs(services: list[t.Service],
              endpoints_by_svc: dict[str, t.Endpoints]) -> list[_PortProgram]:
    out = []
    for svc in sorted(services, key=lambda s: (s.metadata.namespace,
                                               s.metadata.name)):
        if not svc.spec.cluster_ip or svc.spec.cluster_ip == "None":
            continue  # headless: DNS-only, nothing to NAT
        eps = endpoints_by_svc.get(
            f"{svc.metadata.namespace}/{svc.metadata.name}")
        sticky = 0
        if svc.spec.session_affinity == "ClientIP":
            sticky = svc.spec.session_affinity_timeout_seconds
        for p in svc.spec.ports:
            pname = (f"{svc.metadata.namespace}/{svc.metadata.name}"
                     f":{p.name}")
            targets = []
            if eps is not None:
                for ss in eps.subsets:
                    for ep_port in ss.ports:
                        if (ep_port.name or "") != (p.name or ""):
                            continue
                        for addr in ss.addresses:
                            targets.append(f"{addr.ip}:{ep_port.port}")
            out.append(_PortProgram(
                svc_port_name=pname,
                protocol=p.protocol.lower(),
                cluster_ip=svc.spec.cluster_ip,
                port=p.port,
                node_port=p.node_port,
                endpoints=sorted(targets),
                affinity_seconds=sticky))
    return out


def render_service_rules(services: list[t.Service],
                         endpoints_by_svc: dict[str, t.Endpoints],
                         cluster_cidr: str = "",
                         masquerade_all: bool = False) -> str:
    """The full iptables-restore input kube-proxy's iptables mode would
    program for these Services/Endpoints: a ``*filter`` section
    (no-endpoint REJECTs + forward-accept) and a ``*nat`` section
    (capture -> per-service statistic load balancing -> per-endpoint
    DNAT). Deterministic for golden-file equivalence tests."""
    progs = _programs(services, endpoints_by_svc)

    filter_chains = [f":{SERVICES_CHAIN} - [0:0]",
                     f":{FORWARD_CHAIN} - [0:0]"]
    filter_rules: list[str] = []
    nat_chains = [f":{SERVICES_CHAIN} - [0:0]",
                  f":{NODEPORTS_CHAIN} - [0:0]",
                  f":{POSTROUTING_CHAIN} - [0:0]",
                  f":{MARK_MASQ_CHAIN} - [0:0]"]
    nat_rules: list[str] = []

    nat_rules.append(
        f'-A {POSTROUTING_CHAIN} -m comment --comment '
        f'"kubernetes service traffic requiring SNAT" '
        f'-m mark --mark {MASQ_MARK} -j MASQUERADE')
    nat_rules.append(
        f"-A {MARK_MASQ_CHAIN} -j MARK --set-xmark {MASQ_MARK}")

    for pr in progs:
        comment = f'-m comment --comment "{pr.svc_port_name}'
        match = (f"-m {pr.protocol} -p {pr.protocol} "
                 f"-d {pr.cluster_ip}/32 --dport {pr.port}")

        if not pr.endpoints:
            # No ready endpoints: REJECT at the filter table so clients
            # fail fast instead of hanging in SYN retries.
            filter_rules.append(
                f'-A {SERVICES_CHAIN} {comment} has no endpoints" '
                f"{match} -j REJECT")
            if pr.node_port:
                filter_rules.append(
                    f'-A {SERVICES_CHAIN} {comment} has no endpoints" '
                    f"-m addrtype --dst-type LOCAL -m {pr.protocol} "
                    f"-p {pr.protocol} --dport {pr.node_port} -j REJECT")
            continue

        chain = svc_chain(pr.svc_port_name, pr.protocol)
        nat_chains.append(f":{chain} - [0:0]")

        # Capture the cluster IP. Off-cluster sources masquerade
        # (static-route-to-any-node bouncing, proxier.go:1211).
        if masquerade_all:
            nat_rules.append(
                f'-A {SERVICES_CHAIN} {comment} cluster IP" {match} '
                f"-j {MARK_MASQ_CHAIN}")
        elif cluster_cidr:
            nat_rules.append(
                f'-A {SERVICES_CHAIN} {comment} cluster IP" {match} '
                f"! -s {cluster_cidr} -j {MARK_MASQ_CHAIN}")
        nat_rules.append(
            f'-A {SERVICES_CHAIN} {comment} cluster IP" {match} '
            f"-j {chain}")

        if pr.node_port:
            np_match = (f"-m {pr.protocol} -p {pr.protocol} "
                        f"--dport {pr.node_port}")
            nat_rules.append(
                f'-A {NODEPORTS_CHAIN} {comment}" {np_match} '
                f"-j {MARK_MASQ_CHAIN}")
            nat_rules.append(
                f'-A {NODEPORTS_CHAIN} {comment}" {np_match} -j {chain}')

        sep_chains = [sep_chain(pr.svc_port_name, pr.protocol, ep)
                      for ep in pr.endpoints]
        for sc in sep_chains:
            nat_chains.append(f":{sc} - [0:0]")

        # Session affinity first: a recent-list hit short-circuits the
        # random balancing below (proxier.go:1465).
        if pr.affinity_seconds:
            for sc in sep_chains:
                nat_rules.append(
                    f'-A {chain} {comment}" -m recent --name {sc} '
                    f"--rcheck --seconds {pr.affinity_seconds} --reap "
                    f"-j {sc}")

        # Probability-weighted fanout: i-th rule fires 1/(n-i) of the
        # time it is reached, giving uniform selection overall.
        n = len(sep_chains)
        for i, sc in enumerate(sep_chains):
            if i < n - 1:
                nat_rules.append(
                    f'-A {chain} {comment}" -m statistic --mode random '
                    f"--probability {probability(n - i)} -j {sc}")
            else:
                nat_rules.append(f'-A {chain} {comment}" -j {sc}')

        for sc, ep in zip(sep_chains, pr.endpoints):
            ep_ip = ep.rsplit(":", 1)[0]
            # Hairpin: a pod reaching itself through the VIP must SNAT.
            nat_rules.append(
                f'-A {sc} {comment}" -s {ep_ip}/32 -j {MARK_MASQ_CHAIN}')
            dnat = f'-A {sc} {comment}"'
            if pr.affinity_seconds:
                dnat += f" -m recent --name {sc} --set"
            nat_rules.append(
                f"{dnat} -m {pr.protocol} -p {pr.protocol} "
                f"-j DNAT --to-destination {ep}")

    # NodePort tail-call LAST (it matches any local address).
    nat_rules.append(
        f'-A {SERVICES_CHAIN} -m comment --comment '
        f'"kubernetes service nodeports; NOTE: this must be the last '
        f'rule in this chain" -m addrtype --dst-type LOCAL '
        f"-j {NODEPORTS_CHAIN}")

    filter_rules.append(
        f'-A {FORWARD_CHAIN} -m comment --comment '
        f'"kubernetes forwarding rules" -m mark --mark {MASQ_MARK} '
        f"-j ACCEPT")
    if cluster_cidr:
        for flag in ("-s", "-d"):
            filter_rules.append(
                f"-A {FORWARD_CHAIN} {flag} {cluster_cidr} "
                f"-m conntrack --ctstate RELATED,ESTABLISHED -j ACCEPT")

    return "\n".join(["*filter", *filter_chains, *filter_rules, "COMMIT",
                      "*nat", *nat_chains, *nat_rules, "COMMIT", ""])


# ---------------------------------------------------------------------------
# Hostports
# ---------------------------------------------------------------------------


@dataclass
class PodPortMapping:
    """A pod's hostPort claims (reference: hostport.PodPortMapping)."""
    namespace: str
    name: str
    pod_ip: str
    #: (host_port, container_port, protocol)
    ports: list[tuple[int, int, str]] = field(default_factory=list)

    @property
    def full_name(self) -> str:
        return f"{self.name}_{self.namespace}"


def render_hostport_rules(mappings: list[PodPortMapping]) -> str:
    """The *nat ruleset for pod hostPorts (reference:
    hostport_syncer.go SyncHostports): KUBE-HOSTPORTS dispatch by
    --dport, per-mapping KUBE-HP chain doing hairpin-masq + DNAT to
    podIP:containerPort."""
    chains = [f":{HOSTPORTS_CHAIN} - [0:0]"]
    rules: list[str] = []
    flat = []
    for m in sorted(mappings, key=lambda m: (m.namespace, m.name)):
        for host_port, container_port, proto in sorted(m.ports):
            flat.append((m, host_port, container_port, proto.lower()))
    for m, host_port, container_port, proto in flat:
        chain = hostport_chain(host_port, proto, m.full_name)
        chains.append(f":{chain} - [0:0]")
        comment = (f'-m comment --comment '
                   f'"{m.full_name} hostport {host_port}"')
        rules.append(
            f"-A {HOSTPORTS_CHAIN} {comment} -m {proto} -p {proto} "
            f"--dport {host_port} -j {chain}")
        rules.append(
            f"-A {chain} {comment} -s {m.pod_ip}/32 -j {MARK_MASQ_CHAIN}")
        rules.append(
            f"-A {chain} {comment} -m {proto} -p {proto} "
            f"-j DNAT --to-destination {m.pod_ip}:{container_port}")
    return "\n".join(["*nat", *chains, *rules, "COMMIT", ""])


def find_hostports(pod: t.Pod) -> list[tuple[int, int, str]]:
    """(host_port, container_port, protocol) claims in a pod spec."""
    out = []
    for c in pod.spec.containers + pod.spec.init_containers:
        for p in c.ports:
            if p.host_port:
                out.append((p.host_port, p.container_port or p.host_port,
                            p.protocol))
    return out


# ---------------------------------------------------------------------------
# Applying (privileged deployments only)
# ---------------------------------------------------------------------------


class HostportManager:
    """Node-side hostPort bookkeeping (reference: the kubelet's
    hostport syncer, invoked from sandbox setup/teardown). The node
    agent notes each networked pod; the full ruleset re-renders on any
    change and applies where privileged. ``last_rendered`` stays
    inspectable either way."""

    def __init__(self):
        self._pods: dict[str, PodPortMapping] = {}  # uid -> mapping
        self._prev_chains: set[str] = set()
        #: note_pod/forget_pod are offloaded to worker threads by
        #: independent per-pod workers; the whole read-render-apply
        #: must be atomic or interleaved applies can -X a chain the
        #: other thread's ruleset still references.
        self._lock = make_lock("iptables.Proxier")
        self.last_rendered = ""
        self.applied = False

    def note_pod(self, pod: t.Pod, pod_ip: str) -> None:
        """Idempotent: per-container-start calls with an unchanged
        mapping skip the render/apply entirely."""
        ports = find_hostports(pod)
        if not ports:
            return
        mapping = PodPortMapping(
            pod.metadata.namespace, pod.metadata.name, pod_ip, ports)
        with self._lock:
            if self._pods.get(pod.metadata.uid) == mapping:
                return
            self._pods[pod.metadata.uid] = mapping
            self._sync_locked()

    def forget_pod(self, uid: str) -> None:
        with self._lock:
            if self._pods.pop(uid, None) is not None:
                self._sync_locked()

    def _sync_locked(self) -> None:
        self.last_rendered = render_hostport_rules(
            sorted(self._pods.values(), key=lambda m: (m.namespace, m.name)))
        to_apply = with_stale_chain_cleanup(self.last_rendered,
                                            self._prev_chains)
        self._prev_chains = declared_dynamic_chains(self.last_rendered)
        # Apply first (creates KUBE-HOSTPORTS), then hook it into the
        # built-ins; the jump targets must exist before -I can succeed.
        self.applied = apply_rules(to_apply)
        if self.applied:
            ensure_jump_rules(hostports=True)


class IptablesSyncer:
    """The privileged-deployment dataplane loop: watch Services +
    Endpoints, re-render the full ruleset on any change (debounced),
    and ``iptables-restore`` it. The render is always exercised (the
    text is kept on ``last_rendered`` for inspection/metrics); the
    kernel apply happens only where :func:`can_apply` — elsewhere the
    userspace proxy carries traffic and this syncer just proves the
    ruleset. Reference: Proxier.syncRunner's bounded-frequency sync."""

    def __init__(self, client, cluster_cidr: str = "",
                 min_sync_interval: float = 1.0):
        import asyncio
        from ..client.informer import SharedInformer
        self.client = client
        self.cluster_cidr = cluster_cidr
        self.min_sync_interval = min_sync_interval
        self._svc = SharedInformer(client, "services")
        self._eps = SharedInformer(client, "endpoints")
        self._dirty = asyncio.Event()
        self._task = None
        self._prev_chains: set[str] = set()
        self.last_rendered = ""
        self.applied = False
        self.syncs = 0

    async def start(self) -> None:
        import asyncio
        for inf in (self._svc, self._eps):
            inf.add_handlers(on_add=lambda o: self._dirty.set(),
                             on_update=lambda o, n: self._dirty.set(),
                             on_delete=lambda o: self._dirty.set())
            inf.start()
        for inf in (self._svc, self._eps):
            await inf.wait_for_sync()
        self._dirty.set()
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        import asyncio
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        for inf in (self._svc, self._eps):
            await inf.stop()

    async def _loop(self) -> None:
        import asyncio
        while True:
            await self._dirty.wait()
            self._dirty.clear()
            try:
                # Offload: apply blocks up to its subprocess timeout
                # under xtables lock contention, and this loop shares
                # the control plane's event loop.
                await asyncio.to_thread(self.sync)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one bad sync must not
                log.exception("iptables sync failed; will retry on "
                              "next change")  # kill the loop for good
            await asyncio.sleep(self.min_sync_interval)  # debounce

    def sync(self) -> None:
        eps_by_svc = {e.metadata.namespace + "/" + e.metadata.name: e
                      for e in self._eps.list()}
        self.last_rendered = render_service_rules(
            self._svc.list(), eps_by_svc, cluster_cidr=self.cluster_cidr)
        to_apply = with_stale_chain_cleanup(self.last_rendered,
                                            self._prev_chains)
        self._prev_chains = declared_dynamic_chains(self.last_rendered)
        # Apply first (creates the KUBE-* chains), then hook them into
        # the built-ins — a jump to a not-yet-created chain fails.
        self.applied = apply_rules(to_apply)
        if self.applied:
            ensure_jump_rules()
        self.syncs += 1


def can_apply() -> bool:
    import os
    import shutil
    return os.geteuid() == 0 and shutil.which("iptables-restore") is not None


def jump_rule_specs(hostports: bool = False) -> list[tuple[str, str, list[str]]]:
    """(table, builtin chain, rule args) hooking the KUBE-* chains into
    the kernel's built-ins — without these the restored rulesets are
    inert. Reference: Proxier's iptablesJumpChains +
    ensureKubeHostportChains; kube-proxy installs them with EnsureRule,
    separately from the restore payload (appending them inside a
    --noflush restore would duplicate them every sync).

    ``hostports=True`` returns the KUBE-HOSTPORTS hooks instead — only
    the HostportManager installs those (its restore is what creates
    that chain; ensuring a jump to a chain that never exists would
    fail every service sync on hostport-less clusters)."""
    if hostports:
        hp = ["-m", "comment", "--comment", "kube hostport portals",
              "-m", "addrtype", "--dst-type", "LOCAL",
              "-j", HOSTPORTS_CHAIN]
        return [("nat", "PREROUTING", hp), ("nat", "OUTPUT", hp)]
    portal = ["-m", "comment", "--comment", "kubernetes service portals",
              "-j", SERVICES_CHAIN]
    return [
        ("nat", "PREROUTING", portal),
        ("nat", "OUTPUT", portal),
        ("nat", "POSTROUTING",
         ["-m", "comment", "--comment", "kubernetes postrouting rules",
          "-j", POSTROUTING_CHAIN]),
        # The filter-table KUBE-SERVICES (no-endpoint REJECTs) must be
        # reachable from every path a client's SYN can take: local
        # processes (OUTPUT), pod-forwarded traffic (FORWARD), and
        # NodePort traffic addressed to the node itself (INPUT).
        ("filter", "INPUT", portal),
        ("filter", "OUTPUT", portal),
        ("filter", "FORWARD", portal),
        ("filter", "FORWARD",
         ["-m", "comment", "--comment", "kubernetes forwarding rules",
          "-j", FORWARD_CHAIN]),
    ]


def ensure_jump_rules(hostports: bool = False,
                      specs: list | None = None) -> bool:
    """Idempotently install the built-in-chain jumps (``-C`` probe,
    ``-I`` on miss). Root-gated like apply_rules. Call AFTER the first
    apply_rules — the jumps target chains the restore creates.
    ``specs`` overrides the default spec list (the ipvs mode's ruleset
    creates a different chain set, so it supplies its own)."""
    if not can_apply():
        return False
    import subprocess
    ok = True
    for table, chain, args in (specs if specs is not None
                               else jump_rule_specs(hostports)):
        try:
            probe = subprocess.run(
                ["iptables", "-t", table, "-C", chain, *args],
                capture_output=True, timeout=10)
            if probe.returncode == 0:
                continue
            ins = subprocess.run(
                ["iptables", "-t", table, "-I", chain, *args],
                capture_output=True, timeout=10)
            if ins.returncode != 0:
                log.error("installing %s/%s jump failed: %s", table, chain,
                          ins.stderr.decode())
                ok = False
        except Exception as e:  # noqa: BLE001 — incl. TimeoutExpired
            log.error("jump-rule install %s/%s: %s", table, chain, e)
            ok = False
    return ok


_KUBE_DYNAMIC_PREFIXES = ("KUBE-SVC-", "KUBE-SEP-", "KUBE-HP-")


def declared_dynamic_chains(restore_text: str,
                            prefixes: tuple = _KUBE_DYNAMIC_PREFIXES
                            ) -> set[str]:
    """The dynamically-named chains a restore text declares.
    ``prefixes`` lets other rulesets (netpolicy's KTPU-NP* chains)
    reuse the stale-chain machinery."""
    out = set()
    for line in restore_text.splitlines():
        if line.startswith(":"):
            name = line[1:].split()[0]
            if name.startswith(prefixes):
                out.add(name)
    return out


def with_stale_chain_cleanup(restore_text: str,
                             prev_chains: set[str],
                             prefixes: tuple = _KUBE_DYNAMIC_PREFIXES
                             ) -> str:
    """--noflush keeps everything we don't mention, so chains for
    deleted Services/Endpoints would accumulate in the kernel forever.
    Declare each stale chain (declaring flushes it) and ``-X`` it at
    the end of its table, the reference's delete-stale-chains pass
    (proxier.go:1593-1608)."""
    current = declared_dynamic_chains(restore_text, prefixes)
    stale = sorted(prev_chains - current)
    if not stale:
        return restore_text
    lines = restore_text.splitlines()
    # All dynamic chains live in *nat; find its section bounds.
    nat_at = lines.index("*nat")
    commit_at = len(lines) - 1 - lines[::-1].index("COMMIT")
    decls = [f":{c} - [0:0]" for c in stale]
    deletes = [f"-X {c}" for c in stale]
    lines = (lines[:nat_at + 1] + decls + lines[nat_at + 1:commit_at]
             + deletes + lines[commit_at:])
    return "\n".join(lines)


def apply_rules(restore_text: str, timeout: float = 15.0) -> bool:
    """``iptables-restore --noflush`` (the reference's RestoreAll with
    NoFlushTables — never clobber non-kube chains). Returns False,
    with a log line, when unprivileged: the userspace proxy remains
    the dataplane there. Callers on an event loop must offload (this
    blocks up to ``timeout`` under xtables lock contention)."""
    if not can_apply():
        log.debug("iptables-restore unavailable (no root or no binary); "
                  "ruleset not applied")
        return False
    import subprocess
    try:
        proc = subprocess.run(
            ["iptables-restore", "--noflush"], input=restore_text.encode(),
            capture_output=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        log.error("iptables-restore timed out after %.0fs "
                  "(xtables lock contention?)", timeout)
        return False
    if proc.returncode != 0:
        log.error("iptables-restore failed: %s", proc.stderr.decode())
        return False
    return True
