"""Cluster networking — pod IPAM, service VIPs, and the proxy dataplane.

Reference split: ``pkg/controller/node/ipam`` (pod CIDR assignment),
``pkg/registry/core/service/ipallocator`` (cluster-IP bitmap),
``pkg/proxy/userspace`` (VIP -> endpoint forwarding), and kubelet's
service env injection (``pkg/kubelet/envvars/envvars.go``).
"""
from .ipam import CIDRAllocator, PodIPAllocator, cidr_hosts, int_to_ip, ip_to_int
from .envvars import service_env_vars
from .proxy import ServiceProxy

__all__ = [
    "CIDRAllocator", "PodIPAllocator", "ServiceProxy",
    "cidr_hosts", "int_to_ip", "ip_to_int", "service_env_vars",
]
