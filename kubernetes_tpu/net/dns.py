"""Cluster DNS — the kube-dns addon analog.

Reference: the kube-dns/skydns addon (``cluster/addons/dns``) answering
``<svc>.<ns>.svc.cluster.local`` with the Service's cluster IP and —
for headless services (the StatefulSet rank-discovery substrate,
SURVEY §2.4) — per-pod records ``<hostname>.<svc>.<ns>.svc.<domain>``
from Endpoints.

TPU-native shape: an in-process asyncio UDP responder fed by the same
service/endpoints informers the proxy uses (one watch stream, no
separate resolver fleet). Pods get ``KTPU_DNS_SERVER=<ip>:<port>`` in
their env; a JAX multi-host job can resolve its peers' pod IPs by
rank hostname without an external coordinator. Only A/IN queries are
answered (the addon's job here); everything else returns NOTIMP.
"""
from __future__ import annotations

import asyncio
import logging
import struct
from typing import Optional

from ..api import types as t
from ..client.informer import SharedInformer

log = logging.getLogger("clusterdns")

_FLAG_RESPONSE = 0x8180   # QR | RD | RA, NOERROR
_FLAG_NXDOMAIN = 0x8183
_FLAG_NOTIMP = 0x8184 | 0x0004  # NOTIMP rcode


def _parse_query(data: bytes) -> Optional[tuple[int, str, int, int, bytes]]:
    """(txn id, lowercase name, qtype, qclass, question bytes) or None."""
    if len(data) < 12:
        return None
    txn, flags, qd, _an, _ns, _ar = struct.unpack("!HHHHHH", data[:12])
    if flags & 0x8000 or qd != 1:
        return None
    labels = []
    pos = 12
    while pos < len(data):
        ln = data[pos]
        if ln == 0:
            pos += 1
            break
        if ln > 63 or pos + 1 + ln > len(data):
            return None
        labels.append(data[pos + 1: pos + 1 + ln].decode("ascii", "replace"))
        pos += 1 + ln
    if pos + 4 > len(data):
        return None
    qtype, qclass = struct.unpack("!HH", data[pos: pos + 4])
    return txn, ".".join(labels).lower(), qtype, qclass, data[12: pos + 4]


def _response(txn: int, question: bytes, ips: list[str],
              flags: int = _FLAG_RESPONSE, ttl: int = 5) -> bytes:
    # Encode first, count after: a non-IPv4 endpoint address (user-
    # created Endpoints can hold anything) must be dropped without
    # desyncing the header's answer count from the records present.
    records = []
    for ip in ips:
        try:
            raw = bytes(int(x) for x in ip.split("."))
        except ValueError:
            continue
        if len(raw) != 4:
            continue
        # 0xc00c: compression pointer to the question name at offset 12.
        records.append(struct.pack("!HHHIH", 0xC00C, 1, 1, ttl, 4) + raw)
    head = struct.pack("!HHHHHH", txn, flags, 1, len(records), 0, 0)
    return head + question + b"".join(records)


class ClusterDNS(asyncio.DatagramProtocol):
    """Start with ``await dns.start()``; resolve() is the pure core."""

    def __init__(self, client, domain: str = "cluster.local",
                 host: str = "127.0.0.1", port: int = 0):
        self.client = client
        self.domain = domain.strip(".").lower()
        self.host = host
        self.port = port
        self.services: Optional[SharedInformer] = None
        self.endpoints: Optional[SharedInformer] = None
        self._transport = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> int:
        self.services = SharedInformer(self.client, "services")
        self.endpoints = SharedInformer(self.client, "endpoints")
        self.services.start()
        self.endpoints.start()
        await self.services.wait_for_sync()
        await self.endpoints.wait_for_sync()
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self.host, self.port))
        self.port = self._transport.get_extra_info("sockname")[1]
        log.info("cluster DNS serving on %s:%d for *.%s",
                 self.host, self.port, self.domain)
        return self.port

    async def stop(self) -> None:
        if self._transport is not None:
            self._transport.close()
        for inf in (self.services, self.endpoints):
            if inf is not None:
                await inf.stop()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- resolution --------------------------------------------------------

    def resolve(self, name: str) -> Optional[list[str]]:
        """A records for ``name`` or None (NXDOMAIN).

        ``<svc>.<ns>.svc.<domain>``            -> cluster IP, or every
                                                  ready pod IP (headless)
        ``<hostname>.<svc>.<ns>.svc.<domain>`` -> that pod's IP
        """
        name = name.strip(".").lower()
        suffix = f".svc.{self.domain}"
        if not name.endswith(suffix):
            return None
        parts = name[: -len(suffix)].split(".")
        if len(parts) == 2:
            svc_name, ns = parts
            svc = self.services.get(f"{ns}/{svc_name}")
            if svc is None:
                return None
            if svc.spec.cluster_ip and svc.spec.cluster_ip != "None":
                return [svc.spec.cluster_ip]
            return self._endpoint_ips(ns, svc_name)  # headless
        if len(parts) == 3:
            hostname, svc_name, ns = parts
            ep = self.endpoints.get(f"{ns}/{svc_name}")
            if ep is None:
                return None
            ips = [a.ip for subset in ep.subsets for a in subset.addresses
                   if a.hostname == hostname and a.ip]
            return ips or None
        return None

    def _endpoint_ips(self, ns: str, svc_name: str) -> Optional[list[str]]:
        ep = self.endpoints.get(f"{ns}/{svc_name}")
        if ep is None:
            return None
        ips = [a.ip for subset in ep.subsets for a in subset.addresses if a.ip]
        return ips or None

    # -- UDP ---------------------------------------------------------------

    def connection_made(self, transport) -> None:
        self._transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        try:
            parsed = _parse_query(data)
            if parsed is None:
                return
            txn, name, qtype, qclass, question = parsed
            if qtype != 1 or qclass != 1:  # A / IN only
                self._transport.sendto(
                    _response(txn, question, [], flags=_FLAG_NOTIMP), addr)
                return
            ips = self.resolve(name)
            if ips:
                self._transport.sendto(_response(txn, question, ips), addr)
            else:
                self._transport.sendto(
                    _response(txn, question, [], flags=_FLAG_NXDOMAIN), addr)
        except Exception:  # noqa: BLE001 — a bad packet must not kill DNS
            log.exception("dns query handling failed")


def make_query(name: str, txn: int = 0x1234) -> bytes:
    """Build an A/IN query (client side; also what tests use)."""
    out = struct.pack("!HHHHHH", txn, 0x0100, 1, 0, 0, 0)
    for label in name.strip(".").split("."):
        raw = label.encode()
        out += bytes([len(raw)]) + raw
    return out + b"\x00" + struct.pack("!HH", 1, 1)


def parse_answer_ips(data: bytes) -> list[str]:
    """Extract A-record IPs from a response built by :func:`_response`."""
    if len(data) < 12:
        return []
    _txn, flags, _qd, an, _ns, _ar = struct.unpack("!HHHHHH", data[:12])
    if flags & 0x000F:  # rcode != NOERROR
        return []
    pos = 12
    while pos < len(data) and data[pos] != 0:  # skip question name
        pos += 1 + data[pos]
    pos += 5  # null + qtype + qclass
    ips = []
    for _ in range(an):
        if pos + 16 > len(data):
            break
        rdlen = struct.unpack("!H", data[pos + 10: pos + 12])[0]
        if rdlen == 4:
            ips.append(".".join(str(b) for b in data[pos + 12: pos + 16]))
        pos += 12 + rdlen
    return ips
