"""Service proxy — the kube-proxy analog (userspace mode).

Reference: ``pkg/proxy/userspace/proxier.go`` — for every service port,
open a local listener and forward accepted connections to one of the
service's ready endpoints (round-robin), reprogramming as Services and
Endpoints change. The reference's iptables mode
(``pkg/proxy/iptables/proxier.go:973 syncProxyRules``) moves the same
table into the kernel; a userspace forwarder is the honest equivalent
for a framework whose dev dataplane is real OS processes without root.

TPU-first note: training traffic (ICI collectives) never crosses this —
the proxy carries control-plane traffic (rendezvous/coordination
endpoints, metrics scrapes). Throughput is therefore not the design
driver; correctness under endpoint churn is.

Routing: endpoints publish virtual pod IPs (identity), which are not
routable on a dev host. The proxy resolves each endpoint to its node's
real address via the node informer (``EndpointAddress.node_name``) —
ProcessRuntime pods share the node's network namespace, exactly like
hostNetwork pods in the reference.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ..api import types as t
from ..client.informer import SharedInformer
from ..client.interface import Client

log = logging.getLogger("proxy")


def _port_key(name: str, port: int) -> str:
    return name or str(port)


class _PortForwarder:
    """One listening socket forwarding to a mutable backend list."""

    def __init__(self, bind_host: str, bind_port: int):
        self.bind_host = bind_host
        self.bind_port = bind_port          # 0 = ephemeral
        self.local_port = 0
        self.backends: list[tuple[str, int]] = []
        self._rr = 0
        self._server: Optional[asyncio.base_events.Server] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.bind_host, self.bind_port)
        self.local_port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    def pick(self) -> Optional[tuple[str, int]]:
        if not self.backends:
            return None
        self._rr = (self._rr + 1) % len(self.backends)
        return self.backends[self._rr]

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        backend = self.pick()
        if backend is None:
            writer.close()
            return
        try:
            r2, w2 = await asyncio.open_connection(*backend)
        except OSError:
            writer.close()
            return

        async def pipe(src: asyncio.StreamReader, dst: asyncio.StreamWriter):
            try:
                while True:
                    chunk = await src.read(65536)
                    if not chunk:
                        break
                    dst.write(chunk)
                    await dst.drain()
            except (OSError, asyncio.CancelledError):
                pass  # OSError covers ConnectionError, ETIMEDOUT, EBADF
            finally:
                # Half-close: propagate FIN without discarding data the
                # peer has not read yet (a full close() here can RST).
                try:
                    if dst.can_write_eof():
                        dst.write_eof()
                except (OSError, RuntimeError):
                    pass

        await asyncio.gather(pipe(reader, w2), pipe(r2, writer),
                             return_exceptions=True)
        for w in (writer, w2):
            try:
                w.close()
            except (OSError, RuntimeError):
                pass  # transport already torn down


class ServiceProxy:
    """Watches Services/Endpoints/Nodes; keeps one forwarder per
    service port. ``local_endpoint`` is the seam the node agent uses to
    point ``{SVC}_SERVICE_HOST/PORT`` env at a reachable address."""

    def __init__(self, client: Client, bind_host: str = "127.0.0.1"):
        self.client = client
        self.bind_host = bind_host
        self._svc = SharedInformer(client, "services")
        self._eps = SharedInformer(client, "endpoints")
        self._nodes = SharedInformer(client, "nodes")
        self._forwarders: dict[tuple[str, str, str], _PortForwarder] = {}
        self._nodeports: dict[tuple[str, str, str], _PortForwarder] = {}
        self._dirty: asyncio.Queue[str] = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        self._stopped = False

    @property
    def services_informer(self) -> SharedInformer:
        """Public seam for co-located consumers (the node agent shares
        this informer instead of opening a second watch stream)."""
        return self._svc

    async def start(self) -> None:
        self._svc.add_handlers(
            on_add=lambda s: self._mark(s.key()),
            on_update=lambda o, n: self._mark(n.key()),
            on_delete=lambda s: self._mark(s.key()))
        self._eps.add_handlers(
            on_add=lambda e: self._mark(e.key()),
            on_update=lambda o, n: self._mark(n.key()),
            on_delete=lambda e: self._mark(e.key()))
        # Node churn changes endpoint-host resolution: re-sync every
        # service when a node appears or its addresses change (rare
        # events; full re-mark is fine).
        self._nodes.add_handlers(
            on_add=lambda n: self._mark_all(),
            on_update=lambda o, n: (
                self._mark_all()
                if o.status.addresses != n.status.addresses else None))
        for inf in (self._svc, self._eps, self._nodes):
            inf.start()
        for inf in (self._svc, self._eps, self._nodes):
            await inf.wait_for_sync()
        for svc in self._svc.list():
            self._mark(svc.key())
        self._task = asyncio.get_running_loop().create_task(self._worker())

    async def stop(self) -> None:
        self._stopped = True
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        for fwd in list(self._forwarders.values()) + list(self._nodeports.values()):
            await fwd.stop()
        self._forwarders.clear()
        self._nodeports.clear()
        for inf in (self._svc, self._eps, self._nodes):
            await inf.stop()

    # -- table maintenance -------------------------------------------------

    def _mark(self, key: str) -> None:
        self._dirty.put_nowait(key)

    def _mark_all(self) -> None:
        for svc in self._svc.list():
            self._mark(svc.key())

    async def _worker(self) -> None:
        while not self._stopped:
            key = await self._dirty.get()
            try:
                await self._sync_service(key)
            except Exception:  # noqa: BLE001
                log.exception("proxy sync %s failed", key)

    async def _sync_service(self, key: str) -> None:
        ns, name = key.split("/", 1)
        svc = self._svc.get(key)
        if svc is None or svc.spec.cluster_ip == "None":
            await self._drop_service(ns, name)
            return
        backends = self._resolve_backends(ns, name)
        want: set[tuple[str, str, str]] = set()
        for p in svc.spec.ports:
            pk = _port_key(p.name, p.port)
            fid = (ns, name, pk)
            want.add(fid)
            fwd = self._forwarders.get(fid)
            if fwd is None:
                fwd = _PortForwarder(self.bind_host, 0)
                await fwd.start()
                self._forwarders[fid] = fwd
            # Endpoint ports match service ports by NAME ("" for the
            # single unnamed port) — reference endpoint semantics; the
            # endpoint's port number is the target port.
            fwd.backends = backends.get(p.name, [])
            if not (svc.spec.type == "NodePort" and p.node_port):
                # Port no longer exposed as NodePort (type change or
                # node_port cleared): tear the listener down.
                stale = self._nodeports.pop(fid, None)
                if stale:
                    await stale.stop()
            else:
                np = self._nodeports.get(fid)
                if np is None or np.bind_port != p.node_port:
                    if np:
                        # Drop the stale entry NOW: if start() below
                        # fails, a dead forwarder must not linger and
                        # shadow a later rebind to the same port.
                        await np.stop()
                        self._nodeports.pop(fid, None)
                    np = _PortForwarder("", p.node_port)
                    try:
                        await np.start()
                        self._nodeports[fid] = np
                    except OSError as e:
                        log.warning("nodeport %s/%s:%s: %s", ns, name,
                                    p.node_port, e)
                        np = None
                if np:
                    np.backends = backends.get(p.name, [])
        # Ports removed from the service spec.
        for fid in [f for f in self._forwarders if f[:2] == (ns, name)]:
            if fid not in want:
                await self._forwarders.pop(fid).stop()
                np = self._nodeports.pop(fid, None)
                if np:
                    await np.stop()

    async def _drop_service(self, ns: str, name: str) -> None:
        for table in (self._forwarders, self._nodeports):
            for fid in [f for f in table if f[:2] == (ns, name)]:
                await table.pop(fid).stop()

    def _resolve_backends(self, ns: str, name: str) -> dict[str, list[tuple[str, int]]]:
        eps = self._eps.get(f"{ns}/{name}")
        if eps is None:
            return {}
        out: dict[str, list[tuple[str, int]]] = {}
        for subset in eps.subsets:
            hosts = [self._endpoint_host(a) for a in subset.addresses]
            hosts = [h for h in hosts if h]
            for p in subset.ports:
                out.setdefault(p.name, []).extend((h, p.port) for h in hosts)
        return out

    def _endpoint_host(self, addr: t.EndpointAddress) -> str:
        if addr.node_name:
            node = self._nodes.get(addr.node_name)
            if node is not None and node.status.addresses:
                return node.status.addresses[0].address
        return addr.ip

    # -- lookup API (consumed by the agent's env injection) ---------------

    def local_endpoint(self, namespace: str, name: str,
                       port: "str | int") -> Optional[tuple[str, int]]:
        fwd = self._forwarders.get((namespace, name, str(port)))
        if fwd is None:
            return None
        host = self.bind_host or "127.0.0.1"
        return host, fwd.local_port

    def resolve_service(self, svc: t.Service) -> Optional[tuple[str, dict[str, int]]]:
        """envvars.Resolver: (reachable host, {port key: local port}).

        All-or-nothing: if ANY service port has no forwarder yet (sync
        window after a port is added), return None so env injection
        falls back to the VIP uniformly instead of emitting a localhost
        host paired with an unforwarded port number."""
        ports: dict[str, int] = {}
        host = None
        for p in svc.spec.ports:
            pk = _port_key(p.name, p.port)
            ep = self.local_endpoint(svc.metadata.namespace,
                                     svc.metadata.name, pk)
            if ep is None:
                return None
            host, ports[pk] = ep[0], ep[1]
        if host is None:
            return None
        return host, ports
