"""NetworkPolicy enforcement — filter-table ruleset renderer + syncer.

The reference apiserver stores NetworkPolicies and leaves enforcement
to the CNI plugin (Calico, kube-router, ...); those enforcers program
per-pod iptables *filter* chains. This module is that enforcer for the
framework's kernel dataplane: compute the full iptables-restore filter
ruleset from (policies, pods, namespaces) — ALWAYS, golden-file tested
— and apply it only where privileged, exactly the posture of
``net/iptables.py``'s NAT side (rationale at ``iptables.py:1-15``).

Chain structure (kube-router-style per-pod firewall chains with a
VERDICT MARK, not ACCEPT):

    KTPU-NETPOL            dispatch: dst-ip -> per-pod ingress chain,
                           src-ip -> per-pod egress chain — EVERY
                           matching chain is traversed (chains RETURN,
                           never ACCEPT, so when both endpoints of a
                           connection are governed, both policies are
                           evaluated; an ACCEPT in the first would end
                           hook traversal and bypass the second)
    KTPU-NPP-IN-<h>        one per governed (pod, Ingress): clear the
                           verdict mark, conntrack RETURN, per-rule
                           jumps each followed by admit-on-mark
                           RETURN, final DROP
    KTPU-NPP-OUT-<h>       same for Egress
    KTPU-NPR-<h>           one per policy rule: peer matches SET the
                           mark (0x10000, kube-router's NPC verdict
                           bit) instead of accepting
    KTPU-NPB-<h>           one per ipBlock-with-excepts: excepts
                           RETURN (to the RULE chain, so later peers
                           of the same rule still evaluate — additive
                           semantics), then the block sets the mark

Reference semantics implemented: selected pods default-deny per
``policy_types``; rules are additive across policies; unselected pods
are untouched (no chain, no dispatch rule).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from ..api import types as t
from ..api.networking import (POLICY_EGRESS, POLICY_INGRESS, NetworkPolicy,
                              default_policy_types)

DISPATCH_CHAIN = "KTPU-NETPOL"
#: Verdict mark bit (kube-router NPC uses the same value).
MARK = "0x10000"
ADMIT = f"-j MARK --set-xmark {MARK}/{MARK}"
NP_PREFIXES = ("KTPU-NPP-", "KTPU-NPR-", "KTPU-NPB-")


def _h(payload: str) -> str:
    return hashlib.sha256(payload.encode()).hexdigest()[:12].upper()


def pod_chain(direction: str, namespace: str, pod_name: str) -> str:
    tag = "IN" if direction == POLICY_INGRESS else "OUT"
    return f"KTPU-NPP-{tag}-{_h(f'{namespace}/{pod_name}/{direction}')}"


def rule_chain(policy_key: str, direction: str, index: int) -> str:
    return f"KTPU-NPR-{_h(f'{policy_key}/{direction}/{index}')}"


def block_chain(rchain: str, cidr: str, excepts: tuple) -> str:
    return f"KTPU-NPB-{_h(f'{rchain}/{cidr}/{sorted(excepts)}')}"


@dataclass
class _Resolved:
    """One rendered peer: concrete sources + the rule's port list."""
    peer_ips: list[str] = field(default_factory=list)
    cidr: str = ""
    excepts: list[str] = field(default_factory=list)
    any_peer: bool = False
    ports: list = field(default_factory=list)


def _ns_labels(namespaces: list[t.Namespace]) -> dict[str, dict]:
    return {ns.metadata.name: dict(ns.metadata.labels)
            for ns in namespaces}


def _resolve_peers(rule_peers, policy_ns: str, pods: list[t.Pod],
                   namespaces: list[t.Namespace]) -> list[_Resolved]:
    """Each peer resolves independently (additive)."""
    out = []
    ns_labels = _ns_labels(namespaces)
    for peer in rule_peers:
        r = _Resolved()
        if peer.ip_block is not None:
            r.cidr = peer.ip_block.cidr
            r.excepts = list(peer.ip_block.except_cidrs)
        else:
            if peer.namespace_selector is not None:
                ns_names = {name for name, labels in ns_labels.items()
                            if peer.namespace_selector.matches(labels)}
            else:
                ns_names = {policy_ns}
            for pod in pods:
                if pod.metadata.namespace not in ns_names:
                    continue
                if (peer.pod_selector is not None
                        and not peer.pod_selector.matches(
                            pod.metadata.labels)):
                    continue
                ip = pod.status.pod_ip
                if ip:
                    r.peer_ips.append(ip)
            r.peer_ips.sort()
        out.append(r)
    return out


def compute_rules(policies: list[NetworkPolicy], pods: list[t.Pod],
                  namespaces: list[t.Namespace]) -> dict:
    """-> {(namespace, pod): {"ip":..., direction: [(chain, [_Resolved])]}}
    for every governed pod with an IP."""
    governed: dict = {}
    for np in policies:
        ptypes = default_policy_types(np.spec)
        selected = [p for p in pods
                    if p.metadata.namespace == np.metadata.namespace
                    and np.spec.pod_selector.matches(p.metadata.labels)
                    and p.status.pod_ip]
        if not selected:
            continue
        key = f"{np.metadata.namespace}/{np.metadata.name}"
        for direction, rules in ((POLICY_INGRESS, np.spec.ingress),
                                 (POLICY_EGRESS, np.spec.egress)):
            if direction not in ptypes:
                continue
            rendered = []
            for i, rule in enumerate(rules):
                peers = (rule.from_peers if direction == POLICY_INGRESS
                         else rule.to_peers)
                resolved = (_resolve_peers(peers, np.metadata.namespace,
                                           pods, namespaces)
                            if peers else [_Resolved(any_peer=True)])
                for r in resolved:
                    r.ports = list(rule.ports)
                rendered.append((rule_chain(key, direction, i), resolved))
            for pod in selected:
                pk = (pod.metadata.namespace, pod.metadata.name)
                governed.setdefault(pk, {"ip": pod.status.pod_ip})
                governed[pk].setdefault(direction, []).extend(rendered)
    return governed


def _match_ports(ports) -> list[str]:
    if not ports:
        return [""]
    out = []
    for p in ports:
        proto = p.protocol.lower()
        if p.port:
            out.append(f"-p {proto} --dport {p.port}")
        else:
            out.append(f"-p {proto}")
    return out


def render_filter_rules(policies: list[NetworkPolicy], pods: list[t.Pod],
                        namespaces: list[t.Namespace]) -> str:
    """Full iptables-restore *filter* input (deterministic ordering —
    the golden files depend on it)."""
    governed = compute_rules(policies, pods, namespaces)
    chains = [f":{DISPATCH_CHAIN} - [0:0]"]
    rules: list[str] = []
    rule_bodies: dict[str, list[str]] = {}
    block_bodies: dict[str, list[str]] = {}

    for (ns, name) in sorted(governed):
        entry = governed[(ns, name)]
        ip = entry["ip"]
        for direction in (POLICY_INGRESS, POLICY_EGRESS):
            if direction not in entry:
                continue
            pchain = pod_chain(direction, ns, name)
            chains.append(f":{pchain} - [0:0]")
            flag = "-d" if direction == POLICY_INGRESS else "-s"
            rules.append(
                f'-A {DISPATCH_CHAIN} {flag} {ip}/32 -m comment '
                f'--comment "policy for {ns}/{name}" -j {pchain}')
            # Clear the verdict bit first: a previous pod chain's
            # admit must not leak into this one's decision.
            rules.append(f"-A {pchain} -j MARK --set-xmark 0x0/{MARK}")
            rules.append(
                f"-A {pchain} -m conntrack --ctstate RELATED,ESTABLISHED "
                f"-j RETURN")
            peer_flag = "-s" if direction == POLICY_INGRESS else "-d"
            for rchain, resolved in entry[direction]:
                if rchain not in rule_bodies:
                    body: list[str] = []
                    for r in resolved:
                        pms = _match_ports(r.ports)
                        if r.cidr and r.excepts:
                            # Excepts RETURN from their OWN chain so
                            # later peers of this rule still run. ALL
                            # the rule's ports live inside the block
                            # chain behind ONE jump (keying the chain
                            # per-port would drop every port but the
                            # first).
                            bchain = block_chain(rchain, r.cidr,
                                                 tuple(r.excepts))
                            if bchain not in block_bodies:
                                bb = [f"-A {bchain} {peer_flag} {ex} "
                                      f"-j RETURN" for ex in r.excepts]
                                for pm in pms:
                                    pm_sfx = f" {pm}" if pm else ""
                                    bb.append(
                                        f"-A {bchain} {peer_flag} "
                                        f"{r.cidr}{pm_sfx} {ADMIT}")
                                block_bodies[bchain] = bb
                            body.append(f"-A {rchain} -j {bchain}")
                            continue
                        for pm in pms:
                            pm_sfx = f" {pm}" if pm else ""
                            if r.any_peer:
                                body.append(f"-A {rchain}{pm_sfx} {ADMIT}")
                            elif r.cidr:
                                body.append(
                                    f"-A {rchain} {peer_flag} {r.cidr}"
                                    f"{pm_sfx} {ADMIT}")
                            else:
                                for pip in r.peer_ips:
                                    body.append(
                                        f"-A {rchain} {peer_flag} "
                                        f"{pip}/32{pm_sfx} {ADMIT}")
                    rule_bodies[rchain] = body
                rules.append(f"-A {pchain} -j {rchain}")
                rules.append(f"-A {pchain} -m mark --mark {MARK}/{MARK} "
                             f"-j RETURN")
            rules.append(
                f'-A {pchain} -m comment --comment "default deny '
                f'({direction.lower()})" -j DROP')

    for extra in (rule_bodies, block_bodies):
        for chain_name in sorted(extra):
            chains.append(f":{chain_name} - [0:0]")
    body_rules = [line
                  for extra in (rule_bodies, block_bodies)
                  for chain_name in sorted(extra)
                  for line in extra[chain_name]]
    return "\n".join(["*filter", *chains, *rules, *body_rules,
                      "COMMIT"]) + "\n"


def jump_rule_specs() -> list[tuple[str, str, list[str]]]:
    """(table, chain, rule-args) hooks: pod traffic traverses FORWARD
    (routed netns dataplanes) and INPUT/OUTPUT (the host-local process
    runtime)."""
    return [
        ("filter", "FORWARD", ["-j", DISPATCH_CHAIN]),
        ("filter", "INPUT", ["-j", DISPATCH_CHAIN]),
        ("filter", "OUTPUT", ["-j", DISPATCH_CHAIN]),
    ]


class NetworkPolicySyncer:
    """Watches policies/pods/namespaces; recomputes the filter ruleset
    on churn; applies via the shared iptables machinery (apply_rules +
    stale-chain cleanup + ensure_jump_rules) when privileged. Mirrors
    IptablesSyncer's shape and its to_thread offload — apply blocks on
    the xtables lock and must not stall the control-plane loop."""

    def __init__(self, client, min_sync_interval: float = 0.25):
        self.client = client
        self.min_sync_interval = min_sync_interval
        self.last_rendered = ""
        self.applied = False
        self.syncs = 0
        self._prev_chains: set[str] = set()
        self._informers = []
        self._dirty = None
        self._task = None

    async def start(self) -> None:
        import asyncio

        from ..client.informer import SharedInformer
        self._dirty = asyncio.Event()
        for plural in ("networkpolicies", "pods", "namespaces"):
            inf = SharedInformer(self.client, plural)
            inf.add_handlers(
                on_add=lambda o: self._dirty.set(),
                on_update=lambda o, n: self._dirty.set(),
                on_delete=lambda o: self._dirty.set())
            inf.start()
            self._informers.append(inf)
        for inf in self._informers:
            await inf.wait_for_sync()
        self._dirty.set()
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        import asyncio
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        for inf in self._informers:
            await inf.stop()

    async def _loop(self) -> None:
        import asyncio
        while True:
            await self._dirty.wait()
            self._dirty.clear()
            try:
                await asyncio.to_thread(self.sync)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — keep syncing on errors
                import logging
                logging.getLogger("netpolicy").exception("sync failed")
            await asyncio.sleep(self.min_sync_interval)

    def sync(self) -> None:
        from .iptables import (apply_rules, declared_dynamic_chains,
                               ensure_jump_rules, with_stale_chain_cleanup)
        pols, pods, nss = self._informers
        self.last_rendered = render_filter_rules(
            pols.list(), pods.list(), nss.list())
        to_apply = with_stale_chain_cleanup(
            self.last_rendered, self._prev_chains, prefixes=NP_PREFIXES)
        self._prev_chains = declared_dynamic_chains(
            self.last_rendered, prefixes=NP_PREFIXES)
        self.applied = apply_rules(to_apply)
        if self.applied:
            ensure_jump_rules(specs=jump_rule_specs())
        self.syncs += 1
