"""Service discovery env vars — the pre-DNS Kubernetes mechanism.

Reference: ``pkg/kubelet/envvars/envvars.go`` ``FromServices`` — for
every service visible to the pod, inject ``{SVC}_SERVICE_HOST``,
``{SVC}_SERVICE_PORT`` (first port), and ``{SVC}_SERVICE_PORT_{NAME}``
per named port. The kubelet builds this map from its service informer
at container start (``kubelet_pods.go getServiceEnvVarMap``).
"""
from __future__ import annotations

import re
from typing import Callable, Iterable, Optional

from ..api import types as t

_NAME_RE = re.compile(r"[^A-Z0-9_]")

#: resolve(service) -> (host, port_map) override, used when a local
#: ServiceProxy provides the actual reachable address for the VIP.
Resolver = Callable[[t.Service], Optional[tuple[str, dict[str, int]]]]


def _env_name(name: str) -> str:
    return _NAME_RE.sub("_", name.upper().replace("-", "_"))


def service_env_vars(services: Iterable[t.Service], namespace: str,
                     resolve: Optional[Resolver] = None) -> dict[str, str]:
    """Env map for a pod in ``namespace``. Headless services (no
    cluster IP) are skipped — they are DNS-identity only."""
    env: dict[str, str] = {}
    for svc in services:
        if svc.metadata.namespace != namespace:
            continue
        host = svc.spec.cluster_ip
        port_override: dict[str, int] = {}
        if resolve is not None:
            r = resolve(svc)
            if r is not None:
                host, port_override = r
        if not host or host == "None":
            continue
        base = _env_name(svc.metadata.name)
        env[f"{base}_SERVICE_HOST"] = host
        for i, p in enumerate(svc.spec.ports):
            port = port_override.get(p.name or str(p.port), p.port)
            if i == 0:
                env[f"{base}_SERVICE_PORT"] = str(port)
            if p.name:
                env[f"{base}_SERVICE_PORT_{_env_name(p.name)}"] = str(port)
    return env
