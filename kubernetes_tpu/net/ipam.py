"""IP address management — pod IPs and service cluster IPs.

Reference: ``pkg/controller/node/ipam/range_allocator.go`` (carves one
pod CIDR per node out of the cluster CIDR) and ``pkg/registry/core/
service/ipallocator/allocator.go`` (bitmap allocator for service VIPs).

Redesign notes: the reference persists the service-IP bitmap as its own
etcd object; here both allocators are in-memory and rebuilt from the
API objects they serve (node.spec.pod_cidr / service.spec.cluster_ip /
pod.status.pod_ip), which is the crash-only pattern the rest of the
framework uses — the API object IS the checkpoint.
"""
from __future__ import annotations

from typing import Iterable, Optional


def ip_to_int(ip: str) -> int:
    a, b, c, d = (int(x) for x in ip.split("."))
    return (a << 24) | (b << 16) | (c << 8) | d


def int_to_ip(n: int) -> str:
    return f"{(n >> 24) & 255}.{(n >> 16) & 255}.{(n >> 8) & 255}.{n & 255}"


def parse_cidr(cidr: str) -> tuple[int, int]:
    """Return (network int, prefix length)."""
    ip, _, plen = cidr.partition("/")
    plen_i = int(plen or "32")
    mask = ((1 << plen_i) - 1) << (32 - plen_i) if plen_i else 0
    return ip_to_int(ip) & mask, plen_i


def cidr_hosts(cidr: str) -> int:
    """Usable host addresses (network + broadcast excluded for /30 and
    wider, matching conventional IPv4 subnetting)."""
    _, plen = parse_cidr(cidr)
    size = 1 << (32 - plen)
    return size - 2 if size > 2 else size


class CIDRAllocator:
    """Carve fixed-size sub-CIDRs out of a cluster CIDR (one per node).

    Reference: ``range_allocator.go`` — same contract (occupy on
    observe, allocate next free), no etcd bitmap.
    """

    def __init__(self, cluster_cidr: str = "10.64.0.0/16",
                 node_prefix_len: int = 24):
        self.cluster_cidr = cluster_cidr
        self.node_prefix_len = node_prefix_len
        net, plen = parse_cidr(cluster_cidr)
        if node_prefix_len < plen:
            raise ValueError(f"node prefix /{node_prefix_len} wider than "
                             f"cluster CIDR {cluster_cidr}")
        self._net = net
        self._count = 1 << (node_prefix_len - plen)
        self._block = 1 << (32 - node_prefix_len)
        self._used: set[int] = set()

    def occupy(self, cidr: str) -> None:
        """Mark an externally-observed assignment as used."""
        net, _ = parse_cidr(cidr)
        idx = (net - self._net) // self._block
        if 0 <= idx < self._count:
            self._used.add(idx)

    def contains(self, cidr: str) -> bool:
        """Whether ``cidr`` is one of this allocator's node blocks."""
        net, plen = parse_cidr(cidr)
        if plen != self.node_prefix_len:
            return False
        idx = (net - self._net) // self._block
        return 0 <= idx < self._count and net == self._net + idx * self._block

    def is_used(self, cidr: str) -> bool:
        net, _ = parse_cidr(cidr)
        return (net - self._net) // self._block in self._used

    def release(self, cidr: str) -> None:
        net, _ = parse_cidr(cidr)
        idx = (net - self._net) // self._block
        self._used.discard(idx)

    def allocate(self) -> str:
        for idx in range(self._count):
            if idx not in self._used:
                self._used.add(idx)
                return (f"{int_to_ip(self._net + idx * self._block)}"
                        f"/{self.node_prefix_len}")
        raise RuntimeError(f"cluster CIDR {self.cluster_cidr} exhausted "
                           f"({self._count} node blocks)")


class PodIPAllocator:
    """Per-pod IPs from one node's pod CIDR, keyed by pod UID.

    Sequential first-free scan; .1 is reserved for the node itself
    (the CNI bridge address analog).
    """

    def __init__(self, cidr: str):
        self.cidr = cidr
        net, plen = parse_cidr(cidr)
        self._base = net + 2          # .0 network, .1 node
        self._size = max(0, (1 << (32 - plen)) - 3)  # minus broadcast
        self._by_uid: dict[str, int] = {}
        self._used: set[int] = set()
        #: uid -> IP OUTSIDE the node CIDR (CNI-plugin-assigned: the
        #: plugin owns its ranges; the allocator just records).
        self._external: dict[str, str] = {}

    @property
    def node_ip(self) -> str:
        net, _ = parse_cidr(self.cidr)
        return int_to_ip(net + 1)

    def has(self, uid: str) -> bool:
        return uid in self._by_uid or uid in self._external

    def ip_for(self, uid: str) -> str:
        """Allocate (idempotently) an IP for the pod UID."""
        if uid in self._external:
            return self._external[uid]
        if uid in self._by_uid:
            return int_to_ip(self._base + self._by_uid[uid])
        for off in range(self._size):
            if off not in self._used:
                self._used.add(off)
                self._by_uid[uid] = off
                return int_to_ip(self._base + off)
        raise RuntimeError(f"pod CIDR {self.cidr} exhausted")

    def occupy(self, uid: str, ip: str) -> None:
        """Adopt an existing pod->IP mapping (agent restart rebuild,
        or a CNI plugin's assignment — which may live outside the node
        CIDR, or not be IPv4 at all; the plugin owns its ranges)."""
        if uid in self._by_uid or uid in self._external:
            return
        try:
            off = ip_to_int(ip) - self._base
        except (ValueError, IndexError):
            self._external[uid] = ip  # e.g. IPv6 from a dual-stack plugin
            return
        if 0 <= off < self._size:
            self._used.add(off)
            self._by_uid[uid] = off
        else:
            self._external[uid] = ip

    def release(self, uid: str) -> None:
        self._external.pop(uid, None)
        off = self._by_uid.pop(uid, None)
        if off is not None:
            self._used.discard(off)

    def __len__(self) -> int:
        return len(self._by_uid) + len(self._external)


class ServiceIPAllocator:
    """Cluster-IP (VIP) allocator for Services.

    Reference: ``pkg/registry/core/service/ipallocator/allocator.go`` —
    the bitmap lives in etcd there; here occupancy is rebuilt from the
    stored Services themselves (registry does this lazily on first
    allocation).
    """

    def __init__(self, cidr: str = "10.96.0.0/16"):
        self.cidr = cidr
        net, plen = parse_cidr(cidr)
        self._base = net + 1
        self._size = max(0, (1 << (32 - plen)) - 2)
        self._used: set[int] = set()

    def occupy(self, ip: str) -> None:
        off = ip_to_int(ip) - self._base
        if 0 <= off < self._size:
            self._used.add(off)

    def contains(self, ip: str) -> bool:
        return 0 <= ip_to_int(ip) - self._base < self._size

    def is_used(self, ip: str) -> bool:
        return (ip_to_int(ip) - self._base) in self._used

    def release(self, ip: str) -> None:
        self._used.discard(ip_to_int(ip) - self._base)

    def allocate(self) -> str:
        for off in range(self._size):
            if off not in self._used:
                self._used.add(off)
                return int_to_ip(self._base + off)
        raise RuntimeError(f"service CIDR {self.cidr} exhausted")


def default_node_cidr(node_name: str, base: str = "10.88.0.0/16") -> str:
    """Deterministic fallback CIDR for a standalone agent (no IPAM
    controller running): hash the node name into the base range."""
    net, plen = parse_cidr(base)
    blocks = 1 << (24 - plen)
    idx = _stable_hash(node_name) % blocks
    return f"{int_to_ip(net + idx * 256)}/24"


def _stable_hash(s: str) -> int:
    h = 2166136261
    for ch in s.encode():
        h = ((h ^ ch) * 16777619) & 0xFFFFFFFF
    return h


def rebuild_pod_allocator(cidr: str, pods: Iterable) -> PodIPAllocator:
    """Build an allocator pre-occupied with the IPs of existing pods
    (crash-only restart: state rebuilt from the API)."""
    alloc = PodIPAllocator(cidr)
    for pod in pods:
        ip = getattr(pod.status, "pod_ip", "")
        uid = pod.metadata.uid
        if ip and uid:
            alloc.occupy(uid, ip)
    return alloc
