"""DaemonSet controller — one pod per eligible node.

Reference: ``pkg/controller/daemon`` (2.0k LoC). As in the reference era
(v1.9), the controller itself places pods by setting ``spec.nodeName``
directly — daemon pods bypass the scheduler, which is what lets the TPU
device plugin and metrics exporter run even on NotReady nodes.
Tolerations/nodeSelector/taints are evaluated here.
"""
from __future__ import annotations

from typing import Optional

from ..api import errors
from ..api import types as t
from ..api import workloads as w
from ..api.meta import is_controlled_by, now
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import (Controller, PodControl, is_pod_active, is_pod_ready,
                   pod_ready_since)


def node_eligible(ds: w.DaemonSet, node: t.Node) -> bool:
    template = ds.spec.template
    # Unschedulable nodes stay eligible: daemon pods ARE the node's
    # plumbing (matches the reference's critical-daemon behavior).
    for k, v in template.spec.node_selector.items():
        if node.metadata.labels.get(k) != v:
            return False
    for taint in node.spec.taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        # Not-ready/unreachable taints are tolerated by default: daemons
        # must keep running to fix the node.
        if taint.key in (t.TAINT_NODE_NOT_READY, t.TAINT_NODE_UNREACHABLE,
                         t.TAINT_NODE_UNSCHEDULABLE):
            continue
        if not any(tol.tolerates(taint) for tol in template.spec.tolerations):
            return False
    if template.spec.affinity and template.spec.affinity.node_required:
        if not any(term.matches(node.metadata.labels)
                   for term in template.spec.affinity.node_required):
            return False
    return True


class DaemonSetController(Controller):
    name = "daemonset-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 2):
        super().__init__(client, factory, workers)
        self.pod_control = PodControl(client, self.recorder)
        self.ds_informer = self.watch("daemonsets")
        self.pod_informer = self.watch("pods")
        self.node_informer = self.watch("nodes")
        self.ds_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n),
            on_delete=self.enqueue_obj)
        self.pod_informer.add_handlers(
            on_add=lambda p: self.enqueue_owner(p, "DaemonSet"),
            on_update=lambda o, n: self.enqueue_owner(n, "DaemonSet"),
            on_delete=lambda p: self.enqueue_owner(p, "DaemonSet"))
        # Any node change can flip eligibility for every DaemonSet.
        self.node_informer.add_handlers(
            on_add=lambda n: self._enqueue_all(),
            on_update=lambda o, n: self._enqueue_all(),
            on_delete=lambda n: self._enqueue_all())

    def _enqueue_all(self) -> None:
        for ds in self.ds_informer.list():
            self.enqueue_obj(ds)

    def _pods_by_node(self, ds: w.DaemonSet) -> dict[str, list[t.Pod]]:
        out: dict[str, list[t.Pod]] = {}
        for pod in self.pod_informer.list():
            if pod.metadata.namespace != ds.metadata.namespace:
                continue
            if not is_controlled_by(pod, ds):
                continue
            out.setdefault(pod.spec.node_name, []).append(pod)
        return out

    async def sync(self, key: str) -> Optional[float]:
        ds = self.ds_informer.get(key)
        if ds is None or ds.metadata.deletion_timestamp is not None:
            return None
        by_node = self._pods_by_node(ds)
        eligible = {n.metadata.name for n in self.node_informer.list()
                    if node_eligible(ds, n)}

        for node_name in eligible:
            all_here = by_node.get(node_name, [])
            # Reap terminal daemon pods — the reference daemon controller
            # deletes failed pods so they don't accumulate unboundedly.
            for pod in all_here:
                if (pod.status.phase in (t.POD_FAILED, t.POD_SUCCEEDED)
                        and pod.metadata.deletion_timestamp is None):
                    await self.pod_control.delete_pod(ds, pod)
            pods = [p for p in all_here if is_pod_active(p)]
            if not pods:
                def place(pod, node=node_name):
                    pod.spec.node_name = node
                await self.pod_control.create_pod(
                    ds, ds.spec.template,
                    generate_name=f"{ds.metadata.name}-", mutate=place)
            elif len(pods) > 1:
                for pod in pods[1:]:
                    await self.pod_control.delete_pod(ds, pod)

        for node_name, pods in by_node.items():
            if node_name and node_name not in eligible:
                for pod in pods:
                    if is_pod_active(pod):
                        await self.pod_control.delete_pod(ds, pod)

        await self._update_status(ds, by_node, eligible)
        return None

    async def _update_status(self, ds, by_node, eligible) -> None:
        ts = now()
        scheduled = {n: ps for n, ps in by_node.items()
                     if n and any(is_pod_active(p) for p in ps)}
        new = w.DaemonSetStatus(
            desired_number_scheduled=len(eligible),
            current_number_scheduled=sum(1 for n in scheduled if n in eligible),
            number_misscheduled=sum(1 for n in scheduled if n not in eligible),
            number_ready=sum(
                1 for n, ps in scheduled.items()
                if any(is_pod_ready(p) for p in ps)),
            number_available=sum(
                1 for n, ps in scheduled.items()
                if any(pod_ready_since(p, ds.spec.min_ready_seconds, ts)
                       for p in ps)),
            observed_generation=ds.metadata.generation)
        if new == ds.status:
            return
        fresh = w.DaemonSet(metadata=ds.metadata, spec=ds.spec, status=new)
        try:
            await self.client.update(fresh, subresource="status")
        except errors.NotFoundError:
            pass
