"""ResourceQuota controller — recompute usage, level-triggered.

Reference: ``pkg/controller/resourcequota`` + ``pkg/quota``: admission
enforces quotas synchronously (apiserver/admission.py
ResourceQuotaPlugin); this controller recalculates ``status.used`` from
actual objects so drift (force deletes, failed pods, admission races)
self-heals. Tracked resources mirror the admission plugin: pods, cpu,
memory, and google.com/tpu chips.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ..api import errors
from ..api import types as t
from ..api.scheme import deepcopy
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller


from ..apiserver.quota import pod_usage  # shared with admission  # noqa: E402


class ResourceQuotaController(Controller):
    name = "resourcequota-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 interval: float = 15.0):
        super().__init__(client, factory, workers=1)
        self.interval = interval
        self.quota_informer = self.watch("resourcequotas")
        self.pod_informer = self.watch("pods")
        self.quota_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n))
        self.pod_informer.add_handlers(
            on_add=lambda p: self._enqueue_ns(p),
            on_update=lambda o, n: self._enqueue_ns(n),
            on_delete=lambda p: self._enqueue_ns(p))
        self._task: Optional[asyncio.Task] = None

    def _enqueue_ns(self, pod: t.Pod) -> None:
        for q in self.quota_informer.list():
            if q.metadata.namespace == pod.metadata.namespace:
                self.enqueue_obj(q)

    async def on_start(self) -> None:
        async def resync():
            while True:
                await asyncio.sleep(self.interval)
                for q in self.quota_informer.list():
                    self.enqueue_obj(q)
        self._task = asyncio.get_running_loop().create_task(resync())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        await super().stop()

    async def sync(self, key: str) -> Optional[float]:
        quota = self.quota_informer.get(key)
        if quota is None:
            return None
        used: dict[str, float] = {}
        for pod in self.pod_informer.list():
            if pod.metadata.namespace != quota.metadata.namespace:
                continue
            if pod.metadata.deletion_timestamp is not None:
                continue
            for res, qty in pod_usage(pod).items():
                used[res] = used.get(res, 0.0) + qty
        tracked = {res: used.get(res, 0.0) for res in quota.spec.hard}
        if quota.status.used == tracked and \
                quota.status.hard == quota.spec.hard:
            return None
        fresh = deepcopy(quota)
        fresh.status.hard = dict(quota.spec.hard)
        fresh.status.used = tracked
        try:
            await self.client.update(fresh, subresource="status")
        except (errors.NotFoundError, errors.ConflictError):
            pass
        return None
