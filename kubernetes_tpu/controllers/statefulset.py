"""StatefulSet controller — ranked, stable-identity workers.

Reference: ``pkg/controller/statefulset`` (1.7k LoC). Pods are named
``<set>-<ordinal>`` and carry stable DNS identity via the headless
service (hostname=pod name, subdomain=serviceName). This is the rank
substrate for distributed TPU jobs (SURVEY.md section 2.4: "stable
identity for ranks: StatefulSet + headless Services").

TPU-first addition: every pod gets ``TPU_WORKER_ID=<ordinal>`` and
``TPU_WORKER_HOSTNAMES`` env so a JAX multi-host job can bootstrap
``jax.distributed`` without an external coordinator.
"""
from __future__ import annotations

import hashlib
import json
from typing import Optional

from ..api import errors
from ..api import types as t
from ..api import workloads as w
from ..api.meta import controller_ref, is_controlled_by
from ..api.scheme import deepcopy, to_dict
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import (Controller, PodControl, is_pod_active, is_pod_ready,
                   merge_container_env, rank_hostnames)

POD_NAME_LABEL = "statefulset.tpu/pod-name"
REVISION_LABEL = "statefulset.tpu/revision"


def _revision(spec_template: t.PodTemplateSpec) -> str:
    payload = json.dumps(to_dict(spec_template), sort_keys=True, default=str)
    return hashlib.sha1(payload.encode()).hexdigest()[:10]


def ordinal_of(pod_name: str, set_name: str) -> int:
    suffix = pod_name[len(set_name) + 1:]
    try:
        return int(suffix)
    except ValueError:
        return -1


class StatefulSetController(Controller):
    name = "statefulset-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 2):
        super().__init__(client, factory, workers)
        self.pod_control = PodControl(client, self.recorder)
        self.set_informer = self.watch("statefulsets")
        self.pod_informer = self.watch("pods")
        self.set_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n),
            on_delete=self.enqueue_obj)
        self.pod_informer.add_handlers(
            on_add=lambda p: self.enqueue_owner(p, "StatefulSet"),
            on_update=lambda o, n: self.enqueue_owner(n, "StatefulSet"),
            on_delete=lambda p: self.enqueue_owner(p, "StatefulSet"))

    def _pods_for(self, st: w.StatefulSet) -> dict[int, t.Pod]:
        out: dict[int, t.Pod] = {}
        for pod in self.pod_informer.list():
            if pod.metadata.namespace != st.metadata.namespace:
                continue
            if not is_controlled_by(pod, st):
                continue
            o = ordinal_of(pod.metadata.name, st.metadata.name)
            if o >= 0:
                out[o] = pod
        return out

    @staticmethod
    def claim_name(st: w.StatefulSet, template_name: str,
                   ordinal: int) -> str:
        """Reference naming: <template>-<set>-<ordinal> — the claim's
        stability across pod recreation IS the stable-storage contract."""
        return f"{template_name}-{st.metadata.name}-{ordinal}"

    async def _ensure_claims(self, st: w.StatefulSet, ordinal: int) -> None:
        """Create this ordinal's PVCs if absent (idempotent; existing
        claims are never touched — a replacement pod reattaches)."""
        for tpl in st.spec.volume_claim_templates:
            name = self.claim_name(st, tpl.metadata.name, ordinal)
            claim = t.PersistentVolumeClaim(
                metadata=t.ObjectMeta(  # type: ignore[attr-defined]
                    name=name, namespace=st.metadata.namespace,
                    labels=dict(st.spec.template.metadata.labels)),
                spec=deepcopy(tpl.spec))
            try:
                await self.client.create(claim)
            except errors.AlreadyExistsError:
                pass

    def _mutator(self, st: w.StatefulSet, ordinal: int, revision: str):
        hostnames = rank_hostnames(st.metadata.name, st.spec.replicas,
                                   st.spec.service_name,
                                   st.metadata.namespace)

        def mutate(pod: t.Pod) -> None:
            pod.spec.hostname = pod.metadata.name
            pod.spec.subdomain = st.spec.service_name
            pod.metadata.labels = {**pod.metadata.labels,
                                   POD_NAME_LABEL: pod.metadata.name,
                                   REVISION_LABEL: revision}
            merge_container_env(pod.spec.containers, [
                t.EnvVar(name="TPU_WORKER_ID", value=str(ordinal)),
                t.EnvVar(name="TPU_WORKER_HOSTNAMES", value=hostnames),
            ])
            have = {v.name for v in pod.spec.volumes}
            for tpl in st.spec.volume_claim_templates:
                if tpl.metadata.name in have:
                    continue  # template's volume overridden in the pod
                pod.spec.volumes.append(t.Volume(
                    name=tpl.metadata.name,
                    persistent_volume_claim=t.PersistentVolumeClaimVolume(
                        claim_name=self.claim_name(
                            st, tpl.metadata.name, ordinal))))

        return mutate

    async def sync(self, key: str) -> Optional[float]:
        st = self.set_informer.get(key)
        if st is None or st.metadata.deletion_timestamp is not None:
            return None
        revision = _revision(st.spec.template)
        pods = self._pods_for(st)
        ordered = st.spec.pod_management_policy != "Parallel"

        # Create missing ordinals [0, replicas), lowest first; in
        # OrderedReady mode stop at the first not-yet-ready predecessor.
        for i in range(st.spec.replicas):
            pod = pods.get(i)
            if pod is None:
                await self._ensure_claims(st, i)
                await self.pod_control.create_pod(
                    st, st.spec.template, name=f"{st.metadata.name}-{i}",
                    mutate=self._mutator(st, i, revision))
                if ordered:
                    break
                continue
            if ordered and not (is_pod_active(pod) and is_pod_ready(pod)):
                break

        # Scale down: delete ordinals >= replicas, highest first.
        extra = sorted((o for o in pods if o >= st.spec.replicas), reverse=True)
        for o in extra:
            await self.pod_control.delete_pod(st, pods[o])
            if ordered:
                break

        # Rolling update: replace outdated pods, highest ordinal first,
        # one at a time, only while all other pods are ready.
        if st.spec.update_strategy == w.ROLLING_UPDATE:
            current = [pods[o] for o in sorted(pods) if o < st.spec.replicas]
            if all(is_pod_ready(p) for p in current if is_pod_active(p)):
                for pod in sorted(
                        current,
                        key=lambda p: -ordinal_of(p.metadata.name,
                                                  st.metadata.name)):
                    if pod.metadata.deletion_timestamp is not None:
                        break
                    if pod.metadata.labels.get(REVISION_LABEL) != revision:
                        await self.pod_control.delete_pod(st, pod)
                        break

        await self._update_status(st, revision)
        return None

    async def _update_status(self, st: w.StatefulSet, revision: str) -> None:
        pods = self._pods_for(st)
        active = [p for p in pods.values() if is_pod_active(p)]
        updated = sum(1 for p in active
                      if p.metadata.labels.get(REVISION_LABEL) == revision)
        # Reference contract (currentRevision/updateRevision): current is
        # the pre-rollout revision until every replica is on the new one,
        # at which point it is promoted — so steady state reports
        # current_replicas == updated_replicas == replicas.
        current_rev = st.status.current_revision or revision
        if updated == st.spec.replicas and len(active) == st.spec.replicas:
            current_rev = revision
        new = w.StatefulSetStatus(
            observed_generation=st.metadata.generation,
            replicas=len(active),
            ready_replicas=sum(1 for p in active if is_pod_ready(p)),
            current_replicas=sum(
                1 for p in active
                if p.metadata.labels.get(REVISION_LABEL) == current_rev),
            updated_replicas=updated,
            current_revision=current_rev,
            update_revision=revision,
        )
        if new == st.status:
            return
        fresh = w.StatefulSet(metadata=st.metadata, spec=st.spec, status=new)
        try:
            await self.client.update(fresh, subresource="status")
        except errors.NotFoundError:
            pass
