"""Controller base: informer events -> rate-limited workqueue -> sync(key).

Reference pattern: ``pkg/controller/replicaset/replica_set.go`` — ``Run``
(:178) spins workers, ``worker`` (:433) drains the queue, ``syncReplicaSet``
(:572) reconciles one key; errors re-enqueue with per-item exponential
backoff, success forgets the item. Controllers here are asyncio-native:
informer handlers run on the loop and enqueue synchronously.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Any, Iterable, Optional

from ..api import errors
from ..api.meta import (TypedObject, controller_ref, get_controller_of,
                        is_controlled_by)
from ..client.informer import InformerFactory, SharedInformer
from ..client.interface import Client
from ..client.record import EventRecorder
from ..client.workqueue import RateLimitingQueue

log = logging.getLogger("controller")

#: Index name mapping objects to their controller-owner uid.
OWNER_INDEX = "owner-uid"


def owner_uid_index(obj: TypedObject) -> list[str]:
    ref = get_controller_of(obj)
    return [ref.uid] if ref else []


class Controller:
    """Base reconcile loop. Subclasses implement :meth:`sync`."""

    name = "controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 1):
        self.client = client
        self.factory = factory
        self.workers = workers
        self.queue = RateLimitingQueue()
        self.recorder = EventRecorder(client, self.name)
        self._tasks: list[asyncio.Task] = []
        self._informers: list[SharedInformer] = []
        self._stopped = False

    # -- wiring -----------------------------------------------------------

    def watch(self, plural: str, indexers: Optional[dict] = None,
              resync_period: float = 0.0) -> SharedInformer:
        inf = self.factory.informer(plural, indexers=indexers,
                                    resync_period=resync_period)
        self._informers.append(inf)
        return inf

    def enqueue(self, key: str) -> None:
        if not self._stopped:
            self.queue.add_nowait(key)

    def enqueue_obj(self, obj: TypedObject) -> None:
        self.enqueue(obj.key())

    def enqueue_owner(self, obj: TypedObject, kind: str) -> None:
        """Enqueue the controller-owner of ``obj`` if it has the given kind."""
        ref = get_controller_of(obj)
        if ref and ref.kind == kind:
            ns = obj.metadata.namespace
            self.enqueue(f"{ns}/{ref.name}" if ns else ref.name)

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        for inf in self._informers:
            if inf._task is None:
                inf.start()
        for inf in self._informers:
            await inf.wait_for_sync()
        for i in range(self.workers):
            self._tasks.append(loop.create_task(self._worker(i)))
        await self.on_start()

    async def on_start(self) -> None:
        """Hook for controllers needing periodic loops (override)."""

    async def stop(self) -> None:
        self._stopped = True
        await self.queue.shut_down()
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            try:
                await task
            except asyncio.CancelledError:
                pass
        self._tasks.clear()

    async def _worker(self, i: int) -> None:
        while True:
            key = await self.queue.get()
            if key is None:
                return
            try:
                requeue_after = await self.sync(key)
                self.queue.forget(key)
                if requeue_after:
                    await self.queue.add_after(key, requeue_after)
            except asyncio.CancelledError:
                raise
            except errors.ConflictError:
                # Stale read: the informer will deliver the fresh object;
                # retry quickly without counting it as a failure.
                await self.queue.add_after(key, 0.01)
            except Exception:  # noqa: BLE001
                log.exception("%s: sync(%s) failed", self.name, key)
                await self.queue.add_rate_limited(key)
            finally:
                await self.queue.done(key)

    async def sync(self, key: str) -> Optional[float]:
        """Reconcile one object; return seconds to requeue after, or None."""
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Pod bookkeeping shared by the workload controllers
# ---------------------------------------------------------------------------


def is_pod_terminal(pod) -> bool:
    return pod.status.phase in ("Succeeded", "Failed")


def is_pod_active(pod) -> bool:
    """Counts toward replicas: not terminal, not being deleted."""
    return not is_pod_terminal(pod) and pod.metadata.deletion_timestamp is None


def is_pod_ready(pod) -> bool:
    for c in pod.status.conditions:
        if c.type == "Ready":
            return c.status == "True"
    return False


def pod_ready_since(pod, min_ready_seconds: int, now) -> bool:
    """Available = ready for at least minReadySeconds."""
    if not is_pod_ready(pod):
        return False
    if min_ready_seconds <= 0:
        return True
    for c in pod.status.conditions:
        if c.type == "Ready" and c.last_transition_time is not None:
            age = (now - c.last_transition_time).total_seconds()
            return age >= min_ready_seconds
    return False


def active_pods_to_delete_first(pods: list) -> list:
    """Deletion preference when scaling down (reference:
    ``pkg/controller/controller_utils.go ActivePods`` sort): unassigned
    before assigned, pending before running, not-ready before ready,
    higher restarts first, younger first."""

    def rank(pod):
        phase_rank = {"Pending": 0, "Unknown": 1, "Running": 2}.get(
            pod.status.phase, 2)
        restarts = sum(cs.restart_count for cs in pod.status.container_statuses)
        created = pod.metadata.creation_timestamp
        age = created.timestamp() if created else 0.0
        return (
            0 if not pod.spec.node_name else 1,
            phase_rank,
            1 if is_pod_ready(pod) else 0,
            -restarts,
            -age,
        )

    return sorted(pods, key=rank)


class PodControl:
    """Create/delete pods on behalf of a controller object (reference:
    ``pkg/controller/controller_utils.go RealPodControl``)."""

    def __init__(self, client: Client, recorder: EventRecorder):
        self.client = client
        self.recorder = recorder

    async def create_pod(self, owner: TypedObject, template, name: str = "",
                         generate_name: str = "", extra_labels=None,
                         mutate=None):
        from ..api import types as t
        from ..api.scheme import deepcopy

        pod = t.Pod(metadata=deepcopy(template.metadata),
                    spec=deepcopy(template.spec))
        pod.metadata.name = name
        pod.metadata.generate_name = generate_name or (
            "" if name else f"{owner.metadata.name}-")
        pod.metadata.namespace = owner.metadata.namespace
        pod.metadata.resource_version = ""
        pod.metadata.uid = ""
        if extra_labels:
            pod.metadata.labels = {**pod.metadata.labels, **extra_labels}
        av, kind = owner.api_version, owner.kind
        pod.metadata.owner_references = [controller_ref(owner, av, kind)]
        if mutate:
            mutate(pod)
        created = await self.client.create(pod)
        self.recorder.event(owner, "Normal", "SuccessfulCreate",
                            f"Created pod {created.metadata.name}")
        return created

    async def delete_pod(self, owner: TypedObject, pod) -> None:
        try:
            await self.client.delete("pods", pod.metadata.namespace,
                                     pod.metadata.name)
        except errors.NotFoundError:
            return
        self.recorder.event(owner, "Normal", "SuccessfulDelete",
                            f"Deleted pod {pod.metadata.name}")


def claim_pods(owner: TypedObject, selector, pods: Iterable) -> list:
    """Pods controlled by ``owner``: already-owned ones plus orphans whose
    labels match the selector (adoption is done by the caller writing the
    owner ref; here orphans are simply claimed for counting — the registry
    write happens on the next create/update)."""
    claimed = []
    for pod in pods:
        if is_controlled_by(pod, owner):
            claimed.append(pod)
            continue
        ref = get_controller_of(pod)
        if ref is None and selector is not None and \
                selector.matches(pod.metadata.labels):
            claimed.append(pod)
    return claimed


def rank_hostnames(base: str, count: int, service: str,
                   namespace: str) -> str:
    """Comma list of stable rank hostnames (``<base>-<i>[.<svc>.<ns>]``)
    for TPU_WORKER_HOSTNAMES — ONE format shared by the StatefulSet and
    Indexed-Job controllers, because :mod:`..workloads.rendezvous`
    parses it (rank order = list order; FQDN suffixing happens there)."""
    return ",".join(
        f"{base}-{i}.{service}.{namespace}" if service else f"{base}-{i}"
        for i in range(count))


def merge_container_env(containers, extra) -> None:
    """Append ``extra`` EnvVars to every container that doesn't already
    define them (user template wins over controller injection)."""
    for c in containers:
        have = {e.name for e in c.env}
        c.env = c.env + [e for e in extra if e.name not in have]
