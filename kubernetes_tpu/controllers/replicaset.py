"""ReplicaSet controller — keep N active pods matching a template.

Reference: ``pkg/controller/replicaset/replica_set.go`` (``Run :178``,
``worker :433``, ``syncReplicaSet :572``): lister read, diff desired vs
actual, create/delete via clientset, status update; watch events close
the loop.
"""
from __future__ import annotations

from typing import Optional

from ..api import errors
from ..api import types as t
from ..api import workloads as w
from ..api.meta import controller_ref, now
from ..api.scheme import deepcopy
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import (OWNER_INDEX, Controller, PodControl,
                   active_pods_to_delete_first, claim_pods, is_pod_active,
                   is_pod_ready, owner_uid_index, pod_ready_since)

#: Cap on creates/deletes per sync, so one huge RS cannot starve others
#: (reference: burstReplicas=500).
BURST_REPLICAS = 500


class ReplicaSetController(Controller):
    name = "replicaset-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 2):
        super().__init__(client, factory, workers)
        self.pod_control = PodControl(client, self.recorder)
        self.rs_informer = self.watch("replicasets")
        self.pod_informer = self.watch("pods",
                                       indexers={OWNER_INDEX: owner_uid_index})
        self.rs_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda old, new: self.enqueue_obj(new),
            on_delete=self.enqueue_obj)
        self.pod_informer.add_handlers(
            on_add=lambda pod: self.enqueue_owner(pod, "ReplicaSet"),
            on_update=lambda old, new: self.enqueue_owner(new, "ReplicaSet"),
            on_delete=lambda pod: self.enqueue_owner(pod, "ReplicaSet"))

    def _pods_for(self, rs: w.ReplicaSet) -> list[t.Pod]:
        owned = self.pod_informer.store.by_index(OWNER_INDEX, rs.metadata.uid)
        orphans = [p for p in self.pod_informer.list()
                   if p.metadata.namespace == rs.metadata.namespace
                   and not p.metadata.owner_references]
        return claim_pods(rs, rs.spec.selector, owned + orphans)

    async def _adopt(self, rs: w.ReplicaSet, pods: list[t.Pod]) -> None:
        """Write the controller owner-ref onto claimed orphans so their
        events route back here (reference: ControllerRefManager adoption)."""
        for pod in pods:
            if pod.metadata.owner_references:
                continue
            fresh = deepcopy(pod)
            fresh.metadata.owner_references = [
                controller_ref(rs, w.APPS_V1, "ReplicaSet")]
            try:
                await self.client.update(fresh)
            except (errors.ConflictError, errors.NotFoundError):
                pass  # informer will redeliver; next sync retries

    async def sync(self, key: str) -> Optional[float]:
        rs = self.rs_informer.get(key)
        if rs is None or rs.metadata.deletion_timestamp is not None:
            return None
        all_pods = self._pods_for(rs)
        await self._adopt(rs, all_pods)
        active = [p for p in all_pods if is_pod_active(p)]
        diff = rs.spec.replicas - len(active)
        if diff > 0:
            for _ in range(min(diff, BURST_REPLICAS)):
                await self.pod_control.create_pod(rs, rs.spec.template)
        elif diff < 0:
            victims = active_pods_to_delete_first(active)[: min(-diff, BURST_REPLICAS)]
            for pod in victims:
                await self.pod_control.delete_pod(rs, pod)
        await self._update_status(rs, active)
        # minReadySeconds availability matures with time, not with an event.
        if rs.spec.min_ready_seconds > 0 and diff == 0:
            ready = sum(1 for p in active if is_pod_ready(p))
            avail = sum(1 for p in active
                        if pod_ready_since(p, rs.spec.min_ready_seconds, now()))
            if ready != avail:
                return float(rs.spec.min_ready_seconds)
        return None

    async def _update_status(self, rs: w.ReplicaSet, active: list[t.Pod]) -> None:
        ts = now()
        new = w.ReplicaSetStatus(
            replicas=len(active),
            fully_labeled_replicas=sum(
                1 for p in active
                if rs.spec.selector is None
                or rs.spec.selector.matches(p.metadata.labels)),
            ready_replicas=sum(1 for p in active if is_pod_ready(p)),
            available_replicas=sum(
                1 for p in active
                if pod_ready_since(p, rs.spec.min_ready_seconds, ts)),
            observed_generation=rs.metadata.generation,
        )
        if new == rs.status:
            return
        fresh = w.ReplicaSet(metadata=rs.metadata, spec=rs.spec, status=new)
        try:
            await self.client.update(fresh, subresource="status")
        except errors.NotFoundError:
            pass
