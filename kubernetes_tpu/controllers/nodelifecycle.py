"""Node lifecycle controller — failure detection + elastic recovery.

Reference: ``pkg/controller/node/node_controller.go`` (``Run :555``,
``monitorNodeStatus :619``) + ``taintManager`` (``:185,307-333``):

- every tick, compare each node's heartbeat (NodeStatus Ready condition
  + its heartbeat Lease, the cheaper signal) against a grace period;
  stale nodes get Ready=Unknown and the ``unreachable`` NoExecute
  taint; Ready=False nodes get the ``not-ready`` taint;
- the taint manager evicts pods from NoExecute-tainted nodes unless
  tolerated (honoring ``toleration_seconds``); workload controllers
  then recreate them elsewhere — elasticity is emergent from
  level-triggered reconcile, exactly as in the reference.

TPU-first delta: a node whose TPU topology reports unhealthy chips gets
a ``tpu-unhealthy`` NoSchedule taint so new slices avoid it while
running gangs decide their own fate (gang restart is the Job
controller's call, not the node controller's).
"""
from __future__ import annotations

import asyncio
import time
from typing import Optional

from ..api import errors
from ..api import types as t
from ..api.meta import now
from ..api.scheme import deepcopy
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller

TAINT_TPU_UNHEALTHY = "node.tpu/tpu-unhealthy"


class NodeLifecycleController(Controller):
    name = "node-lifecycle-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 2,
                 monitor_interval: float = 5.0,
                 grace_period: float = 40.0):
        super().__init__(client, factory, workers)
        self.monitor_interval = monitor_interval
        self.grace_period = grace_period
        self.node_informer = self.watch("nodes")
        self.pod_informer = self.watch("pods")
        self.lease_informer = self.watch("leases")
        # Taint-manager reactions: pods on freshly tainted nodes. Node
        # status heartbeats arrive every few seconds per node, so only
        # react when the NoExecute taint set actually changed —
        # otherwise this is O(nodes * pods) steady-state churn
        # (reference taint manager diffs taints the same way).
        self.node_informer.add_handlers(
            on_add=lambda n: self._enqueue_node_pods(n),
            on_update=self._on_node_update)
        self.pod_informer.add_handlers(
            on_add=lambda p: self.enqueue(f"pod/{p.key()}"),
            on_update=lambda o, n: self.enqueue(f"pod/{n.key()}"))
        self._monitor_task: Optional[asyncio.Task] = None
        #: pod key -> scheduled eviction task (tolerationSeconds timers).
        self._evictions: dict[str, asyncio.Task] = {}
        #: pod key -> monotonic time its eviction was first
        #: PDB-blocked (escalation clock, see _evict).
        self._pdb_blocked: dict[str, float] = {}

    # -- lifecycle --------------------------------------------------------

    async def on_start(self) -> None:
        self._monitor_task = asyncio.get_running_loop().create_task(
            self._monitor_loop())

    async def stop(self) -> None:
        if self._monitor_task:
            self._monitor_task.cancel()
            try:
                await self._monitor_task
            except asyncio.CancelledError:
                pass
        for task in self._evictions.values():
            task.cancel()
        self._evictions.clear()
        await super().stop()

    @staticmethod
    def _no_execute_taints(node: t.Node) -> set[tuple[str, str]]:
        return {(taint.key, taint.value) for taint in node.spec.taints
                if taint.effect == "NoExecute"}

    def _on_node_update(self, old: t.Node, new: t.Node) -> None:
        if self._no_execute_taints(old) != self._no_execute_taints(new):
            self._enqueue_node_pods(new)

    def _enqueue_node_pods(self, node: t.Node) -> None:
        for pod in self.pod_informer.list():
            if pod.spec.node_name == node.metadata.name:
                self.enqueue(f"pod/{pod.key()}")

    # -- monitorNodeStatus -------------------------------------------------

    async def _monitor_loop(self) -> None:
        while True:
            try:
                await self._monitor_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                import logging
                logging.getLogger("controller").exception(
                    "node monitor pass failed")
            await asyncio.sleep(self.monitor_interval)

    def _heartbeat_of(self, node: t.Node):
        ready = t.get_node_condition(node.status, t.NODE_READY)
        beats = []
        if ready is not None and ready.last_heartbeat_time is not None:
            beats.append(ready.last_heartbeat_time)
        lease = self.lease_informer.get(
            f"kube-system/node-{node.metadata.name}")
        if lease is not None and lease.spec.renew_time is not None:
            beats.append(lease.spec.renew_time)
        return max(beats) if beats else None

    async def _monitor_once(self) -> None:
        ts = now()
        for node in self.node_informer.list():
            ready = t.get_node_condition(node.status, t.NODE_READY)
            beat = self._heartbeat_of(node)
            stale = (beat is None
                     or (ts - beat).total_seconds() > self.grace_period)
            # Taints reconcile every tick (a swallowed write conflict on
            # one pass self-heals on the next — level-triggered).
            if stale:
                if ready is None or ready.status != "Unknown":
                    await self._mark_unknown(node)
                await self._set_taints(node, unreachable=True)
            elif ready is not None and ready.status == "False":
                await self._set_taints(node, not_ready=True)
            else:
                # Fresh heartbeat with Ready True, Unknown, or absent
                # (e.g. lease renewals resumed before the agent reposted
                # status): clear lifecycle taints unconditionally so a
                # healthy node stops evicting pods.
                await self._set_taints(node)

    async def _mark_unknown(self, node: t.Node) -> None:
        fresh = deepcopy(node)
        ready = t.get_node_condition(fresh.status, t.NODE_READY)
        if ready is None:
            ready = t.NodeCondition(type=t.NODE_READY)
            fresh.status.conditions.append(ready)
        ready.status = "Unknown"
        ready.reason = "NodeStatusUnknown"
        ready.message = "node agent stopped posting status"
        ready.last_transition_time = now()
        try:
            await self.client.update(fresh, subresource="status")
            self.recorder.event(node, "Warning", "NodeNotReady",
                                f"node {node.metadata.name} heartbeat stale")
        except (errors.ConflictError, errors.NotFoundError):
            pass

    async def _set_taints(self, node: t.Node, unreachable: bool = False,
                          not_ready: bool = False) -> None:
        """Reconcile lifecycle taints; TPU health taint rides along."""
        managed = {t.TAINT_NODE_UNREACHABLE: unreachable,
                   t.TAINT_NODE_NOT_READY: not_ready,
                   TAINT_TPU_UNHEALTHY: self._tpu_unhealthy(node)}
        current = {taint.key for taint in node.spec.taints
                   if taint.key in managed}
        desired = {key for key, on in managed.items() if on}
        if current == desired:
            return
        fresh = deepcopy(node)
        fresh.spec.taints = [taint for taint in fresh.spec.taints
                             if taint.key not in managed]
        for key in desired:
            effect = ("NoSchedule" if key == TAINT_TPU_UNHEALTHY
                      else "NoExecute")
            fresh.spec.taints.append(
                t.Taint(key=key, effect=effect, time_added=now()))
        try:
            await self.client.update(fresh)
        except (errors.ConflictError, errors.NotFoundError):
            pass

    @staticmethod
    def _tpu_unhealthy(node: t.Node) -> bool:
        topo = node.status.tpu
        if topo is None or not topo.chips:
            return False
        return any(c.health != t.TPU_HEALTHY for c in topo.chips)

    # -- taint manager (NoExecute eviction) --------------------------------

    async def sync(self, key: str) -> Optional[float]:
        if not key.startswith("pod/"):
            return None
        pod_key = key[len("pod/"):]
        pod = self.pod_informer.get(pod_key)
        if pod is None or pod.metadata.deletion_timestamp is not None \
                or not pod.spec.node_name:
            self._cancel_eviction(pod_key)
            return None
        node = self.node_informer.get(pod.spec.node_name)
        if node is None:
            return None
        no_execute = [taint for taint in node.spec.taints
                      if taint.effect == "NoExecute"]
        if not no_execute:
            self._cancel_eviction(pod_key)
            return None
        # Tolerated forever? tolerationSeconds bounds the stay.
        delays = []
        for taint in no_execute:
            tols = [tol for tol in pod.spec.tolerations if tol.tolerates(taint)]
            if not tols:
                delays.append(0.0)
                continue
            secs = [tol.toleration_seconds for tol in tols
                    if tol.toleration_seconds is not None]
            if secs:
                base = taint.time_added or now()
                remaining = max(secs) - (now() - base).total_seconds()
                delays.append(max(remaining, 0.0))
            # else: tolerated indefinitely — no delay entry.
        if not delays:
            self._cancel_eviction(pod_key)
            return None
        delay = min(delays)
        if delay <= 0:
            await self._evict(pod)
        else:
            self._schedule_eviction(pod_key, delay)
        return None

    #: How long taint eviction respects a blocking PDB before
    #: escalating: a NoExecute-tainted node is (or is about to be)
    #: gone, so after this grace the disruption is involuntary — the
    #: override still records accounting in the budget.
    PDB_ESCALATE_S = 120.0

    async def _evict(self, pod: t.Pod) -> None:
        # Keep the escalation clock: this is a RETRY of an eviction in
        # progress, not a cancellation.
        self._cancel_eviction(pod.key(), reset_clock=False)
        self.recorder.event(pod, "Warning", "TaintEviction",
                            f"evicting pod from {pod.spec.node_name}")
        try:
            await self.client.evict(
                pod.metadata.namespace, pod.metadata.name,
                t.Eviction(override_budget=self._escalated(pod)))
            self._pdb_blocked.pop(pod.key(), None)
        except errors.NotFoundError:
            self._pdb_blocked.pop(pod.key(), None)
        except errors.TooManyRequestsError as e:
            # Only a BUDGET refusal (details.cause, stamped by the
            # eviction subresource) advances the escalation clock — an
            # apiserver max-in-flight 429 under overload must never
            # convert into a budget override.
            if e.details.get("cause") != "DisruptionBudget":
                self._schedule_eviction(pod.key(), 10.0)
                return
            self._note_pdb_blocked(pod, "a PodDisruptionBudget")
        except errors.ServiceUnavailableError as e:
            # Ambiguous coverage (>1 PDB) is a 503 from the eviction
            # subresource, marked by details.cause. Only THAT 503
            # starts the escalation clock — a generic 503 (apiserver
            # draining, proxy hiccup) escalating into override_budget
            # would punch through healthy budgets, the exact failure
            # the 429 path's cause check prevents. The pod still sits
            # on a NoExecute-tainted node, so after PDB_ESCALATE_S the
            # retry goes out with override_budget, which records in
            # EVERY covering budget instead of gating.
            if e.details.get("cause") != "DisruptionBudget":
                self._schedule_eviction(pod.key(), 10.0)
                return
            self._note_pdb_blocked(pod, "overlapping PodDisruptionBudgets")

    def _note_pdb_blocked(self, pod: t.Pod, why: str) -> None:
        # Budget says no: note when we first asked and retry —
        # voluntary for PDB_ESCALATE_S, involuntary after.
        self._pdb_blocked.setdefault(pod.key(), time.monotonic())
        self.recorder.event(
            pod, "Warning", "TaintEvictionBlocked",
            f"eviction blocked by {why}; will "
            f"escalate in {self.PDB_ESCALATE_S:.0f}s")
        self._schedule_eviction(pod.key(), 10.0)

    def _escalated(self, pod: t.Pod) -> bool:
        first = self._pdb_blocked.get(pod.key())
        return (first is not None
                and time.monotonic() - first >= self.PDB_ESCALATE_S)

    def _schedule_eviction(self, pod_key: str, delay: float) -> None:
        if pod_key in self._evictions:
            return

        async def later():
            await asyncio.sleep(delay)
            self._evictions.pop(pod_key, None)
            self.enqueue(f"pod/{pod_key}")

        self._evictions[pod_key] = asyncio.get_running_loop().create_task(
            later())

    def _cancel_eviction(self, pod_key: str, reset_clock: bool = True) -> None:
        task = self._evictions.pop(pod_key, None)
        if task:
            task.cancel()
        if reset_clock:
            # The pod is no longer under taint eviction (taint cleared,
            # pod gone/tolerating): a stale escalation stamp must not
            # let a FUTURE same-named pod punch through its PDB.
            self._pdb_blocked.pop(pod_key, None)
