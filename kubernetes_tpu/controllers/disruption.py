"""Disruption controller — PodDisruptionBudget status.

Reference: ``pkg/controller/disruption``: keep
``status.disruptions_allowed`` current so voluntary evictions (drain)
can be admission-checked against it. For a gang-scheduled training job
a PDB with min_available == gang size means "never voluntarily break
the gang".
"""
from __future__ import annotations

from typing import Optional

from ..api import errors
from ..api import types as t
from ..api import workloads as w
from ..api.scheme import deepcopy
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller, is_pod_active, is_pod_ready


class DisruptionController(Controller):
    name = "disruption-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 1):
        super().__init__(client, factory, workers)
        self.pdb_informer = self.watch("poddisruptionbudgets")
        self.pod_informer = self.watch("pods")
        self.pdb_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n))
        self.pod_informer.add_handlers(
            on_add=lambda p: self._enqueue_matching(p),
            on_update=lambda o, n: self._enqueue_matching(n),
            on_delete=lambda p: self._enqueue_matching(p))

    def _enqueue_matching(self, pod: t.Pod) -> None:
        for pdb in self.pdb_informer.list():
            if pdb.metadata.namespace != pod.metadata.namespace:
                continue
            if pdb.spec.selector is None or \
                    pdb.spec.selector.matches(pod.metadata.labels):
                self.enqueue_obj(pdb)

    #: disrupted_pods entries older than this are dropped — an
    #: approved eviction whose deleter crashed must not pin the budget
    #: forever (reference: DeletionTimeout, disruption.go).
    DISRUPTION_TIMEOUT_S = 120.0

    async def sync(self, key: str) -> Optional[float]:
        pdb = self.pdb_informer.get(key)
        if pdb is None:
            return None
        pods = [p for p in self.pod_informer.list()
                if p.metadata.namespace == pdb.metadata.namespace
                and (pdb.spec.selector is None
                     or pdb.spec.selector.matches(p.metadata.labels))
                and is_pod_active(p)]
        expected = len(pods)
        # Eviction-approved pods (disrupted_pods, stamped by the
        # eviction subresource) count as already-gone even while the
        # delete is in flight — otherwise N callers could each pass
        # the allowed check against the same healthy count. Entries
        # expire (crashed deleter) or clear when the pod is deleted
        # or observed running-and-ready again past its stamp.
        from ..api.meta import now as meta_now, parse_stamp
        ts = meta_now()
        active_names = {p.metadata.name for p in pods}
        disrupted = {}
        for pod_name, stamp in pdb.status.disrupted_pods.items():
            if pod_name not in active_names:
                continue  # deleted: entry served its purpose
            try:
                t0 = parse_stamp(stamp)
            except ValueError:
                continue
            if (ts - t0).total_seconds() < self.DISRUPTION_TIMEOUT_S:
                disrupted[pod_name] = stamp
        healthy = sum(1 for p in pods
                      if is_pod_ready(p)
                      and p.metadata.name not in disrupted)
        if pdb.spec.min_available is not None:
            desired_healthy = pdb.spec.min_available
        elif pdb.spec.max_unavailable is not None:
            desired_healthy = max(expected - pdb.spec.max_unavailable, 0)
        else:
            desired_healthy = expected
        allowed = max(healthy - desired_healthy, 0)
        new = w.PodDisruptionBudgetStatus(
            disruptions_allowed=allowed, current_healthy=healthy,
            desired_healthy=desired_healthy, expected_pods=expected,
            observed_generation=pdb.metadata.generation,
            disrupted_pods=disrupted)
        # With in-flight disruptions, ALWAYS come back (even when the
        # status is unchanged this tick) — a crashed deleter's entry
        # expires only if someone re-examines it.
        requeue = (self.DISRUPTION_TIMEOUT_S / 2) if disrupted else None
        if new == pdb.status:
            return requeue
        fresh = deepcopy(pdb)
        fresh.status = new
        try:
            await self.client.update(fresh, subresource="status")
        except (errors.ConflictError, errors.NotFoundError):
            pass
        return requeue
