"""PersistentVolume binder — static binding + host-path provisioning.

Reference: ``pkg/controller/volume/persistentvolume`` — the PV binder
matches pending claims to Available volumes (capacity, access modes,
storage class), binds both sides, and releases/deletes volumes when
claims go away; dynamic provisioning creates volumes on demand via the
storage class's provisioner. Here the one in-tree provisioner is
host-path (``PROVISIONER_HOSTPATH``) — the local-up/dev posture; real
deployments would add drivers behind the same seam.

Crash recovery: the PV's ``claim_ref`` is the single source of binding
truth. A half-finished bind (claim_ref set, PVC not yet updated) is
completed on the next sync because the claim looks for a PV already
reserved for it before matching fresh ones; a periodic reconcile pass
releases Bound PVs whose claim vanished while the controller was down.
"""
from __future__ import annotations

import asyncio
import os
import shutil
import uuid
from typing import Optional

from ..api import errors, types as t
from ..api.meta import ObjectMeta
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller


def _storage(quantities: dict) -> float:
    return t.parse_quantity(quantities.get("storage", 0.0))


def _pv_matches(pv: t.PersistentVolume, pvc: t.PersistentVolumeClaim) -> bool:
    if pv.status.phase != t.PV_AVAILABLE or pv.spec.claim_ref is not None:
        return False
    if pv.spec.storage_class_name != pvc.spec.storage_class_name:
        return False
    if not set(pvc.spec.access_modes) <= set(pv.spec.access_modes):
        return False
    return _storage(pv.spec.capacity) >= \
        _storage(pvc.spec.resources.requests)


class PersistentVolumeBinder(Controller):
    name = "persistentvolume-binder"

    def __init__(self, client: Client, factory: InformerFactory,
                 provision_dir: str = "", workers: int = 1,
                 resync_seconds: float = 30.0):
        super().__init__(client, factory, workers)
        self.provision_dir = provision_dir or "/tmp/ktpu-pv"
        self.resync_seconds = resync_seconds
        self.pvc_informer = self.watch("persistentvolumeclaims")
        self.pv_informer = self.watch("persistentvolumes")
        self.sc_informer = self.watch("storageclasses")
        self.pvc_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n),
            on_delete=self._pvc_gone)
        # Only transitions that can UNBLOCK a claim re-enqueue pending
        # claims — the binder's own per-bind writes must not trigger
        # O(claims^2) churn during a provisioning burst.
        self.pv_informer.add_handlers(
            on_add=lambda pv: self._enqueue_pending_claims(),
            on_update=lambda o, n: (
                self._enqueue_pending_claims()
                if (o.status.phase != t.PV_AVAILABLE
                    and n.status.phase == t.PV_AVAILABLE)
                or (o.spec.claim_ref is not None
                    and n.spec.claim_ref is None) else None))
        self._resync_task: Optional[asyncio.Task] = None

    async def on_start(self) -> None:
        self._resync_task = asyncio.get_running_loop().create_task(
            self._resync_loop())

    async def stop(self) -> None:
        if self._resync_task:
            self._resync_task.cancel()
            try:
                await self._resync_task
            except asyncio.CancelledError:
                pass
        await super().stop()

    def _enqueue_pending_claims(self) -> None:
        for pvc in self.pvc_informer.list():
            if pvc.status.phase != t.PVC_BOUND:
                self.enqueue_obj(pvc)

    def _pvc_gone(self, pvc: t.PersistentVolumeClaim) -> None:
        self.enqueue(f"orphan-scan::{pvc.metadata.uid}")

    async def _resync_loop(self) -> None:
        """Level-triggered safety net: deletions missed while down (the
        informer can't replay them) must still release their PVs."""
        while True:
            await asyncio.sleep(self.resync_seconds)
            self.enqueue("orphan-scan::periodic")

    async def sync(self, key: str) -> Optional[float]:
        if key.startswith("orphan-scan::"):
            await self._scan_orphaned_pvs()
            return None
        pvc = self.pvc_informer.get(key)
        if pvc is None or pvc.status.phase == t.PVC_BOUND:
            return None
        # Crash recovery: a PV already reserved for this claim wins over
        # any fresh match (a half-finished bind completes, never forks).
        pv = self._reserved_for(pvc) or self._find_pv(pvc)
        if pv is None:
            if pvc.spec.volume_name:
                # Explicitly requested volume not (yet) available: wait
                # for it — never silently provision a substitute
                # (reference: volume_name pins the claim).
                self.recorder.event(pvc, "Normal", "WaitingForVolume",
                                    f"waiting for volume "
                                    f"{pvc.spec.volume_name!r}")
                return None
            pv = await self._provision(pvc)
        if pv is None:
            self.recorder.event(pvc, "Normal", "WaitingForVolume",
                                "no matching PersistentVolume; waiting")
            return None  # a future PV add re-enqueues
        await self._bind(pv, pvc)
        return None

    def _reserved_for(self, pvc: t.PersistentVolumeClaim
                      ) -> Optional[t.PersistentVolume]:
        for pv in self.pv_informer.list():
            ref = pv.spec.claim_ref
            if ref is not None and ref.uid == pvc.metadata.uid:
                return pv
        return None

    def _find_pv(self, pvc: t.PersistentVolumeClaim
                 ) -> Optional[t.PersistentVolume]:
        if pvc.spec.volume_name:
            pv = self.pv_informer.get(pvc.spec.volume_name)
            return pv if pv is not None and _pv_matches(pv, pvc) else None
        # Smallest adequate volume first (reference: best-fit).
        candidates = [pv for pv in self.pv_informer.list()
                      if _pv_matches(pv, pvc)]
        candidates.sort(key=lambda pv: (_storage(pv.spec.capacity),
                                        pv.metadata.name))
        return candidates[0] if candidates else None

    async def _provision(self, pvc: t.PersistentVolumeClaim
                         ) -> Optional[t.PersistentVolume]:
        sc = self.sc_informer.get(pvc.spec.storage_class_name) \
            if pvc.spec.storage_class_name else None
        if sc is None or sc.provisioner != t.PROVISIONER_HOSTPATH:
            return None
        base = sc.parameters.get("base_dir", self.provision_dir)
        name = f"pvc-{pvc.metadata.uid or uuid.uuid4().hex[:12]}"
        path = os.path.join(base, name)
        os.makedirs(path, exist_ok=True)
        pv = t.PersistentVolume(
            metadata=ObjectMeta(name=name,
                                annotations={"pv.kubernetes-tpu/provisioned-by":
                                             sc.provisioner}),
            spec=t.PersistentVolumeSpec(
                capacity={"storage": _storage(pvc.spec.resources.requests)},
                access_modes=list(pvc.spec.access_modes),
                storage_class_name=pvc.spec.storage_class_name,
                host_path=t.HostPathVolume(path=path),
                persistent_volume_reclaim_policy=sc.reclaim_policy))
        try:
            created = await self.client.create(pv)
        except errors.AlreadyExistsError:
            created = await self.client.get("persistentvolumes", "", name)
        self.recorder.event(pvc, "Normal", "Provisioned",
                            f"created volume {name} at {path}")
        return created

    async def _bind(self, pv: t.PersistentVolume,
                    pvc: t.PersistentVolumeClaim) -> None:
        # PV side first (claim_ref is the lock against double-bind),
        # then the claim. Each step is idempotent, so a crash or
        # conflict anywhere resumes via _reserved_for on the next sync.
        cur_pv = await self.client.get("persistentvolumes", "",
                                       pv.metadata.name)
        if cur_pv.spec.claim_ref is None:
            cur_pv.spec.claim_ref = t.ObjectReference(
                kind="PersistentVolumeClaim",
                namespace=pvc.metadata.namespace,
                name=pvc.metadata.name, uid=pvc.metadata.uid)
            cur_pv = await self.client.update(cur_pv)
        elif cur_pv.spec.claim_ref.uid != pvc.metadata.uid:
            return  # raced another claim; re-sync finds the next PV
        if cur_pv.status.phase != t.PV_BOUND:
            cur_pv.status.phase = t.PV_BOUND
            await self.client.update_status(cur_pv)

        cur = await self.client.get("persistentvolumeclaims",
                                    pvc.metadata.namespace, pvc.metadata.name)
        if cur.spec.volume_name != pv.metadata.name:
            cur.spec.volume_name = pv.metadata.name
            cur = await self.client.update(cur)
        if cur.status.phase != t.PVC_BOUND:
            cur.status.phase = t.PVC_BOUND
            cur.status.capacity = dict(pv.spec.capacity)
            await self.client.update_status(cur)
            self.recorder.event(cur, "Normal", "Bound",
                                f"bound to volume {pv.metadata.name}")

    # -- release path ------------------------------------------------------

    async def _scan_orphaned_pvs(self) -> None:
        """Release every PV bound to a claim that no longer exists.
        Driven by both PVC delete events and the periodic resync, so
        deletions missed while the controller was down still converge."""
        claims_by_uid = {pvc.metadata.uid: pvc
                         for pvc in self.pvc_informer.list()}
        for pv in self.pv_informer.list():
            ref = pv.spec.claim_ref
            if ref is None or ref.uid in claims_by_uid:
                continue
            try:
                got = await self.client.get("persistentvolumeclaims",
                                            ref.namespace, ref.name)
                if got.metadata.uid == ref.uid:
                    continue  # truly live; informer lag
                # Same name, NEW claim: the bound one is still gone.
            except errors.NotFoundError:
                pass
            await self._release_pv(pv)

    async def _release_pv(self, pv: t.PersistentVolume) -> None:
        if pv.spec.persistent_volume_reclaim_policy == t.RECLAIM_DELETE:
            # Delete the API object FIRST; only scrub data once the
            # object is actually gone (an admission/authz rejection must
            # not orphan a live PV with destroyed backing data).
            try:
                await self.client.delete("persistentvolumes", "",
                                         pv.metadata.name)
            except errors.NotFoundError:
                pass
            except errors.StatusError:
                return  # retried by the next orphan scan
            if pv.spec.host_path and pv.metadata.annotations.get(
                    "pv.kubernetes-tpu/provisioned-by"):
                shutil.rmtree(pv.spec.host_path.path, ignore_errors=True)
            return
        # Retain: one spec write clearing the ref, one status write to
        # Released. If the second fails, the next scan cannot see the
        # dangling ref anymore — so flip the STATUS first.
        cur = await self.client.get("persistentvolumes", "", pv.metadata.name)
        if cur.status.phase != t.PV_RELEASED:
            cur.status.phase = t.PV_RELEASED
            cur = await self.client.update_status(cur)
        if cur.spec.claim_ref is not None:
            cur.spec.claim_ref = None
            await self.client.update(cur)