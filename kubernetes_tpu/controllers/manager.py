"""Controller manager — one process running every controller.

Reference: ``cmd/kube-controller-manager/app/controllermanager.go``
(``Run :106`` leader-elected at ``:154``; ``NewControllerInitializers
:332`` the controller table; ``StartControllers :463``). All
controllers share one informer factory (one watch per resource, not
one per controller) and stop together when leadership is lost —
crash-only: a restarted manager relists and converges.
"""
from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Callable, Optional

from ..client.informer import InformerFactory
from ..client.interface import Client
from ..client.leaderelection import LeaderElector
from .base import Controller
from .cronjob import CronJobController
from .daemonset import DaemonSetController
from .deployment import DeploymentController
from .disruption import DisruptionController
from .endpoints import EndpointsController
from .garbagecollector import GarbageCollector
from .hpa import HorizontalPodAutoscalerController
from .job import JobController
from .namespace import NamespaceController
from .nodeipam import NodeIpamController
from .nodelifecycle import NodeLifecycleController
from .podgc import PodGCController
from .queue import QueueController
from .replicaset import ReplicaSetController
from .resourcequota import ResourceQuotaController
from .serviceaccount import ServiceAccountController
from .statefulset import StatefulSetController
from .ttl import TTLController
from .volume import PersistentVolumeBinder

log = logging.getLogger("controller-manager")

#: Hard ceiling on how long stop() waits for the run task to honor
#: cancellation before abandoning it — teardown is bounded by a real
#: deadline, never by a wedged controller.
STOP_GRACE_SECONDS = 30.0

#: The controller table (reference: NewControllerInitializers).
DEFAULT_CONTROLLERS: dict[str, Callable[[Client, InformerFactory], Controller]] = {
    "replicaset": ReplicaSetController,
    "deployment": DeploymentController,
    "statefulset": StatefulSetController,
    "daemonset": DaemonSetController,
    "job": JobController,
    "cronjob": CronJobController,
    "node-lifecycle": NodeLifecycleController,
    "node-ipam": NodeIpamController,
    "persistentvolume-binder": PersistentVolumeBinder,
    "serviceaccount": ServiceAccountController,
    "podgc": PodGCController,
    "garbage-collector": GarbageCollector,
    "namespace": NamespaceController,
    "endpoints": EndpointsController,
    "resourcequota": ResourceQuotaController,
    "horizontal-pod-autoscaler": HorizontalPodAutoscalerController,
    "disruption": DisruptionController,
    "ttl": TTLController,
    # Gang admission (queueing/): inert unless the JobQueueing gate is
    # on — it then suspends/admits PodGroups by tenant fair share.
    "job-queueing": QueueController,
}


def _inference_controller(client, factory, **kw):
    # Lazy like the monitor: serving/ is only paid for when built.
    from .inference import InferenceServiceController
    return InferenceServiceController(client, factory, **kw)


#: Inference serving (serving/v1): reconcile InferenceServices into a
#: headless Service + model-server Deployment and autoscale them on
#: the cluster monitor's rollups; inert unless the InferenceAutoscaling
#: gate is on.
DEFAULT_CONTROLLERS["inference"] = _inference_controller


def _train_controller(client, factory, **kw):
    # Lazy like the monitor: training/ machinery is only paid for when
    # built (the controller is inert with the TrainJobController gate
    # off).
    from .train import TrainJobController
    return TrainJobController(client, factory, **kw)


#: Multi-host training (training/v1): reconcile TrainJobs into a
#: headless Service + PodGroup + indexed trainer pod set with gang
#: recovery + checkpoint resume; inert unless the TrainJobController
#: gate is on.
DEFAULT_CONTROLLERS["train"] = _train_controller


def _cluster_monitor(client, factory, **kw):
    # Imported lazily: monitoring/ pulls in aiohttp-scrape machinery a
    # controller-only process may never use.
    from ..monitoring.aggregator import ClusterMonitor
    return ClusterMonitor(client, factory, **kw)


#: metrics-server analog (monitoring/aggregator.py): rolls node /stats
#: into tpu_cluster_*/tpu_node_* series; inert unless the
#: ClusterMonitoring gate is on.
DEFAULT_CONTROLLERS["cluster-monitor"] = _cluster_monitor


def _migration_controller(client, factory, **kw):
    # Lazy like the monitor: migration machinery is only paid for when
    # built (the controller is inert with the GangLiveMigration gate
    # off).
    from .migrate import MigrationController
    return MigrationController(client, factory, **kw)


#: Live gang migration + defragmentation (controllers/migrate.py):
#: reserve-then-move gangs off degraded nodes and consolidate small
#: gangs for large pending ones; inert unless the GangLiveMigration
#: gate is on.
DEFAULT_CONTROLLERS["migration"] = _migration_controller


def _metrics_pipeline(client, factory, **kw):
    # Lazy like the monitor: kmon machinery is only paid for when the
    # ClusterMetricsPipeline gate is on (the controller is inert off).
    from ..monitoring.pipeline import MetricsPipeline
    return MetricsPipeline(client, factory, **kw)


#: kmon Prometheus-analog pipeline (monitoring/pipeline.py): scrape
#: manager -> bounded TSDB -> PromQL-lite -> recording/alerting rules;
#: inert unless the ClusterMetricsPipeline gate is on.
DEFAULT_CONTROLLERS["metrics-pipeline"] = _metrics_pipeline


class ControllerManager:
    def __init__(self, client: Client, controllers: Optional[list[str]] = None,
                 leader_elect: bool = False, identity: str = "",
                 node_scrape_ssl=None, queueing_fits_probe=None,
                 migration_cache_probe=None,
                 migration_interval: float = 5.0,
                 monitor_interval: float = 10.0,
                 autoscale_interval: float = 2.0,
                 metrics_interval: float = 5.0,
                 apiserver_urls=(), component_urls=()):
        self.client = client
        #: Cluster credentials for scraping TLS node servers (the HPA's
        #: real metrics pipeline); the composer wires CA + identity.
        self.node_scrape_ssl = node_scrape_ssl
        #: Backfill placement probe for the queue controller (the
        #: single-binary composer wires the live scheduler cache so
        #: backfill only jumps when a free box actually exists).
        self.queueing_fits_probe = queueing_fits_probe
        #: Live-scheduler-cache probe for the migration controller —
        #: reserve-then-move needs the real cache (reservations + slice
        #: geometry); without it the controller does nothing.
        self.migration_cache_probe = migration_cache_probe
        self.migration_interval = migration_interval
        #: Cluster-monitor sweep cadence + inference autoscaler tick
        #: (smokes shorten both; production keeps the defaults).
        self.monitor_interval = monitor_interval
        self.autoscale_interval = autoscale_interval
        #: kmon scrape/rule-evaluation cadence + the scrape targets the
        #: composer knows about (apiserver URLs incl. HA replicas;
        #: (job, url) pairs for component metrics listeners). Only read
        #: when the ClusterMetricsPipeline gate is on.
        self.metrics_interval = metrics_interval
        self.apiserver_urls = list(apiserver_urls)
        self.component_urls = list(component_urls)
        #: The manager's own /metrics listener (metrics/http.py),
        #: started with the controllers when the pipeline gate is on so
        #: the scrape manager reaches controller-side series the same
        #: way it reaches the scheduler's.
        self.metrics_listener = None
        self.names = list(controllers or DEFAULT_CONTROLLERS)
        self.leader_elect = leader_elect
        self.identity = identity or f"cm-{uuid.uuid4().hex[:8]}"
        self.factory: Optional[InformerFactory] = None
        self.controllers: list[Controller] = []
        self._run_task: Optional[asyncio.Task] = None
        self._elector: Optional[LeaderElector] = None

    def _ctor_kwargs(self, name: str) -> dict:
        """Composer-supplied per-controller configuration; keeps the
        construction loop uniform."""
        if name == "horizontal-pod-autoscaler" \
                and self.node_scrape_ssl is not None:
            from .hpa import SummaryMetricsSource
            return {"metrics": SummaryMetricsSource(
                self.client, ssl_context=self.node_scrape_ssl)}
        if name == "job-queueing" and self.queueing_fits_probe is not None:
            return {"fits_probe": self.queueing_fits_probe}
        if name == "migration":
            kw = {"interval": self.migration_interval}
            if self.migration_cache_probe is not None:
                kw["cache_probe"] = self.migration_cache_probe
            return kw
        if name == "cluster-monitor":
            kw = {"interval": self.monitor_interval}
            if self.node_scrape_ssl is not None:
                kw["ssl_context"] = self.node_scrape_ssl
            return kw
        if name == "inference":
            return {"autoscale_interval": self.autoscale_interval,
                    "max_snapshot_age": max(3 * self.monitor_interval, 10.0)}
        if name == "metrics-pipeline":
            urls = list(self.component_urls)
            if self.metrics_listener is not None \
                    and self.metrics_listener.url:
                urls.append(("controller-manager",
                             self.metrics_listener.url))
            kw = {"interval": self.metrics_interval,
                  "apiserver_urls": self.apiserver_urls,
                  "component_urls": urls}
            if self.node_scrape_ssl is not None:
                kw["ssl_context"] = self.node_scrape_ssl
            return kw
        return {}

    def get_controller(self, name: str):
        """A running controller by its table name, or None — the
        composer's seam for wiring debug surfaces (the apiserver's
        /debug/v1/query reads the metrics-pipeline through this)."""
        for c in self.controllers:
            if getattr(c, "name", "") == name:
                return c
        return None

    async def _run_controllers(self) -> None:
        """Build fresh controllers + informers (a re-elected manager must
        relist, not trust caches from a previous term)."""
        from ..util.features import GATES
        if GATES.enabled("ClusterMetricsPipeline") \
                and self.metrics_listener is None:
            from ..metrics.http import MetricsListener
            self.metrics_listener = MetricsListener(port=0)
            await self.metrics_listener.start()
        self.factory = InformerFactory(self.client)
        self.controllers = [
            DEFAULT_CONTROLLERS[name](self.client, self.factory,
                                      **self._ctor_kwargs(name))
            for name in self.names]
        # The inference autoscaler reads the CO-LOCATED monitor's
        # latest() snapshot (the custom-metrics seam) — wired after
        # construction because both live in this manager's table.
        monitor = next((c for c in self.controllers
                        if getattr(c, "name", "") == "cluster-monitor"),
                       None)
        for c in self.controllers:
            if getattr(c, "name", "") == "inference-controller" \
                    and getattr(c, "metrics_feed", None) is None \
                    and monitor is not None:
                c.metrics_feed = monitor.latest
        # The kmon pipeline records the CO-LOCATED monitor's rollups
        # into its TSDB (the latest()/query-surface consistency
        # contract) — same post-construction wiring as the autoscaler.
        for c in self.controllers:
            if getattr(c, "name", "") == "metrics-pipeline" \
                    and getattr(c, "monitor", None) is None \
                    and monitor is not None:
                c.monitor = monitor
        for c in self.controllers:
            await c.start()
        log.info("controller-manager: %d controllers running",
                 len(self.controllers))
        try:
            await asyncio.Event().wait()  # run until cancelled
        finally:
            await self._teardown()

    async def _teardown(self) -> None:
        for c in self.controllers:
            try:
                await c.stop()
            except Exception:  # noqa: BLE001
                log.exception("controller stop failed")
        if self.factory is not None:
            await self.factory.stop_all()
        self.controllers = []
        if self.metrics_listener is not None:
            await self.metrics_listener.stop()
            self.metrics_listener = None

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        if self.leader_elect:
            self._elector = LeaderElector(self.client, "controller-manager",
                                          self.identity)
            self._run_task = loop.create_task(
                self._elector.run(self._run_controllers))
        else:
            self._run_task = loop.create_task(self._run_controllers())

    async def stop(self) -> None:
        if self._run_task:
            # Bounded, re-cancelling wait (util/tasks.cancel_task): a
            # stop() racing controller STARTUP can lose its first
            # cancellation to CPython's wait_for swallow (GH-86296)
            # inside informer.wait_for_sync — the manager then parks on
            # its run-forever wait with the cancel consumed, and a
            # plain await here hung e2e teardown for minutes.
            from ..util.tasks import cancel_task
            await cancel_task(self._run_task, grace=STOP_GRACE_SECONDS,
                              name="controller-manager")
            self._run_task = None
        # _run_controllers' finally handles teardown when cancelled inside
        # the wait; if cancellation landed elsewhere, sweep again.
        if self.controllers:
            await self._teardown()
