"""Job controller — gang-aware batch execution.

Reference: ``pkg/controller/job`` (0.9k LoC): track active/succeeded/
failed pods, respect parallelism/completions/backoffLimit/
activeDeadlineSeconds, flip Complete/Failed conditions.

TPU-first delta (no reference analog — SURVEY.md section 2.4): when
``spec.gang`` is set the controller materializes a :class:`PodGroup`
before any pod, links every pod to it via ``pod.spec.gang``, and
**fails/restarts members as a unit**: one failed member tears down the
whole gang and the next sync recreates it (counted against
backoffLimit) — the elastic-recovery semantic a multi-host JAX job
needs (a training step cannot survive a missing worker).
"""
from __future__ import annotations

from typing import Optional

from ..api import errors
from ..api import types as t
from ..api import workloads as w
from ..api.meta import controller_ref, is_controlled_by, now
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import (Controller, PodControl, is_pod_active,
                   merge_container_env, rank_hostnames)

JOB_NAME_LABEL = "job.tpu/name"
COMPLETION_INDEX_LABEL = "job.tpu/completion-index"


def _group_name(job: w.Job) -> str:
    return f"job-{job.metadata.name}"


class JobController(Controller):
    name = "job-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 2):
        super().__init__(client, factory, workers)
        self.pod_control = PodControl(client, self.recorder)
        #: Group keys whose teardown reached a terminal verdict
        #: (deleted / unqueued / already gone) — every later resync of
        #: the finished Job would otherwise re-issue the probing GET
        #: forever. FIFO-pruned; a miss just pays one GET.
        self._group_torn_down: dict[str, None] = {}
        self.job_informer = self.watch("jobs")
        self.pod_informer = self.watch("pods")
        self.job_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n),
            on_delete=self.enqueue_obj)
        self.pod_informer.add_handlers(
            on_add=lambda p: self.enqueue_owner(p, "Job"),
            on_update=lambda o, n: self.enqueue_owner(n, "Job"),
            on_delete=lambda p: self.enqueue_owner(p, "Job"))

    def _pods_for(self, job: w.Job) -> list[t.Pod]:
        return [p for p in self.pod_informer.list()
                if p.metadata.namespace == job.metadata.namespace
                and is_controlled_by(p, job)]

    def _finished(self, job: w.Job) -> Optional[str]:
        for c in job.status.conditions:
            if c.type in ("Complete", "Failed") and c.status == "True":
                return c.type
        return None

    # -- gang -------------------------------------------------------------

    async def _ensure_podgroup(self, job: w.Job) -> None:
        gang = job.spec.gang
        name = _group_name(job)
        try:
            await self.client.get("podgroups", job.metadata.namespace, name)
            return
        except errors.NotFoundError:
            pass
        group = t.PodGroup(
            metadata=t.ObjectMeta(
                name=name, namespace=job.metadata.namespace,
                owner_references=[controller_ref(job, w.BATCH_V1, "Job")]),
            spec=t.PodGroupSpec(
                min_member=gang.min_member or job.spec.parallelism,
                slice_shape=list(gang.slice_shape),
                schedule_timeout_seconds=gang.schedule_timeout_seconds,
                queue=gang.queue,
                min_replicas=gang.min_replicas,
                max_replicas=gang.max_replicas))
        if gang.checkpoint_grace_seconds > 0:
            # Graceful-preemption opt-in rides the Job spec: the gang
            # checkpoints (and elastic gangs shrink) instead of dying.
            group.spec.checkpoint = t.CheckpointSpec(
                grace_seconds=gang.checkpoint_grace_seconds)
        from ..util.features import GATES
        if job.spec.active_deadline_seconds \
                and GATES.enabled("JobQueueing"):
            # Projected runtime for the admission backfill pass
            # (queueing/fairshare.py shadow-time check). Gated: with
            # JobQueueing off the created PodGroup must be
            # byte-identical to the ungated build.
            from ..api.queueing import RUNTIME_ANNOTATION
            group.metadata.annotations[RUNTIME_ANNOTATION] = str(
                job.spec.active_deadline_seconds)
        try:
            await self.client.create(group)
        except errors.AlreadyExistsError:
            pass

    # -- pod creation -----------------------------------------------------

    def _mutator(self, job: w.Job, index: int):
        def mutate(pod: t.Pod) -> None:
            pod.metadata.labels = {**pod.metadata.labels,
                                   JOB_NAME_LABEL: job.metadata.name}
            if job.spec.completion_mode == "Indexed":
                pod.metadata.labels[COMPLETION_INDEX_LABEL] = str(index)
            if pod.spec.restart_policy == t.RESTART_ALWAYS:
                pod.spec.restart_policy = t.RESTART_NEVER
            if job.spec.gang is not None:
                pod.spec.gang = _group_name(job)
            if job.spec.completion_mode == "Indexed":
                # Stable ranks exist only in Indexed mode — NonIndexed
                # pods are interchangeable and must not all claim rank 0.
                # Stable DNS identity too (upstream Indexed Jobs set
                # hostname=$(job)-$(index) the same way): with the
                # template carrying spec.subdomain of a headless
                # Service, rank hostnames resolve via cluster DNS and
                # TPU_WORKER_HOSTNAMES lets jax.distributed bootstrap
                # with no external coordinator (workloads/rendezvous.py).
                pod.spec.hostname = f"{job.metadata.name}-{index}"
                rank_env = [
                    t.EnvVar(name="JOB_COMPLETION_INDEX", value=str(index)),
                    t.EnvVar(name="TPU_WORKER_ID", value=str(index)),
                ]
                total = job.spec.completions or job.spec.parallelism
                if pod.spec.subdomain and job.spec.parallelism >= total:
                    # Hostnames only when ALL ranks run concurrently
                    # (the gang case): with parallelism < completions a
                    # worker would wait on ranks that are never up and
                    # deadlock jax.distributed into its backoff limit.
                    rank_env.append(t.EnvVar(
                        name="TPU_WORKER_HOSTNAMES",
                        value=rank_hostnames(
                            job.metadata.name, total, pod.spec.subdomain,
                            job.metadata.namespace)))
                merge_container_env(pod.spec.containers, rank_env)
        return mutate

    async def sync(self, key: str) -> Optional[float]:
        job = self.job_informer.get(key)
        if job is None or job.metadata.deletion_timestamp is not None:
            return None
        if self._finished(job):
            # Level-triggered gang teardown: the delete in the
            # completion/failure transition can be lost (crash or
            # transient API error between the terminal condition write
            # and the delete) — re-issuing here keeps a finished gang
            # from pinning its queue quota forever. No-op when the
            # group is already gone, unqueued, or the gate is off.
            await self._delete_podgroup(job)
            return None
        pods = self._pods_for(job)
        active = [p for p in pods if is_pod_active(p)]
        # Durable, exactly-once progress accounting: terminal pods are
        # counted by UID into status, so deleting their records (pod GC,
        # gang teardown) or an informer-lagged re-sync cannot double-count
        # or rewind. The status write is resourceVersion-guarded, which
        # makes the read-modify-write safe.
        counted_s = set(job.status.counted_succeeded_uids)
        counted_f = set(job.status.counted_failed_uids)
        new_s = [p for p in pods if p.status.phase == t.POD_SUCCEEDED
                 and p.metadata.uid not in counted_s]
        new_f = [p for p in pods if p.status.phase == t.POD_FAILED
                 and p.metadata.uid not in counted_f]
        succeeded = job.status.succeeded + len(new_s)
        failed = job.status.failed + len(new_f)
        completed_indexes = set(job.status.completed_indexes)
        for p in pods:
            if p.status.phase == t.POD_SUCCEEDED:
                idx = p.metadata.labels.get(COMPLETION_INDEX_LABEL)
                if idx is not None:
                    completed_indexes.add(int(idx))
        acct = dict(
            succeeded=succeeded, failed=failed,
            counted_succeeded_uids=sorted(
                counted_s | {p.metadata.uid for p in new_s}),
            counted_failed_uids=sorted(
                counted_f | {p.metadata.uid for p in new_f}),
            completed_indexes=sorted(completed_indexes))
        completions = job.spec.completions
        requeue: Optional[float] = None

        # Deadline exceeded?
        start = job.status.start_time or job.metadata.creation_timestamp
        if job.spec.active_deadline_seconds is not None and start is not None:
            elapsed = (now() - start).total_seconds()
            if elapsed >= job.spec.active_deadline_seconds:
                await self._fail(job, active, acct, "DeadlineExceeded",
                                 "job was active longer than "
                                 f"{job.spec.active_deadline_seconds}s")
                return None
            requeue = job.spec.active_deadline_seconds - elapsed

        if failed > job.spec.backoff_limit:
            await self._fail(job, active, acct, "BackoffLimitExceeded",
                             f"job has failed {failed} times")
            return None

        # Gang: a failed member kills the whole gang; survivors AND the
        # failed records are torn down so the next sync recreates a full,
        # co-scheduled set (failure history is durable in status via the
        # counted-UID accounting above).
        if job.spec.gang is not None and new_f:
            self.recorder.event(job, "Warning", "GangMemberFailed",
                                "tearing down gang for atomic restart")
            for pod in active + new_f:
                await self.pod_control.delete_pod(job, pod)
            await self._update_status(job, [], acct)
            return None

        # Complete?
        if completions is not None:
            if job.spec.completion_mode == "Indexed":
                done = len(completed_indexes) >= completions
            else:
                done = succeeded >= completions
        else:
            done = succeeded > 0 and not active
        if done:
            await self._update_status(job, active, acct, condition="Complete")
            self.recorder.event(job, "Normal", "Completed", "job completed")
            await self._delete_podgroup(job)
            return None

        if job.spec.gang is not None:
            await self._ensure_podgroup(job)

        # How many pods should be running?
        want = job.spec.parallelism
        if completions is not None:
            remaining = (completions - len(completed_indexes)
                         if job.spec.completion_mode == "Indexed"
                         else completions - succeeded)
            want = min(want, remaining)
        if job.spec.completion_mode == "Indexed":
            await self._sync_indexed(job, active, completed_indexes, want)
        else:
            for _ in range(max(want - len(active), 0)):
                await self.pod_control.create_pod(
                    job, job.spec.template, mutate=self._mutator(job, 0))
            for pod in active[max(want, 0):]:
                await self.pod_control.delete_pod(job, pod)

        await self._update_status(job, self._pods_for(job), acct)
        return requeue

    async def _sync_indexed(self, job, active, completed_indexes, want) -> None:
        total = job.spec.completions or job.spec.parallelism
        # One live pod per index: reap duplicates (stale-cache double
        # creates would otherwise leave two pods with the same rank).
        by_idx: dict[str, list] = {}
        for p in active:
            by_idx.setdefault(
                p.metadata.labels.get(COMPLETION_INDEX_LABEL, ""), []).append(p)
        survivors = []
        for idx, group in by_idx.items():
            group.sort(key=lambda p: (
                p.metadata.creation_timestamp.timestamp()
                if p.metadata.creation_timestamp else 0.0))
            survivors.append(group[0])
            for dup in group[1:]:
                await self.pod_control.delete_pod(job, dup)
        # Enforce a lowered parallelism: drop highest indexes first.
        survivors.sort(key=lambda p: int(
            p.metadata.labels.get(COMPLETION_INDEX_LABEL, "0")))
        for p in survivors[max(want, 0):]:
            await self.pod_control.delete_pod(job, p)
        survivors = survivors[:max(want, 0)]
        active_idx = {p.metadata.labels.get(COMPLETION_INDEX_LABEL)
                      for p in survivors}
        budget = want - len(survivors)
        for i in range(total):
            if budget <= 0:
                break
            if i in completed_indexes or str(i) in active_idx:
                continue
            await self.pod_control.create_pod(
                job, job.spec.template,
                generate_name=f"{job.metadata.name}-{i}-",
                mutate=self._mutator(job, i))
            budget -= 1

    async def _fail(self, job, active, acct, reason, message) -> None:
        for pod in active:
            await self.pod_control.delete_pod(job, pod)
        await self._update_status(job, [], acct, condition="Failed",
                                  reason=reason, message=message)
        self.recorder.event(job, "Warning", reason, message)
        await self._delete_podgroup(job)

    async def _delete_podgroup(self, job) -> None:
        """Terminal Job: the gang is over, so its PodGroup goes now —
        a PodGroup's lifetime IS the gang's quota hold (queueing/
        fair-share admission charges a group until it is deleted or
        Failed; waiting for owner-ref GC at Job deletion would pin the
        tenant's quota on finished work indefinitely). Only QUEUED
        gangs: with the gate off, or for a group with no spec.queue
        (checked on the live group — admission may have defaulted it),
        there is no quota hold and the PodGroup must keep surviving
        until Job deletion exactly as before."""
        from ..util.features import GATES
        if job.spec.gang is None or not GATES.enabled("JobQueueing"):
            return
        ns, name = job.metadata.namespace, _group_name(job)
        key = f"{ns}/{name}"
        if key in self._group_torn_down:
            return
        try:
            group = await self.client.get("podgroups", ns, name)
            if group.spec.queue:
                await self.client.delete("podgroups", ns, name)
        except errors.NotFoundError:
            pass
        if len(self._group_torn_down) >= 4096:
            for stale in list(self._group_torn_down)[:2048]:
                del self._group_torn_down[stale]
        self._group_torn_down[key] = None

    async def _update_status(self, job, pods, acct,
                             condition: str = "", reason: str = "",
                             message: str = "") -> None:
        active = [p for p in pods if is_pod_active(p)]
        new = w.JobStatus(
            active=len(active),
            start_time=job.status.start_time or now(),
            completion_time=job.status.completion_time,
            conditions=list(job.status.conditions),
            **acct)
        if condition and not any(c.type == condition and c.status == "True"
                                 for c in new.conditions):
            new.conditions = new.conditions + [w.JobCondition(
                type=condition, status="True", reason=reason, message=message,
                last_transition_time=now())]
            if condition == "Complete":
                new.completion_time = now()
        if new == job.status:
            return
        fresh = w.Job(metadata=job.metadata, spec=job.spec, status=new)
        try:
            await self.client.update(fresh, subresource="status")
        except errors.NotFoundError:
            pass
