"""Deployment controller — declarative rollouts over ReplicaSets.

Reference: ``pkg/controller/deployment`` (3.1k LoC): hash the pod
template, own one ReplicaSet per revision, scale the new RS up and old
RSs down under maxSurge/maxUnavailable (RollingUpdate) or all-at-once
(Recreate), prune history beyond revisionHistoryLimit, aggregate status.
"""
from __future__ import annotations

import hashlib
import json
import math
from typing import Optional

from ..api import errors
from ..api import types as t
from ..api import workloads as w
from ..api.meta import controller_ref, is_controlled_by, now
from ..api.scheme import deepcopy, to_dict
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller, is_pod_active

#: Label carrying the template hash — the join key between a Deployment
#: revision, its ReplicaSet, and that RS's pods.
TEMPLATE_HASH_LABEL = "pod-template-hash"
REVISION_ANNOTATION = "deployment.tpu/revision"


def template_hash(template: t.PodTemplateSpec) -> str:
    payload = json.dumps(to_dict(template), sort_keys=True, default=str)
    return hashlib.sha1(payload.encode()).hexdigest()[:10]


def _resolve_percent(value, total: int, default: str, round_up: bool) -> int:
    """Percent -> pod count. maxSurge rounds up, maxUnavailable rounds
    down (reference: intstr.GetValueFromIntOrPercent usage in
    ``pkg/controller/deployment/util``)."""
    s = str(value if value is not None else default)
    if s.endswith("%"):
        frac = total * float(s[:-1]) / 100.0
        return math.ceil(frac) if round_up else math.floor(frac)
    return int(float(s))


class DeploymentController(Controller):
    name = "deployment-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 2):
        super().__init__(client, factory, workers)
        self.dep_informer = self.watch("deployments")
        self.rs_informer = self.watch("replicasets")
        self.dep_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n),
            on_delete=self.enqueue_obj)
        self.rs_informer.add_handlers(
            on_add=lambda rs: self.enqueue_owner(rs, "Deployment"),
            on_update=lambda o, n: self.enqueue_owner(n, "Deployment"),
            on_delete=lambda rs: self.enqueue_owner(rs, "Deployment"))

    # -- RS bookkeeping ---------------------------------------------------

    def _owned_rss(self, dep: w.Deployment) -> list[w.ReplicaSet]:
        return [rs for rs in self.rs_informer.list()
                if rs.metadata.namespace == dep.metadata.namespace
                and is_controlled_by(rs, dep)]

    async def _new_rs(self, dep: w.Deployment, rss: list[w.ReplicaSet],
                      hash_: str) -> w.ReplicaSet:
        for rs in rss:
            if rs.metadata.labels.get(TEMPLATE_HASH_LABEL) == hash_:
                return rs
        template = deepcopy(dep.spec.template)
        template.metadata.labels = {**template.metadata.labels,
                                    TEMPLATE_HASH_LABEL: hash_}
        selector = deepcopy(dep.spec.selector) if dep.spec.selector else None
        if selector is not None:
            selector.match_labels = {**selector.match_labels,
                                     TEMPLATE_HASH_LABEL: hash_}
        revision = 1 + max(
            (int(rs.metadata.annotations.get(REVISION_ANNOTATION, 0))
             for rs in rss), default=0)
        rs = w.ReplicaSet(
            metadata=t.ObjectMeta(
                name=f"{dep.metadata.name}-{hash_}",
                namespace=dep.metadata.namespace,
                labels=dict(template.metadata.labels),
                annotations={REVISION_ANNOTATION: str(revision)},
                owner_references=[controller_ref(dep, w.APPS_V1, "Deployment")]),
            spec=w.ReplicaSetSpec(replicas=0,
                                  min_ready_seconds=dep.spec.min_ready_seconds,
                                  selector=selector, template=template))
        try:
            created = await self.client.create(rs)
        except errors.AlreadyExistsError:
            created = await self.client.get("replicasets", rs.metadata.namespace,
                                            rs.metadata.name)
        self.recorder.event(dep, "Normal", "ScalingReplicaSet",
                            f"Created replica set {rs.metadata.name}")
        return created

    async def _scale_rs(self, rs: w.ReplicaSet, replicas: int) -> w.ReplicaSet:
        if rs.spec.replicas == replicas:
            return rs
        fresh = deepcopy(rs)
        fresh.spec.replicas = replicas
        return await self.client.update(fresh)

    # -- reconcile --------------------------------------------------------

    async def sync(self, key: str) -> Optional[float]:
        dep = self.dep_informer.get(key)
        if dep is None or dep.metadata.deletion_timestamp is not None:
            return None
        rss = self._owned_rss(dep)
        if dep.spec.paused:
            await self._update_status(dep, rss)
            return None
        hash_ = template_hash(dep.spec.template)
        new_rs = await self._new_rs(dep, rss, hash_)
        old_rss = [rs for rs in rss if rs.metadata.name != new_rs.metadata.name]

        if dep.spec.strategy.type == w.RECREATE:
            await self._rollout_recreate(dep, new_rs, old_rss)
        else:
            await self._rollout_rolling(dep, new_rs, old_rss)

        await self._cleanup_history(dep, old_rss)
        await self._update_status(dep, self._owned_rss(dep))
        return None

    async def _rollout_recreate(self, dep, new_rs, old_rss) -> None:
        for rs in old_rss:
            await self._scale_rs(rs, 0)
        # Wait until old pods are gone before scaling up the new RS.
        if any(rs.status.replicas > 0 for rs in old_rss):
            return
        await self._scale_rs(new_rs, dep.spec.replicas)

    async def _rollout_rolling(self, dep, new_rs, old_rss) -> None:
        desired = dep.spec.replicas
        ru = dep.spec.strategy.rolling_update
        max_surge = _resolve_percent(ru.max_surge, desired, "25%", round_up=True)
        max_unavailable = _resolve_percent(ru.max_unavailable, desired, "25%",
                                           round_up=False)
        if max_surge == 0 and max_unavailable == 0:
            max_unavailable = 1

        old_total = sum(rs.spec.replicas for rs in old_rss)
        all_total = old_total + new_rs.spec.replicas

        # Scale up the new RS bounded by desired + maxSurge.
        if new_rs.spec.replicas < desired:
            allowed = desired + max_surge - all_total
            if allowed > 0:
                grow = min(allowed, desired - new_rs.spec.replicas)
                new_rs = await self._scale_rs(new_rs, new_rs.spec.replicas + grow)
        elif new_rs.spec.replicas > desired:
            new_rs = await self._scale_rs(new_rs, desired)

        # First reap unhealthy old replicas — they contribute nothing to
        # availability, and leaving them gates the rollout forever
        # (reference: cleanupUnhealthyReplicas in rolling.go).
        min_available = desired - max_unavailable
        total_pods = sum(rs.spec.replicas for rs in old_rss) + new_rs.spec.replicas
        new_unavailable = new_rs.spec.replicas - new_rs.status.available_replicas
        max_cleanup = total_pods - min_available - new_unavailable
        refreshed = []
        for rs in sorted(old_rss, key=lambda r: r.metadata.name):
            unhealthy = rs.spec.replicas - rs.status.available_replicas
            if max_cleanup > 0 and unhealthy > 0:
                shrink = min(unhealthy, max_cleanup)
                rs = await self._scale_rs(rs, rs.spec.replicas - shrink)
                max_cleanup -= shrink
            refreshed.append(rs)
        old_rss = refreshed

        # Then scale down healthy old replicas bounded by availability:
        # keep at least desired - maxUnavailable ready pods across all RSs.
        available = sum(rs.status.available_replicas
                        for rs in old_rss) + new_rs.status.available_replicas
        can_remove = available - min_available
        for rs in sorted(old_rss, key=lambda r: r.metadata.name):
            if can_remove <= 0:
                break
            if rs.spec.replicas == 0:
                continue
            shrink = min(rs.spec.replicas, can_remove)
            await self._scale_rs(rs, rs.spec.replicas - shrink)
            can_remove -= shrink

    async def _cleanup_history(self, dep, old_rss) -> None:
        dead = [rs for rs in old_rss
                if rs.spec.replicas == 0 and rs.status.replicas == 0]
        dead.sort(key=lambda rs: int(
            rs.metadata.annotations.get(REVISION_ANNOTATION, 0)))
        excess = len(dead) - dep.spec.revision_history_limit
        for rs in dead[:max(excess, 0)]:
            try:
                await self.client.delete("replicasets", rs.metadata.namespace,
                                         rs.metadata.name)
            except errors.NotFoundError:
                pass

    async def _update_status(self, dep, rss) -> None:
        hash_ = template_hash(dep.spec.template)
        updated = sum(rs.status.replicas for rs in rss
                      if rs.metadata.labels.get(TEMPLATE_HASH_LABEL) == hash_)
        total = sum(rs.status.replicas for rs in rss)
        ready = sum(rs.status.ready_replicas for rs in rss)
        available = sum(rs.status.available_replicas for rs in rss)
        new = w.DeploymentStatus(
            observed_generation=dep.metadata.generation,
            replicas=total, updated_replicas=updated, ready_replicas=ready,
            available_replicas=available,
            unavailable_replicas=max(dep.spec.replicas - available, 0),
            conditions=[deepcopy(c) for c in dep.status.conditions])
        self._set_condition(
            new, "Available",
            "True" if available >= dep.spec.replicas else "False",
            "MinimumReplicasAvailable" if available >= dep.spec.replicas
            else "MinimumReplicasUnavailable")
        complete = (updated == dep.spec.replicas and total == dep.spec.replicas
                    and available >= dep.spec.replicas)
        self._set_condition(
            new, "Progressing", "True",
            "NewReplicaSetAvailable" if complete else "ReplicaSetUpdated")
        if new == dep.status:
            return
        fresh = w.Deployment(metadata=dep.metadata, spec=dep.spec, status=new)
        try:
            await self.client.update(fresh, subresource="status")
        except errors.NotFoundError:
            pass

    @staticmethod
    def _set_condition(status: w.DeploymentStatus, type_: str, value: str,
                       reason: str) -> None:
        for c in status.conditions:
            if c.type == type_:
                if c.status != value or c.reason != reason:
                    c.status, c.reason = value, reason
                    c.last_transition_time = now()
                return
        status.conditions = status.conditions + [w.DeploymentCondition(
            type=type_, status=value, reason=reason,
            last_transition_time=now())]
