"""InferenceService controller — reconcile + autoscale model serving.

Reference shape: a KServe-style reconciler fused with
``pkg/controller/podautoscaler``. One controller does both halves:

- **Reconcile** (``sync``): an InferenceService becomes a *headless*
  Service (per-replica DNS + Endpoints — the discovery substrate
  ``net/dns.py`` and the endpoint router read) plus a Deployment of
  model-server pods (``workloads/model_server.py``), both
  owner-referenced so deletion cascades through the garbage collector.
  The Deployment is created at ``min_replicas`` immediately — the warm
  pool's first half: capacity exists before the first request. The
  second half pre-pulls the model image on candidate nodes via
  short-lived prepull pods, so scale-up replicas skip the cold pull
  (the pull/start split stays visible in the ktrace startup breakdown).

- **Autoscale** (``on_start`` ticker): an HPA-analog loop reading the
  cluster monitor's ``latest()`` rollup (the custom-metrics seam from
  the telemetry PR) — per-pod tokens/s + busy fraction — and moving
  ``Deployment.spec.replicas`` inside ``[min, max]`` through the pure
  decision engine in :mod:`kubernetes_tpu.serving.autoscaler`
  (stabilization window, rate limits, stale-snapshot refusal).

Everything is inert while the ``InferenceAutoscaling`` gate is off:
no API traffic, no annotations — byte-identical to the ungated build.
"""
from __future__ import annotations

import asyncio
import logging
import math
import os
import sys
import time
from typing import Callable, Optional

from ..api import errors, serving as s
from ..api import types as t
from ..api import workloads as w
from ..api.meta import controller_ref, is_controlled_by, now
from ..api.scheme import deepcopy
from ..client.informer import InformerFactory
from ..client.interface import Client
from ..serving import autoscaler as engine
from ..util.tasks import spawn
from .base import Controller, is_pod_active, is_pod_ready

log = logging.getLogger("inference")


def _gated() -> bool:
    from ..util.features import GATES
    return GATES.enabled("InferenceAutoscaling")


class InferenceServiceController(Controller):
    name = "inference-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 metrics_feed: Optional[Callable[[], dict]] = None,
                 autoscale_interval: float = 2.0,
                 max_snapshot_age: float = 30.0):
        super().__init__(client, factory, workers=1)
        #: ClusterMonitor.latest seam ({} = no monitor wired; the
        #: autoscaler then refuses every tick, visibly, instead of
        #: scaling blind). The controller-manager wires the co-located
        #: monitor's latest() after construction.
        self.metrics_feed = metrics_feed
        self.autoscale_interval = autoscale_interval
        self.max_snapshot_age = max_snapshot_age
        self._states: dict[str, engine.ServiceState] = {}
        self._ticker: Optional[asyncio.Task] = None
        self.isvc_informer = self.watch("inferenceservices")
        self.dep_informer = self.watch("deployments")
        self.pod_informer = self.watch("pods")
        self.node_informer = self.watch("nodes")
        self.isvc_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n),
            on_delete=self._drop_state)
        self.dep_informer.add_handlers(
            on_add=lambda d: self.enqueue_owner(d, "InferenceService"),
            on_update=lambda o, n: self.enqueue_owner(n, "InferenceService"))
        self.pod_informer.add_handlers(
            on_add=self._pod_event, on_delete=self._pod_event,
            on_update=lambda o, n: self._pod_event(n))

    def _pod_event(self, pod: t.Pod) -> None:
        svc = pod.metadata.labels.get(s.SERVICE_LABEL) \
            or pod.metadata.labels.get(s.PREPULL_LABEL)
        if svc:
            self.enqueue(f"{pod.metadata.namespace}/{svc}")

    def _drop_state(self, isvc) -> None:
        self._states.pop(isvc.key(), None)
        for g in (engine.DESIRED, engine.UTILIZATION, engine.SNAPSHOT_AGE):
            g.remove(service=isvc.key())
        self.enqueue_obj(isvc)

    async def on_start(self) -> None:
        self._ticker = spawn(self._autoscale_loop(),
                             name="inference-autoscaler")

    async def stop(self) -> None:
        if self._ticker is not None:
            self._ticker.cancel()
            try:
                await self._ticker
            except asyncio.CancelledError:
                pass
            self._ticker = None
        await super().stop()

    # -- reconcile --------------------------------------------------------

    async def sync(self, key: str) -> Optional[float]:
        if not _gated():
            return None
        isvc = self.isvc_informer.get(key)
        if isvc is None or isvc.metadata.deletion_timestamp is not None:
            return None  # owner refs cascade Service/Deployment/pods
        await self._ensure_service(isvc)
        dep = await self._ensure_deployment(isvc)
        await self._sync_warm_pool(isvc)
        await self._update_status(isvc, dep)
        return None

    def _selector_labels(self, isvc) -> dict:
        return {s.SERVICE_LABEL: isvc.metadata.name}

    async def _ensure_service(self, isvc) -> None:
        name, ns = isvc.metadata.name, isvc.metadata.namespace
        existing = None
        try:
            existing = await self.client.get("services", ns, name)
        except errors.NotFoundError:
            pass
        if existing is not None:
            return
        port = s.effective_spec(isvc.spec).port
        svc = t.Service(
            metadata=t.ObjectMeta(
                name=name, namespace=ns,
                labels=self._selector_labels(isvc),
                owner_references=[controller_ref(
                    isvc, s.SERVING_V1, "InferenceService")]),
            spec=t.ServiceSpec(
                # Headless: DNS answers per-replica A records and the
                # endpoint router balances client-side; no VIP hop on
                # the inference hot path.
                cluster_ip="None",
                selector=dict(self._selector_labels(isvc)),
                ports=[t.ServicePort(name="http", port=port,
                                     target_port=port)]))
        try:
            await self.client.create(svc)
            self.recorder.event(isvc, "Normal", "CreatedService",
                                f"created headless service {name}")
        except errors.AlreadyExistsError:
            pass

    def _pod_template(self, isvc) -> t.PodTemplateSpec:
        # Effective spec: an object created while the gate was OFF (no
        # admission defaults) or updated to zero a field must never
        # yield a port-0 probe or a 0 tok/s rating.
        spec = s.effective_spec(isvc.spec)
        command = [sys.executable, "-m",
                   "kubernetes_tpu.workloads.model_server",
                   "--model", spec.model,
                   "--port", str(spec.port),
                   "--rated-tokens-per-sec",
                   f"{spec.rated_tokens_per_sec:g}"]
        env = []
        trace = os.environ.get("KTPU_TRACE", "")
        if trace:
            # Single-host composition shares the arming env; the server
            # then opens per-request serve spans (queue/decode split)
            # and spools them to the apiserver's trace ingest.
            env.append(t.EnvVar(name="KTPU_TRACE", value=trace))
            base = getattr(self.client, "base_url", "")
            if base:
                env.append(t.EnvVar(name="KTPU_TRACE_INGEST",
                                    value=f"{base}/debug/v1/traces"))
        container = t.Container(
            name="server", image=spec.image, command=command, env=env,
            resources=t.ResourceRequirements(
                requests={t.RESOURCE_CPU: spec.cpu_per_replica}),
            readiness_probe=t.Probe(
                http_get=t.HTTPGetAction(path="/healthz", port=spec.port),
                period_seconds=1, timeout_seconds=2, failure_threshold=3))
        pod_spec = t.PodSpec(containers=[container])
        chips = s.replica_chips(spec)
        if chips > 0:
            pod_spec.tpu_resources = [t.PodTpuRequest(
                name="tpu", chips=chips,
                slice_shape=list(spec.slice_shape))]
            container.tpu_requests = ["tpu"]
        return t.PodTemplateSpec(
            metadata=t.ObjectMeta(labels=self._selector_labels(isvc)),
            spec=pod_spec)

    async def _ensure_deployment(self, isvc) -> Optional[w.Deployment]:
        from ..api.selectors import LabelSelector
        name, ns = isvc.metadata.name, isvc.metadata.namespace
        dep = self.dep_informer.get(f"{ns}/{name}")
        if dep is not None and is_controlled_by(dep, isvc):
            # Template drift (model/port/image change) rolls through
            # the deployment controller; replicas stay autoscaler-owned.
            want = self._pod_template(isvc)
            if dep.spec.template != want:
                fresh = deepcopy(dep)
                fresh.spec.template = want
                try:
                    return await self.client.update(fresh)
                except (errors.ConflictError, errors.NotFoundError):
                    return dep
            return dep
        if dep is not None:
            log.warning("deployment %s/%s exists but is not owned by "
                        "InferenceService %s; leaving it alone", ns, name,
                        name)
            return None
        dep = w.Deployment(
            metadata=t.ObjectMeta(
                name=name, namespace=ns,
                labels=self._selector_labels(isvc),
                annotations={s.MANAGED_ANNOTATION: isvc.metadata.name},
                owner_references=[controller_ref(
                    isvc, s.SERVING_V1, "InferenceService")]),
            spec=w.DeploymentSpec(
                replicas=max(isvc.spec.min_replicas, 1),
                selector=LabelSelector(
                    match_labels=dict(self._selector_labels(isvc))),
                template=self._pod_template(isvc)))
        try:
            created = await self.client.create(dep)
        except errors.AlreadyExistsError:
            return self.dep_informer.get(f"{ns}/{name}")
        self.recorder.event(
            isvc, "Normal", "CreatedDeployment",
            f"created deployment {name} at {dep.spec.replicas} replicas "
            f"(warm pool)")
        return created

    # -- warm pool --------------------------------------------------------

    async def _sync_warm_pool(self, isvc) -> None:
        """Pre-pull the model image on candidate nodes AHEAD of the
        first scale-up: short-lived prepull pods (restartPolicy=Never,
        command exits immediately) pinned to nodes not yet serving this
        model. Once one succeeds, the node's image store holds the
        artifact and a later replica's ktrace ``pull`` span collapses
        to a cache hit — time-to-first-ready excludes the cold pull."""
        from ..node.images import is_artifact_ref
        spec = s.effective_spec(isvc.spec)
        if not spec.image or not is_artifact_ref(spec.image):
            return  # built-in image: nothing to pull anywhere
        ns, name = isvc.metadata.namespace, isvc.metadata.name
        want = spec.warm_pool_nodes or min(
            max(spec.max_replicas - max(spec.min_replicas, 1), 0), 2)
        pods = [p for p in self.pod_informer.list()
                if p.metadata.namespace == ns]
        warm_nodes = {p.spec.node_name for p in pods if p.spec.node_name
                      and (p.metadata.labels.get(s.SERVICE_LABEL) == name
                           or p.metadata.labels.get(s.PREPULL_LABEL) == name)}
        # The DURABLE warm record (status.warm_nodes) joins in: without
        # it, reaping a Succeeded prepull would erase the only evidence
        # the node is warm and the next sync — kicked by that very
        # delete event — would re-create the same prepull forever.
        warm_nodes |= set(isvc.status.warm_nodes)
        # Reap finished prepull pods — AFTER recording their node.
        for p in pods:
            if p.metadata.labels.get(s.PREPULL_LABEL) == name \
                    and p.status.phase in ("Succeeded", "Failed"):
                if p.status.phase == "Succeeded" and p.spec.node_name:
                    if not await self._record_warm_node(
                            isvc, p.spec.node_name):
                        continue  # conflict: retry before the delete
                    warm_nodes.add(p.spec.node_name)
                try:
                    await self.client.delete("pods", ns, p.metadata.name)
                except errors.NotFoundError:
                    pass
        live_prepulls = sum(
            1 for p in pods
            if p.metadata.labels.get(s.PREPULL_LABEL) == name
            and is_pod_active(p))
        chips = s.replica_chips(spec)
        candidates = []
        for node in self.node_informer.list():
            nname = node.metadata.name
            if nname in warm_nodes or node.spec.unschedulable:
                continue
            cap = node.status.allocatable.get(t.RESOURCE_TPU, 0) \
                or node.status.capacity.get(t.RESOURCE_TPU, 0)
            if chips and cap < chips:
                continue
            candidates.append(nname)
        for nname in sorted(candidates)[:max(want - live_prepulls, 0)]:
            pod = t.Pod(
                metadata=t.ObjectMeta(
                    name=f"{name}-prepull-{nname}"[:63], namespace=ns,
                    labels={s.PREPULL_LABEL: name},
                    owner_references=[controller_ref(
                        isvc, s.SERVING_V1, "InferenceService")]),
                spec=t.PodSpec(
                    restart_policy=t.RESTART_NEVER,
                    node_name=nname,  # pre-bound: no scheduler pass
                    containers=[t.Container(
                        name="prepull", image=spec.image,
                        command=[sys.executable, "-c", "pass"])]))
            try:
                await self.client.create(pod)
                self.recorder.event(
                    isvc, "Normal", "WarmPoolPrepull",
                    f"pre-pulling {spec.image} on node {nname}")
            except errors.AlreadyExistsError:
                pass

    async def _record_warm_node(self, isvc, node: str) -> bool:
        """Durably mark ``node`` warm for this service (status write,
        WAL-backed) — must land BEFORE the prepull pod is deleted."""
        if node in isvc.status.warm_nodes:
            return True
        fresh = deepcopy(isvc)
        fresh.status.warm_nodes = sorted(
            set(isvc.status.warm_nodes) | {node})
        try:
            await self.client.update(fresh, subresource="status")
            return True
        except errors.NotFoundError:
            return True  # service deleted: nothing left to protect
        except errors.ConflictError:
            return False  # stale copy: the resync retries the reap

    # -- status -----------------------------------------------------------

    def _replica_pods(self, isvc) -> list[t.Pod]:
        name, ns = isvc.metadata.name, isvc.metadata.namespace
        return [p for p in self.pod_informer.list()
                if p.metadata.namespace == ns and is_pod_active(p)
                and p.metadata.labels.get(s.SERVICE_LABEL) == name]

    async def _update_status(self, isvc, dep) -> None:
        pods = self._replica_pods(isvc)
        new = deepcopy(isvc.status)
        new.replicas = len(pods)
        new.ready_replicas = sum(1 for p in pods if is_pod_ready(p))
        if dep is not None:
            new.desired_replicas = dep.spec.replicas
        if new == isvc.status:
            return
        fresh = deepcopy(isvc)
        fresh.status = new
        try:
            await self.client.update(fresh, subresource="status")
        except (errors.ConflictError, errors.NotFoundError):
            pass

    # -- autoscaler -------------------------------------------------------

    async def _autoscale_loop(self) -> None:
        while True:
            await asyncio.sleep(self.autoscale_interval)
            if not _gated():
                continue
            try:
                await self.autoscale_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one bad tick must not
                log.exception("autoscale tick failed")  # kill the loop

    def _sample(self, isvc) -> Optional[engine.MetricsSample]:
        """Fold the monitor snapshot's per-pod rows into one service
        sample. ``mfu`` carries the model server's busy fraction (the
        stats pipeline's generic utilization slot)."""
        if self.metrics_feed is None:
            return None
        snap = self.metrics_feed() or {}
        pods_stats = snap.get("pods") or {}
        utils, tokens = [], 0.0
        for p in self._replica_pods(isvc):
            rec = pods_stats.get(p.key())
            if rec is None:
                continue
            tokens += float(rec.get("tokens_per_sec", 0.0) or 0.0)
            if "mfu" in rec:
                utils.append(float(rec["mfu"]))
        return engine.MetricsSample(
            utilization=sum(utils) / len(utils) if utils else 0.0,
            tokens_per_sec=round(tokens, 1),
            reporting=len(utils),
            age_seconds=float(snap.get("age_seconds", float("inf"))))

    async def autoscale_once(self) -> None:
        """One pass over every InferenceService (tests call this
        directly with a synthetic feed)."""
        clock = time.monotonic()
        for isvc in self.isvc_informer.list():
            key = isvc.key()
            dep = self.dep_informer.get(key)
            if dep is None or not is_controlled_by(dep, isvc):
                continue
            current = dep.spec.replicas
            pods = self._replica_pods(isvc)
            ready = sum(1 for p in pods if is_pod_ready(p))
            sample = self._sample(isvc)
            state = self._states.setdefault(key, engine.ServiceState())
            decision = engine.decide(
                s.effective_spec(isvc.spec), current, ready, sample,
                state, clock, max_snapshot_age=self.max_snapshot_age)
            engine.export_metrics(key, decision, sample, current)
            state.last_desired = decision.desired
            await self._apply_decision(isvc, dep, sample, decision)

    async def _apply_decision(self, isvc, dep, sample, decision) -> None:
        current = dep.spec.replicas
        changed = not decision.refused and decision.desired != current
        if changed:
            fresh = deepcopy(dep)
            fresh.spec.replicas = decision.desired
            try:
                await self.client.update(fresh)
            except (errors.ConflictError, errors.NotFoundError):
                return
            self.recorder.event(
                isvc, "Normal", "Rescaled",
                f"scaled {current} -> {decision.desired} "
                f"({decision.reason})")
        new = deepcopy(isvc.status)
        new.desired_replicas = decision.desired
        if sample is not None:
            new.tokens_per_sec = sample.tokens_per_sec
            new.utilization = round(sample.utilization, 4)
            # inf (no sweep yet) stays -1: JSON has no Infinity.
            new.snapshot_age_seconds = (
                round(sample.age_seconds, 3)
                if math.isfinite(sample.age_seconds) else -1.0)
        new.last_scale_reason = decision.reason
        if changed:
            new.last_scale_time = now()
        if self._status_material_change(isvc.status, new, changed,
                                        decision.refused):
            fresh = deepcopy(isvc)
            fresh.status = new
            try:
                await self.client.update(fresh, subresource="status")
            except (errors.ConflictError, errors.NotFoundError):
                pass

    @staticmethod
    def _status_material_change(old, new, changed: bool,
                                refused: bool) -> bool:
        """Whether this tick's status is worth an API write. The
        snapshot age and the utilization reading drift every tick by
        nature; writing them verbatim would cost one MVCC write + watch
        fan-out per service per tick FOREVER at steady state. A write
        happens only when something an operator acts on moved: the
        target, a refusal-state flip, a utilization/throughput shift
        beyond reporting noise, or the very first sample."""
        if changed or new.desired_replicas != old.desired_replicas:
            return True
        stale_kind = "metrics snapshot stale"
        if refused != old.last_scale_reason.startswith(stale_kind):
            return True
        if (old.snapshot_age_seconds < 0) != (new.snapshot_age_seconds
                                              < 0):
            return True  # first sample / feed appeared or vanished
        if abs(new.utilization - old.utilization) >= 0.05:
            return True
        if abs(new.tokens_per_sec - old.tokens_per_sec) >= max(
                1.0, 0.1 * old.tokens_per_sec):
            return True
        return False
