"""Node IPAM controller — ensures every node carries a pod CIDR.

Reference: ``pkg/controller/node/ipam/range_allocator.go`` — there the
controller owns the allocator. Here allocation lives in ONE place, the
registry's node strategy (``apiserver/registry.py _prepare_node``),
because two independent allocators (controller + create strategy)
could race each other into assigning the same block. The controller's
job is the legacy/repair path: a node observed without a CIDR (e.g.
durable data from before the feature) gets a no-op spec write, which
the registry turns into an assignment.
"""
from __future__ import annotations

from typing import Optional

from ..api import errors, types as t
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller


class NodeIpamController(Controller):
    name = "node-ipam-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 1):
        super().__init__(client, factory, workers)
        self.node_informer = self.watch("nodes")
        self.node_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n))

    async def sync(self, key: str) -> Optional[float]:
        node = self.node_informer.get(key)
        if node is None or node.spec.pod_cidr:
            return None
        try:
            cur = await self.client.get("nodes", "", node.metadata.name)
            if cur.spec.pod_cidr:
                return None
            # No-op spec write; the registry update strategy assigns
            # the CIDR server-side (single-allocator invariant).
            await self.client.update(cur)
        except errors.NotFoundError:
            pass
        return None
