"""CronJob controller — time-based Job creation.

Reference: ``pkg/controller/cronjob`` (0.9k LoC): every sync tick, for
each CronJob compute the most recent schedule time since the last one;
if unsatisfied and within startingDeadlineSeconds, create a Job named
``<cronjob>-<scheduled-unix-minutes>``; honor suspend +
concurrencyPolicy; prune history beyond the limits.
"""
from __future__ import annotations

import asyncio
import datetime
from typing import Optional

from ..api import errors
from ..api import types as t
from ..api import workloads as w
from ..api.meta import controller_ref, is_controlled_by, now
from ..api.scheme import deepcopy
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller
from ..util.cron import CronSchedule


class CronJobController(Controller):
    name = "cronjob-controller"

    #: Seconds between schedule scans (reference: 10s resync).
    tick = 10.0

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 1):
        super().__init__(client, factory, workers)
        self.cj_informer = self.watch("cronjobs")
        self.job_informer = self.watch("jobs")
        self.cj_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n),
            on_delete=self.enqueue_obj)
        self.job_informer.add_handlers(
            on_add=lambda j: self.enqueue_owner(j, "CronJob"),
            on_update=lambda o, n: self.enqueue_owner(n, "CronJob"),
            on_delete=lambda j: self.enqueue_owner(j, "CronJob"))
        self._tick_task: Optional[asyncio.Task] = None

    async def on_start(self) -> None:
        self._tick_task = asyncio.get_running_loop().create_task(self._ticker())

    async def stop(self) -> None:
        if self._tick_task:
            self._tick_task.cancel()
            try:
                await self._tick_task
            except asyncio.CancelledError:
                pass
        await super().stop()

    async def _ticker(self) -> None:
        while True:
            for cj in self.cj_informer.list():
                self.enqueue_obj(cj)
            await asyncio.sleep(self.tick)

    def _jobs_for(self, cj: w.CronJob) -> list[w.Job]:
        return [j for j in self.job_informer.list()
                if j.metadata.namespace == cj.metadata.namespace
                and is_controlled_by(j, cj)]

    @staticmethod
    def _job_finished(job: w.Job) -> Optional[str]:
        for c in job.status.conditions:
            if c.type in ("Complete", "Failed") and c.status == "True":
                return c.type
        return None

    async def sync(self, key: str) -> Optional[float]:
        cj = self.cj_informer.get(key)
        if cj is None or cj.metadata.deletion_timestamp is not None:
            return None
        jobs = self._jobs_for(cj)
        running = [j for j in jobs if not self._job_finished(j)]

        # Reconcile status.active and prune history.
        await self._prune(cj, jobs)

        if cj.spec.suspend:
            return None
        try:
            sched = CronSchedule(cj.spec.schedule)
        except ValueError:
            self.recorder.event(cj, "Warning", "InvalidSchedule",
                                f"cannot parse {cj.spec.schedule!r}")
            return None

        since = (cj.status.last_schedule_time
                 or cj.metadata.creation_timestamp or now())
        ts = now()
        due = sched.most_recent(since, ts)
        if due is None:
            return self.tick
        if cj.spec.starting_deadline_seconds is not None and \
                (ts - due).total_seconds() > cj.spec.starting_deadline_seconds:
            self.recorder.event(cj, "Warning", "MissedSchedule",
                                f"missed start {due.isoformat()}")
            await self._mark_scheduled(cj, due, running)
            return self.tick

        if running:
            policy = cj.spec.concurrency_policy
            if policy == "Forbid":
                self.recorder.event(cj, "Normal", "JobAlreadyActive",
                                    "skipping run: previous still active")
                await self._mark_scheduled(cj, due, running)
                return self.tick
            if policy == "Replace":
                for j in running:
                    await self._delete_job(cj, j)
                running = []

        await self._start_job(cj, due)
        return self.tick

    async def _start_job(self, cj: w.CronJob, due) -> None:
        stamp = int(due.timestamp() // 60)
        job = w.Job(
            metadata=t.ObjectMeta(
                name=f"{cj.metadata.name}-{stamp}",
                namespace=cj.metadata.namespace,
                owner_references=[controller_ref(cj, w.BATCH_V1, "CronJob")]),
            spec=deepcopy(cj.spec.job_template))
        created = None
        try:
            created = await self.client.create(job)
            self.recorder.event(cj, "Normal", "SuccessfulCreate",
                                f"Created job {job.metadata.name}")
        except errors.AlreadyExistsError:
            pass
        # status.active = still-running owned jobs + the one just created
        # (the informer has not ingested it yet).
        running = [j for j in self._jobs_for(cj) if not self._job_finished(j)]
        if created is not None and all(
                j.metadata.name != created.metadata.name for j in running):
            running.append(created)
        await self._mark_scheduled(cj, due, running)

    async def _mark_scheduled(self, cj, due, running) -> None:
        fresh = deepcopy(cj)
        fresh.status.last_schedule_time = due
        fresh.status.active = [j.metadata.name for j in running]
        try:
            await self.client.update(fresh, subresource="status")
        except (errors.NotFoundError, errors.ConflictError):
            pass

    async def _delete_job(self, cj, job) -> None:
        try:
            await self.client.delete("jobs", job.metadata.namespace,
                                     job.metadata.name)
        except errors.NotFoundError:
            pass
        # Cascade to the job's pods here as well: deletion through the
        # garbage collector (owner-reference cascade) is asynchronous,
        # and Replace semantics require the old run to actually stop.
        pods, _ = await self.client.list("pods", job.metadata.namespace)
        for pod in pods:
            refs = pod.metadata.owner_references
            if any(r.uid == job.metadata.uid for r in refs):
                try:
                    await self.client.delete("pods", pod.metadata.namespace,
                                             pod.metadata.name)
                except errors.NotFoundError:
                    pass

    async def _prune(self, cj, jobs) -> None:
        def by_age(js):
            return sorted(js, key=lambda j: (
                j.metadata.creation_timestamp.timestamp()
                if j.metadata.creation_timestamp else 0.0))
        done_ok = by_age([j for j in jobs if self._job_finished(j) == "Complete"])
        done_bad = by_age([j for j in jobs if self._job_finished(j) == "Failed"])
        for j in done_ok[:max(0, len(done_ok)
                              - cj.spec.successful_jobs_history_limit)]:
            await self._delete_job(cj, j)
        for j in done_bad[:max(0, len(done_bad)
                               - cj.spec.failed_jobs_history_limit)]:
            await self._delete_job(cj, j)
