"""TTL controller — scales node object-cache TTL with cluster size.

Reference: ``pkg/controller/ttl/ttl_controller.go`` — annotates every
node with ``node.alpha.kubernetes.io/ttl``, the number of seconds
agents may serve ConfigMaps/Secrets from cache before re-fetching.
Small clusters get 0 (always fresh); big clusters get minutes, cutting
the O(pods) config reads that would otherwise hammer the apiserver at
fleet scale. The node agent's volume manager honors the annotation
(``node/volumes.py`` ObjectCache).
"""
from __future__ import annotations

from typing import Optional

from ..api import errors
from ..api.types import TTL_ANNOTATION
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller

__all__ = ["TTLController", "TTL_ANNOTATION", "ttl_for_cluster_size"]

#: (cluster-size upper bound, ttl seconds) — reference tiers
#: (ttl_controller.go ttlBoundaries).
TTL_BOUNDARIES = [(100, 0), (500, 15), (1000, 30), (5000, 60),
                  (float("inf"), 300)]


def ttl_for_cluster_size(n_nodes: int) -> int:
    for bound, ttl in TTL_BOUNDARIES:
        if n_nodes <= bound:
            return ttl
    return 300


class TTLController(Controller):
    name = "ttl-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 1):
        super().__init__(client, factory, workers)
        self.node_informer = self.watch("nodes")
        # Every add/delete can move the cluster across a boundary; the
        # reference re-enqueues all nodes only when the *tier* changes.
        self.node_informer.add_handlers(
            on_add=self._on_add,
            on_delete=lambda n: self._tier_check(),
            on_update=lambda o, n: self.enqueue_obj(n))
        self._last_ttl: Optional[int] = None

    def _on_add(self, node) -> None:
        # The new node needs its annotation even when the tier didn't
        # move; _tier_check alone would skip it.
        self.enqueue_obj(node)
        self._tier_check()

    def _desired_ttl(self) -> int:
        return ttl_for_cluster_size(len(self.node_informer.list()))

    def _tier_check(self) -> None:
        ttl = self._desired_ttl()
        if ttl == self._last_ttl:
            return
        self._last_ttl = ttl
        for node in self.node_informer.list():
            self.enqueue_obj(node)

    async def sync(self, key: str) -> Optional[float]:
        node = self.node_informer.get(key)
        if node is None:
            return None
        want = str(self._desired_ttl())
        if node.metadata.annotations.get(TTL_ANNOTATION) == want:
            return None
        try:
            cur = await self.client.get("nodes", "", node.metadata.name)
            cur.metadata.annotations[TTL_ANNOTATION] = want
            await self.client.update(cur)
        except errors.NotFoundError:
            return None
        except errors.ConflictError:
            return 0.5  # stale read; retry shortly
        return None
