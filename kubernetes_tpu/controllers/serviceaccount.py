"""ServiceAccount + token controllers.

Reference: ``pkg/controller/serviceaccount`` — two loops: one ensures
every Active namespace has a "default" ServiceAccount, the other mints
a token Secret per ServiceAccount and records it in ``sa.secrets``.
Tokens here are opaque bearer strings (not JWTs): the apiserver's authn
resolves them against token Secrets, yielding the RBAC user
``system:serviceaccount:<ns>:<name>``.
"""
from __future__ import annotations

import base64
import secrets as pysecrets
from typing import Optional

from ..api import errors, types as t
from ..api.meta import ObjectMeta
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller

DEFAULT_SA = "default"
TOKEN_KEY = "token"


class ServiceAccountController(Controller):
    """Ensures the default ServiceAccount + a token Secret per SA."""

    name = "serviceaccount-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 1):
        super().__init__(client, factory, workers)
        self.ns_informer = self.watch("namespaces")
        self.sa_informer = self.watch("serviceaccounts")
        self.secret_informer = self.watch("secrets")
        self.ns_informer.add_handlers(
            on_add=lambda ns: self.enqueue(f"ns::{ns.metadata.name}"),
            on_update=lambda o, n: self.enqueue(f"ns::{n.metadata.name}"))
        self.sa_informer.add_handlers(
            on_add=lambda sa: self.enqueue(sa.key()),
            on_update=lambda o, n: self.enqueue(n.key()),
            # Level-triggered recreate of the default SA + revocation of
            # the deleted SA's token secret (reference TokensController
            # deletes tokens of deleted SAs).
            on_delete=lambda sa: (
                self.enqueue(f"ns::{sa.metadata.namespace}"),
                self.enqueue(f"revoke::{sa.metadata.namespace}/"
                             f"{sa.metadata.name}")))
        # A deleted token secret is re-minted while its SA lives.
        self.secret_informer.add_handlers(
            on_delete=lambda sec: (
                self.enqueue(f"{sec.metadata.namespace}/"
                             f"{sec.metadata.name.removesuffix('-token')}")
                if sec.type == t.SECRET_TYPE_SA_TOKEN
                and sec.metadata.name.endswith("-token") else None))

    async def sync(self, key: str) -> Optional[float]:
        if key.startswith("ns::"):
            await self._ensure_default_sa(key[4:])
            return None
        if key.startswith("revoke::"):
            await self._revoke_token(key[len("revoke::"):])
            return None
        sa = self.sa_informer.get(key)
        if sa is None:
            return None
        await self._ensure_token(sa)
        return None

    async def _revoke_token(self, sa_key: str) -> None:
        """Delete the token secret of a deleted ServiceAccount —
        possession of the old bearer must stop granting its identity."""
        ns, name = sa_key.split("/", 1)
        try:
            await self.client.get("serviceaccounts", ns, name)
            return  # recreated meanwhile; keep the token
        except errors.NotFoundError:
            pass
        try:
            await self.client.delete("secrets", ns, f"{name}-token")
        except errors.NotFoundError:
            pass

    async def _ensure_default_sa(self, ns_name: str) -> None:
        ns = self.ns_informer.get(ns_name)
        if ns is None or ns.status.phase != t.NS_ACTIVE:
            return
        try:
            await self.client.get("serviceaccounts", ns_name, DEFAULT_SA)
        except errors.NotFoundError:
            try:
                await self.client.create(t.ServiceAccount(
                    metadata=ObjectMeta(name=DEFAULT_SA, namespace=ns_name)))
            except (errors.AlreadyExistsError, errors.ForbiddenError):
                pass  # raced / namespace terminating

    async def _ensure_token(self, sa: t.ServiceAccount) -> None:
        ns = sa.metadata.namespace
        secret_name = f"{sa.metadata.name}-token"
        have_secret = False
        try:
            existing = await self.client.get("secrets", ns, secret_name)
            if existing.metadata.annotations.get(
                    t.SA_UID_ANNOTATION) == sa.metadata.uid:
                have_secret = True
            else:
                # Token minted for a PREVIOUS incarnation of this SA
                # name: a delete/recreate must invalidate leaked
                # bearers (reference binds tokens to the SA UID).
                try:
                    await self.client.delete("secrets", ns, secret_name)
                except errors.NotFoundError:
                    pass
        except errors.NotFoundError:
            pass
        if not have_secret:
            token = pysecrets.token_urlsafe(32)
            secret = t.Secret(
                metadata=ObjectMeta(
                    name=secret_name, namespace=ns,
                    annotations={t.SA_NAME_ANNOTATION: sa.metadata.name,
                                 t.SA_UID_ANNOTATION: sa.metadata.uid}),
                type=t.SECRET_TYPE_SA_TOKEN,
                data={TOKEN_KEY: base64.b64encode(token.encode()).decode(),
                      "namespace": base64.b64encode(ns.encode()).decode()})
            try:
                await self.client.create(secret)
            except (errors.AlreadyExistsError, errors.ForbiddenError):
                pass
        if secret_name not in sa.secrets:
            cur = await self.client.get("serviceaccounts", ns,
                                        sa.metadata.name)
            if secret_name not in cur.secrets:
                cur.secrets.append(secret_name)
                await self.client.update(cur)
