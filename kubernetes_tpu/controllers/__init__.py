"""Controllers — level-triggered reconcile loops over the API server.

Reference: ``pkg/controller/`` (36.6k LoC) driven by
``cmd/kube-controller-manager/app/controllermanager.go:332
NewControllerInitializers``. Each controller is an informer-fed,
workqueue-drained reconcile loop (the pattern of
``pkg/controller/replicaset/replica_set.go:178,433,572``).
"""
