"""Namespace controller — drains Terminating namespaces.

Reference: ``pkg/controller/namespace``: when a namespace enters
Terminating (deletion_timestamp set, spec.finalizers pending), delete
every namespaced object it contains, then clear the ``kubernetes_tpu``
finalizer; the registry removes the namespace on that update.
"""
from __future__ import annotations

from typing import Optional

from ..api import errors
from ..api import types as t
from ..api.scheme import deepcopy
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller

#: Namespaced resources purged on namespace deletion.
NAMESPACED = [
    "pods", "services", "endpoints", "configmaps", "secrets", "events",
    "podgroups", "replicasets", "deployments", "statefulsets", "daemonsets",
    "jobs", "cronjobs", "horizontalpodautoscalers", "poddisruptionbudgets",
    "resourcequotas", "limitranges", "leases",
]


class NamespaceController(Controller):
    name = "namespace-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 1):
        super().__init__(client, factory, workers)
        self.ns_informer = self.watch("namespaces")
        self.ns_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n))

    async def sync(self, key: str) -> Optional[float]:
        ns = self.ns_informer.get(key)
        if ns is None or ns.metadata.deletion_timestamp is None:
            return None
        name = ns.metadata.name
        remaining = 0
        for plural in NAMESPACED:
            try:
                items, _ = await self.client.list(plural, name)
            except errors.NotFoundError:
                continue
            for obj in items:
                remaining += 1
                try:
                    # Force-delete pods: their node agents may be gone
                    # with the namespace's workloads anyway.
                    gp = 0 if plural == "pods" else None
                    await self.client.delete(plural, name, obj.metadata.name,
                                             grace_period_seconds=gp)
                except (errors.NotFoundError, errors.ConflictError):
                    pass
        if remaining:
            return 0.1  # deletions are async; check again shortly
        fresh = deepcopy(ns)
        fresh.spec.finalizers = []
        try:
            await self.client.update(fresh)
        except (errors.NotFoundError, errors.ConflictError):
            pass
        return None
