"""Live gang migration + defragmentation — move gangs BEFORE chips die.

The machinery this controller composes already exists: graceful
preemption (signal -> checkpoint -> requeue, ``preemption.py``), durable
recovery rounds (TrainJob resume), and kmon's alert -> taint pipeline
(``tpu.google.com/degraded`` — "the migration seam"). What was missing
is the consumer: today a TpuChipSick alert taints a node and the gang
on it sits there until the chip actually dies, taking unsaved steps
with it. Kant's defragmentation story (PAPERS.md) is the second
trigger: small gangs consolidate onto open contiguous boxes so large
pending gangs can place.

**Reserve-then-move.** A migration round reserves the target
contiguous sub-mesh in the scheduler cache FIRST (``cache.reserve``,
owner = the gang key), writes durable ``status.migration`` round state
(rides the WAL — a crashed controller resumes or aborts from status
alone), and only then signals the gang through the shared preemption
engine. A migration with no landing spot degrades to *do nothing* —
never to an eviction in disguise. The scheduler steers the requeued
gang onto its own reserved box (``restrict_to`` in ``plan_gang``)
and releases the reservation when the plan
lands, so the gang holds its source placement or its target
reservation at every revision (the tpusan ``migration-no-strand``
invariant). Abort paths close status BEFORE releasing the reservation
for the same reason.

**Budget.** ``max_concurrent`` bounds open rounds fleet-wide;
``cooldown_seconds`` spaces rounds per gang; a gang already inside a
graceful-preemption round (Signaled/Checkpointing) is never migrated
on top of it.

Everything is inert while the ``GangLiveMigration`` gate is off — no
watches consumed, no reservations, no status writes; byte-identical
to the ungated build.
"""
from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Callable, Optional

from .. import preemption as gp
from ..api import errors
from ..api import types as t
from ..api.meta import now as meta_now
from ..client.informer import InformerFactory
from ..client.interface import Client
from ..metrics.registry import Counter, Gauge
from ..monitoring.rules import TAINT_DEGRADED
from .base import Controller

log = logging.getLogger("migrate")

#: migration_* metric families (the tpuvet fixture set).
ROUNDS_TOTAL = Counter(
    "migration_rounds_total",
    "completed migration rounds by trigger and outcome",
    labels=("reason", "outcome"))
ROUNDS_OPEN = Gauge(
    "migration_rounds_open",
    "migration rounds currently open (Reserved or Moving)")
NO_TARGET_TOTAL = Counter(
    "migration_no_target_total",
    "migrations skipped because no landing spot existed (the required "
    "degrade-to-no-op, never an eviction in disguise)",
    labels=("reason",))
DEFRAG_GAIN_CHIPS = Gauge(
    "migration_defrag_gain_chips",
    "largest-free-box volume gain of the last planned defrag move")


def _gated() -> bool:
    from ..util.features import GATES
    return GATES.enabled("GangLiveMigration")


def _round_open(group: t.PodGroup) -> bool:
    mig = group.status.migration
    return mig is not None and mig.phase in (t.MIGRATE_RESERVED,
                                             t.MIGRATE_MOVING)


def _cell_key(coord) -> str:
    return ",".join(str(int(c)) for c in coord)


def _parse_cells(cells: list[str]) -> list[tuple]:
    return [tuple(int(x) for x in s.split(",")) for s in cells]


def _chaos_fault():
    """The ``migrate`` chaos site, consulted once per started round."""
    from ..chaos import core as chaos
    c = chaos.CONTROLLER
    if c is None:
        return None
    return c.decide(chaos.SITE_MIGRATE)


class MigrationController(Controller):
    """Reserve-then-move gang migration off sick chips + defrag."""

    name = "migration"

    def __init__(self, client: Client, factory: InformerFactory,
                 cache_probe: Optional[Callable] = None,
                 interval: float = 5.0,
                 max_concurrent: int = 1,
                 cooldown_seconds: float = 120.0,
                 round_timeout_seconds: float = 60.0,
                 defrag: bool = True):
        super().__init__(client, factory, workers=1)
        #: Returns the live SchedulerCache (single-binary composers
        #: wire the real one) or None — without it the controller can
        #: neither reserve nor plan, so it does nothing.
        self.cache_probe = cache_probe
        self.interval = interval
        self.max_concurrent = max_concurrent
        self.cooldown_seconds = cooldown_seconds
        self.round_timeout_seconds = round_timeout_seconds
        self.defrag = defrag
        # Shared-factory informers (one watch per resource cluster-wide,
        # not one per controller); sync/sweep are gate-checked so the
        # controller is inert off.
        self.group_informer = self.watch("podgroups")
        self.pod_informer = self.watch("pods")
        self.node_informer = self.watch("nodes")
        self.group_informer.add_handlers(
            on_update=lambda o, n: self._group_event(n))
        self._sweep_task = None

    def _group_event(self, group: t.PodGroup) -> None:
        if _gated() and _round_open(group):
            self.enqueue_obj(group)

    async def on_start(self) -> None:
        self._sweep_task = asyncio.get_running_loop().create_task(
            self._sweep_loop())

    async def stop(self) -> None:
        if self._sweep_task is not None:
            self._sweep_task.cancel()
            try:
                await self._sweep_task
            except asyncio.CancelledError:
                pass
            self._sweep_task = None
        await super().stop()

    async def _sweep_loop(self) -> None:
        while True:
            try:
                await self.sweep_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — keep sweeping
                log.exception("migration sweep failed")
            await asyncio.sleep(self.interval)

    # -- per-group reconcile (informer-driven responsiveness) -------------

    async def sync(self, key: str) -> Optional[float]:
        if not _gated():
            return None
        cache = self.cache_probe() if self.cache_probe else None
        if cache is None:
            return None
        ns, name = key.split("/", 1)
        try:
            group = await self.client.get("podgroups", ns, name)
        except errors.NotFoundError:
            return None
        if _round_open(group):
            await self._advance_round(cache, group)
        return None

    # -- the sweep ---------------------------------------------------------

    async def sweep_once(self) -> None:
        """One full pass: resume/advance open rounds, then plan new
        ones under the budget. Also the crash-recovery entry point —
        everything here reconstructs from status.migration + the
        cache, never from controller memory."""
        if not _gated():
            return  # byte-identical off: not even a metric sample moves
        cache = self.cache_probe() if self.cache_probe else None
        if cache is None:
            return
        groups = [g for g in self.group_informer.store.list()
                  if isinstance(g, t.PodGroup)]
        open_rounds = 0
        for group in groups:
            if _round_open(group):
                open_rounds += 1
                await self._advance_round(cache, group)
        ROUNDS_OPEN.set(open_rounds)
        budget = self.max_concurrent - open_rounds
        if budget <= 0:
            return
        for group, reason, res_cells, slice_id in self._plan(cache, groups):
            if budget <= 0:
                break
            if await self._start_round(cache, group, reason, res_cells,
                                       slice_id):
                budget -= 1

    # -- candidate selection ----------------------------------------------

    def _degraded_nodes(self) -> set[str]:
        out = set()
        for node in self.node_informer.store.list():
            taints = getattr(node.spec, "taints", None) or ()
            if any(taint.key == TAINT_DEGRADED for taint in taints):
                out.add(node.metadata.name)
        return out

    def _bound_members(self, group: t.PodGroup) -> list[t.Pod]:
        ns = group.metadata.namespace
        name = group.metadata.name
        return [p for p in self.pod_informer.store.list()
                if isinstance(p, t.Pod)
                and p.metadata.namespace == ns and p.spec.gang == name
                and t.is_pod_active(p) and p.spec.node_name]

    def _migratable(self, group: t.PodGroup) -> bool:
        """Budget guards shared by both triggers: checkpoint-opted
        (a gang that cannot checkpoint would just be killed — an
        eviction in disguise), not mid-preemption, out of cooldown,
        no open round."""
        if _round_open(group) or not gp.eligible(group):
            return False
        pre = group.status.preemption
        if pre is not None and pre.phase in (t.PREEMPT_SIGNALED,
                                             t.PREEMPT_CHECKPOINTING):
            return False
        mig = group.status.migration
        if mig is not None and mig.finished_time is not None:
            age = (meta_now() - mig.finished_time).total_seconds()
            if age < self.cooldown_seconds:
                return False
        return True

    def _free_cells(self, cache, sl, degraded: set[str],
                    exclude_owner: str) -> dict:
        """coord -> (node, chip) a migration may land on: free in the
        cache, not on a degraded node, not held by anyone else's live
        reservation."""
        held = cache.reserved_cells(sl.slice_id, exclude_owner=exclude_owner)
        return {c: v for c, v in sl.free(cache).items()
                if v[0] not in degraded and c not in held}

    def _find_target(self, cache, group: t.PodGroup,
                     degraded: set[str]) -> Optional[tuple[dict, str]]:
        """First feasible landing box for the gang's shape: (cells
        coord->(node,chip), slice_id) or None — reserve-first demands
        the box exists BEFORE anything is signaled."""
        from ..scheduler.submesh import find_box
        shape = group.spec.slice_shape
        if not shape:
            return None
        for slice_id in sorted(cache.slices):
            sl = cache.slices[slice_id]
            free = self._free_cells(cache, sl, degraded, group.key())
            box = find_box(set(free), sl.mesh_shape, shape, torus=True)
            if box is not None:
                return {c: free[c] for c in box}, slice_id
        return None

    def _plan(self, cache, groups: list[t.PodGroup]):
        """Yield (group, reason, cells, slice_id) candidate rounds,
        evacuation first (sick chips beat utilization)."""
        degraded = self._degraded_nodes()
        # 1. Evacuation: gangs with bound members on degraded nodes.
        if degraded:
            for group in sorted(groups, key=lambda g: g.key()):
                if not self._migratable(group):
                    continue
                members = self._bound_members(group)
                if not members or not any(
                        p.spec.node_name in degraded for p in members):
                    continue
                target = self._find_target(cache, group, degraded)
                if target is None:
                    NO_TARGET_TOTAL.inc(reason=t.MIGRATE_REASON_DEGRADED)
                    continue  # degrade to no-op, NEVER evict
                yield (group, t.MIGRATE_REASON_DEGRADED) + target
        # 2. Defrag: a pending gang that fits nowhere + a move that
        # grows the largest free box.
        if self.defrag:
            yield from self._plan_defrag(cache, groups, degraded)

    def _plan_defrag(self, cache, groups, degraded):
        """Score candidate moves by the gain in
        ``submesh.largest_free_box_volume`` summed over the touched
        slices; only bother when some pending gang fits nowhere."""
        from ..scheduler.submesh import find_box, largest_free_box_volume
        blocked = []
        for g in groups:
            if g.spec.slice_shape and not g.status.scheduled \
                    and g.status.phase == t.PODGROUP_PENDING \
                    and not _round_open(g):
                vol = 1
                for d in g.spec.slice_shape:
                    vol *= d
                if self._find_target(cache, g, degraded) is None:
                    blocked.append((vol, g.key()))
        if not blocked:
            return
        blocked_vol = max(v for v, _ in blocked)
        free_by_slice = {}
        for slice_id, sl in cache.slices.items():
            free_by_slice[slice_id] = self._free_cells(
                cache, sl, degraded, exclude_owner="")
        before = {sid: largest_free_box_volume(
            set(cells), cache.slices[sid].mesh_shape)
            for sid, cells in free_by_slice.items()}
        best = None  # (-gain, gang key, group, cells, slice_id)
        for group in sorted(groups, key=lambda g: g.key()):
            if not self._migratable(group) or not group.spec.slice_shape:
                continue
            members = self._bound_members(group)
            if not members:
                continue
            vol = 1
            for d in group.spec.slice_shape:
                vol *= d
            if vol >= blocked_vol:
                continue  # moving an equally-large gang cannot help
            src_cells = self._member_cells(cache, members)
            if src_cells is None:
                continue
            src_slice = src_cells[1]
            for slice_id, sl_free in free_by_slice.items():
                sl = cache.slices[slice_id]
                avail = dict(sl_free)
                box = find_box(set(avail), sl.mesh_shape,
                               group.spec.slice_shape, torus=True)
                if box is None:
                    continue
                after = dict(before)
                tgt_free = set(free_by_slice[slice_id]) - set(box)
                src_free = set(free_by_slice[src_slice]) \
                    | set(src_cells[0])
                if slice_id == src_slice:
                    src_free -= set(box)
                    after[slice_id] = largest_free_box_volume(
                        src_free, sl.mesh_shape)
                else:
                    after[slice_id] = largest_free_box_volume(
                        tgt_free, sl.mesh_shape)
                    after[src_slice] = largest_free_box_volume(
                        src_free, cache.slices[src_slice].mesh_shape)
                gain = sum(after.values()) - sum(before.values())
                if gain <= 0:
                    continue
                cand = (-gain, group.key(), group,
                        {c: free_by_slice[slice_id][c] for c in box},
                        slice_id)
                if best is None or cand[:2] < best[:2]:
                    best = cand
        if best is None:
            NO_TARGET_TOTAL.inc(reason=t.MIGRATE_REASON_DEFRAG)
            return
        DEFRAG_GAIN_CHIPS.set(-best[0])
        yield best[2], t.MIGRATE_REASON_DEFRAG, best[3], best[4]

    def _member_cells(self, cache, members) -> Optional[tuple[list, str]]:
        """(coords, slice_id) the gang's bound members hold, via the
        cache's slice geometry; None when unresolvable."""
        by_node_chip = {}
        slice_of = {}
        for sl in cache.slices.values():
            for coord, (node_name, chip_id) in sl.chips.items():
                by_node_chip[(node_name, chip_id)] = coord
                slice_of[(node_name, chip_id)] = sl.slice_id
        coords = []
        slice_id = ""
        for pod in members:
            for claim in pod.spec.tpu_resources:
                for cid in claim.assigned:
                    coord = by_node_chip.get((pod.spec.node_name, cid))
                    if coord is None:
                        return None
                    coords.append(coord)
                    slice_id = slice_of[(pod.spec.node_name, cid)]
        return (coords, slice_id) if coords else None

    # -- round lifecycle ---------------------------------------------------

    def _reserve(self, cache, group: t.PodGroup, members: list[t.Pod],
                 cells: dict, slice_id: str) -> None:
        """Carve the target box in the scheduler cache (same recipe as
        gang preemption: CPU/mem pro-rated onto the box hosts so a
        squatter cannot take the host out from under the gang)."""
        from ..scheduler.cache import Reservation
        gang_prio = max((t.pod_priority(p) for p in members), default=0)
        total_req: dict = {}
        for p in members:
            for res, amt in t.pod_resource_requests(p).items():
                total_req[res] = total_req.get(res, 0.0) + amt
        chips_per_node: dict[str, int] = {}
        for _c, (node_name, _cid) in cells.items():
            chips_per_node[node_name] = chips_per_node.get(node_name, 0) + 1
        node_requests = {
            node_name: {res: amt * count / len(cells)
                        for res, amt in total_req.items()
                        if res != t.RESOURCE_TPU}
            for node_name, count in chips_per_node.items()}
        cache.reserve(Reservation(
            owner=group.key(), priority=gang_prio, slice_id=slice_id,
            cells=dict(cells), node_requests=node_requests),
            ttl=max(2 * self.round_timeout_seconds, 120.0))

    async def _start_round(self, cache, group: t.PodGroup, reason: str,
                           cells: dict, slice_id: str) -> bool:
        """Reserve FIRST, then the durable status write, then signal.
        Any failure after the reservation but before the status write
        is harmless: the unclaimed reservation just TTL-expires."""
        members = self._bound_members(group)
        if not members:
            return False
        self._reserve(cache, group, members, cells, slice_id)
        deadline = time.time() + self.round_timeout_seconds
        target_nodes = sorted({n for n, _ in cells.values()})
        target_cells = sorted(_cell_key(c) for c in cells)

        def mutate(cur: t.PodGroup):
            if _round_open(cur):
                return False  # raced another round; keep ours out
            prev = cur.status.migration
            cur.status.migration = t.MigrationStatus(
                phase=t.MIGRATE_RESERVED, reason=reason,
                target_slice=slice_id, target_cells=target_cells,
                target_nodes=target_nodes, started_time=meta_now(),
                deadline=deadline,
                rounds=prev.rounds if prev is not None else 0)
            return None

        cur = await gp._update_group_status(
            self.client, group.metadata.namespace, group.metadata.name,
            mutate)
        if cur is None:
            cache.release_reservation(group.key())
            return False
        self.recorder.event(
            group, "Normal", "MigrationReserved",
            f"{reason}: reserved {len(cells)} chips on {slice_id} "
            f"({'/'.join(target_nodes)})")
        # Chaos site "migrate": the round is durable (reservation +
        # status) — the two kinds attack the window before the bind.
        fault = _chaos_fault()
        if fault is not None and fault.kind == "crash-mid-round":
            # Simulated controller crash: drop the in-memory round on
            # the floor. The next sweep must resume purely from
            # status.migration + the cache.
            log.warning("chaos: migration controller crash mid-round "
                        "for %s", group.key())
            return True
        if fault is not None and fault.kind == "target-node-down":
            victim = target_nodes[int(fault.param) % len(target_nodes)]
            log.warning("chaos: deleting migration target node %s "
                        "between reserve and bind", victim)
            try:
                await self.client.delete("nodes", "", victim)
            except errors.StatusError:
                pass
            # Fall through: _advance_round sees the dead target and
            # aborts the round cleanly (status first, then release).
        await self._advance_round(cache, cur)
        return True

    async def _advance_round(self, cache, group: t.PodGroup) -> None:
        """Drive one open round forward — also the crash-resume path
        (sweep finds the round in status with no in-memory state)."""
        mig = group.status.migration
        gk = group.key()
        res = cache.reservations.get(gk)
        # Target nodes gone (chaos target-node-down, real node loss):
        # the landing spot no longer exists — abort before signaling
        # anything else. Known nodes = the cache's view.
        targets_alive = all(n in cache.nodes for n in mig.target_nodes)
        if not targets_alive:
            await self._abort(cache, group, "target node lost")
            return
        if time.time() > mig.deadline:
            await self._abort(cache, group, "round deadline exceeded")
            return
        members = self._bound_members(group)
        if mig.phase == t.MIGRATE_RESERVED:
            if res is None and not self._reclaim_reservation(cache, group):
                await self._abort(cache, group,
                                  "reserved box no longer available")
                return
            if not members:
                # Signaled-and-evicted before the Moving stamp landed,
                # or the gang died: treat as moving — the rebind check
                # below closes or the deadline aborts.
                await self._stamp_moving(group)
                return
            if await gp.signal_gang(self.client, group, members,
                                    reason=f"migration:{mig.reason}",
                                    recorder=self.recorder):
                await self._stamp_moving(group)
            else:
                # Engine refused (gate off / not eligible): a migration
                # must never degrade to a hard evict — abort the round.
                await self._abort(cache, group, "preemption engine refused")
            return
        # Moving: closed when the gang is bound again and the scheduler
        # consumed (released) the reservation at plan landing.
        if members and len(members) >= group.spec.min_member \
                and res is None:
            await self._close(group, "moved")
            return
        # Keep-alive: refresh the reservation TTL while the round is
        # legitimately in flight (re-reserve replaces by owner; a copy,
        # not the cached object — reserve() stamps expires in place).
        if res is not None:
            cache.reserve(dataclasses.replace(res),
                          ttl=max(2 * self.round_timeout_seconds, 120.0))
        elif not members:
            # No placement AND no reservation mid-round. The common
            # cause is the benign scheduler window between CONSUMING
            # the reservation at plan landing and the binds reaching
            # the pod store (reclaim fails then — the target cells are
            # already assumed). Re-carve from the durable target when
            # the box really is free (binds rolled back); otherwise
            # leave the round open — the rebind check above closes it
            # next pass, and the deadline aborts a round that never
            # converges. Aborting here would misread every successful
            # landing as a strand.
            self._reclaim_reservation(cache, group)

    def _reclaim_reservation(self, cache, group: t.PodGroup) -> bool:
        """Crash recovery: rebuild the reservation from the durable
        status.migration.target_cells, if the box is still free."""
        mig = group.status.migration
        sl = cache.slices.get(mig.target_slice)
        if sl is None:
            return False
        free = sl.free(cache)
        cells = {}
        for coord in _parse_cells(mig.target_cells):
            v = free.get(coord)
            if v is None:
                return False
            cells[coord] = v
        members = self._bound_members(group)
        self._reserve(cache, group, members or [], cells, mig.target_slice)
        return True

    async def _stamp_moving(self, group: t.PodGroup) -> None:
        def mutate(cur: t.PodGroup):
            if not _round_open(cur):
                return False
            cur.status.migration.phase = t.MIGRATE_MOVING
            return None

        await gp._update_group_status(
            self.client, group.metadata.namespace, group.metadata.name,
            mutate)

    async def _close(self, group: t.PodGroup, outcome: str) -> None:
        mig = group.status.migration

        def mutate(cur: t.PodGroup):
            if not _round_open(cur):
                return False
            st = cur.status.migration
            st.phase = ""
            st.outcome = outcome
            st.finished_time = meta_now()
            st.rounds += 1
            return None

        if await gp._update_group_status(
                self.client, group.metadata.namespace,
                group.metadata.name, mutate) is not None:
            ROUNDS_TOTAL.inc(reason=mig.reason, outcome=outcome)
            self.recorder.event(group, "Normal", "MigrationFinished",
                                f"{mig.reason}: {outcome}")

    async def _abort(self, cache, group: t.PodGroup, why: str) -> None:
        """Close the round, THEN release the reservation — the other
        order opens a window where the gang holds neither placement
        nor reservation inside an open round (a strand)."""
        log.info("migration round for %s aborted: %s", group.key(), why)
        await self._close(group, "aborted")
        cache.release_reservation(group.key())
