"""Garbage collector — owner-reference cascading deletion.

Reference: ``pkg/controller/garbagecollector`` (1.9k LoC): a dependency
graph over ownerReferences; when an owner disappears, its dependents
are deleted (cascading background deletion). Here the graph is the
union of informer caches over every registered resource; on each sweep
(and on any delete event) dependents whose owners are all gone are
deleted. Simpler than the reference's event graph, same invariant:
no object outlives its controller owner.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ..api import errors
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller

#: Resources swept for dependents / consulted for owner uids. Events are
#: excluded (they reference owners informally and expire on their own).
DEFAULT_WATCHED = [
    "pods", "services", "endpoints", "configmaps", "secrets", "podgroups",
    "replicasets", "deployments", "statefulsets", "daemonsets", "jobs",
    "cronjobs", "horizontalpodautoscalers", "poddisruptionbudgets",
    "resourcequotas", "limitranges", "leases", "nodes", "namespaces",
]


class GarbageCollector(Controller):
    name = "garbage-collector"

    def __init__(self, client: Client, factory: InformerFactory,
                 interval: float = 10.0, watched: Optional[list[str]] = None):
        super().__init__(client, factory, workers=1)
        self.interval = interval
        self.watched = list(watched or DEFAULT_WATCHED)
        self._informers_by_plural = {}
        for plural in self.watched:
            inf = self.watch(plural)
            self._informers_by_plural[plural] = inf
            # A deletion anywhere may orphan dependents: sweep soon.
            inf.add_handlers(on_delete=lambda obj: self.enqueue("sweep"))
        self._task: Optional[asyncio.Task] = None

    async def on_start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        await super().stop()

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            self.enqueue("sweep")

    async def sync(self, key: str) -> Optional[float]:
        await self.sweep_once()
        return None

    def _live_uids(self) -> set[str]:
        uids: set[str] = set()
        for inf in self._informers_by_plural.values():
            for obj in inf.list():
                if obj.metadata.deletion_timestamp is None:
                    uids.add(obj.metadata.uid)
        return uids

    async def sweep_once(self) -> None:
        live = self._live_uids()
        for plural, inf in self._informers_by_plural.items():
            for obj in inf.list():
                refs = obj.metadata.owner_references
                if not refs or obj.metadata.deletion_timestamp is not None:
                    continue
                # block_owner_deletion refs aside, an object whose owners
                # are ALL gone is garbage (reference: attemptToDeleteItem).
                if any(ref.uid in live for ref in refs):
                    continue
                try:
                    await self.client.delete(plural, obj.metadata.namespace,
                                             obj.metadata.name)
                except (errors.NotFoundError, errors.ConflictError):
                    pass
