"""Garbage collector — owner-reference cascading deletion.

Reference: ``pkg/controller/garbagecollector`` (1.9k LoC): a dependency
graph over ownerReferences; when an owner disappears, its dependents
are deleted (cascading background deletion). Here the graph is the
union of informer caches over every registered resource; on each sweep
(and on any delete event) dependents whose owners are all gone are
deleted. Simpler than the reference's event graph, same invariant:
no object outlives its controller owner.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ..api import errors
from ..api.meta import FINALIZER_FOREGROUND, FINALIZER_ORPHAN
from ..api.scheme import deepcopy
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller

#: Resources swept for dependents / consulted for owner uids. Events are
#: excluded (they reference owners informally and expire on their own).
DEFAULT_WATCHED = [
    "pods", "services", "endpoints", "configmaps", "secrets", "podgroups",
    "replicasets", "deployments", "statefulsets", "daemonsets", "jobs",
    "cronjobs", "horizontalpodautoscalers", "poddisruptionbudgets",
    "resourcequotas", "limitranges", "leases", "nodes", "namespaces",
]

_KIND_TO_PLURAL: dict[str, str] = {}


def _plural_by_kind() -> dict[str, str]:
    """kind -> plural, derived from the registry's resource specs (the
    same table ``client.rest`` builds) rather than naive pluralization."""
    if not _KIND_TO_PLURAL:
        from ..apiserver.registry import builtin_resources
        for spec in builtin_resources():
            _KIND_TO_PLURAL[spec.kind] = spec.plural
    return _KIND_TO_PLURAL


class GarbageCollector(Controller):
    name = "garbage-collector"

    def __init__(self, client: Client, factory: InformerFactory,
                 interval: float = 10.0, watched: Optional[list[str]] = None):
        super().__init__(client, factory, workers=1)
        self.interval = interval
        self.watched = list(watched or DEFAULT_WATCHED)
        self._informers_by_plural = {}
        for plural in self.watched:
            inf = self.watch(plural)
            self._informers_by_plural[plural] = inf
            # A deletion anywhere may orphan dependents: sweep soon.
            # An object turning terminating-with-propagation-finalizer
            # is only an UPDATE — without reacting to it, every stage
            # of an orphan/foreground cascade would wait out the full
            # sweep interval (4 stages of a Deployment tree = 40s).
            inf.add_handlers(
                on_delete=lambda obj: self.enqueue("sweep"),
                on_update=lambda old, new: self.enqueue("sweep")
                if (new.metadata.deletion_timestamp is not None
                    and (FINALIZER_ORPHAN in new.metadata.finalizers
                         or FINALIZER_FOREGROUND in new.metadata.finalizers))
                else None)
        self._task: Optional[asyncio.Task] = None

    async def on_start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        await super().stop()

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            self.enqueue("sweep")

    async def sync(self, key: str) -> Optional[float]:
        await self.sweep_once()
        return None

    def _live_uids(self) -> set[str]:
        uids: set[str] = set()
        for inf in self._informers_by_plural.values():
            for obj in inf.list():
                if (obj.metadata.deletion_timestamp is None
                        # Terminating-with-orphan counts as alive: its
                        # dependents are pending ORPHANING — collecting
                        # them before the refs are stripped would defeat
                        # the requested policy.
                        or FINALIZER_ORPHAN in obj.metadata.finalizers):
                    uids.add(obj.metadata.uid)
        return uids

    async def _owner_alive(self, ref, namespace: str) -> bool:
        """Live-read an owner ref against the API (quorum read).

        Informer caches across resources have no ordering guarantee — a
        dependent can land in the pods cache before its just-created
        owner reaches the owners' cache. The reference's
        ``attemptToDeleteItem`` confirms absence with a live read before
        deleting; do the same here.
        """
        plural = _plural_by_kind().get(ref.kind)
        if plural is None or plural not in self._informers_by_plural:
            return True  # unknown kind: never cascade on it
        try:
            owner = await self.client.get(plural, namespace, ref.name)
        except errors.NotFoundError:
            return False
        except Exception:  # noqa: BLE001 — transport/5xx/bad-ref errors
            # must not wedge the sweep; be conservative and keep the
            # dependent until a later pass can confirm.
            return True
        return (owner.metadata.uid == ref.uid
                and (owner.metadata.deletion_timestamp is None
                     or FINALIZER_ORPHAN in owner.metadata.finalizers))

    async def _live_dependents_of(self, uid: str, namespace: str) -> list:
        """Dependents confirmed against the API, not caches: clearing a
        propagation finalizer off stale caches would orphan-delete (or
        complete a foreground owner) against the requested policy — the
        same cross-cache race _owner_alive documents, on the other side.
        Only called for owners carrying a propagation finalizer, so the
        per-plural lists are rare."""
        out = []
        for plural in self._informers_by_plural:
            # A failed list must ABORT this owner's propagation (the
            # caller logs and retries next sweep): skipping the plural
            # would clear the finalizer off an incomplete dependent
            # set — orphaning nothing, or completing a foreground
            # owner whose dependents still exist.
            objs, _rev = await self.client.list(plural, namespace)
            for obj in objs:
                if any(ref.uid == uid
                       for ref in obj.metadata.owner_references):
                    out.append((plural, obj))
        return out

    async def _process_propagation(self) -> None:
        """Terminating owners carrying the orphan/foregroundDeletion
        finalizer (set by DELETE propagationPolicy; reference
        garbagecollector.go attemptToOrphan / attemptToDeleteItem's
        blocking-dependents path). Orphan: strip dependents' owner refs
        so they survive, then clear the finalizer. Foreground: delete
        dependents first (transitively foreground); the owner completes
        only when none remain. Per-owner failures are isolated — one
        webhook-rejected update must not wedge collection cluster-wide."""
        for plural, inf in self._informers_by_plural.items():
            for obj in inf.list():
                if obj.metadata.deletion_timestamp is None:
                    continue
                fins = obj.metadata.finalizers
                if (FINALIZER_ORPHAN not in fins
                        and FINALIZER_FOREGROUND not in fins):
                    continue
                try:
                    await self._propagate_one(plural, obj)
                except Exception as e:  # noqa: BLE001
                    import logging
                    logging.getLogger("garbagecollector").warning(
                        "propagation for %s/%s failed (retrying next "
                        "sweep): %s", plural, obj.metadata.name, e)

    async def _propagate_one(self, plural: str, obj) -> None:
        uid = obj.metadata.uid
        ns = obj.metadata.namespace
        if FINALIZER_ORPHAN in obj.metadata.finalizers:
            ok = True
            for dep_plural, dep in await self._live_dependents_of(uid, ns):
                patched = deepcopy(dep)
                patched.metadata.owner_references = [
                    r for r in patched.metadata.owner_references
                    if r.uid != uid]
                try:
                    await self.client.update(patched)
                except errors.ConflictError:
                    ok = False  # retry next sweep with fresh obj
                except errors.NotFoundError:
                    pass
            if ok:
                await self._clear_finalizer(plural, obj, FINALIZER_ORPHAN)
            return
        deps = await self._live_dependents_of(uid, ns)
        for dep_plural, dep in deps:
            if dep.metadata.deletion_timestamp is not None:
                continue
            try:
                # Transitive: the whole dependent TREE must be gone
                # before this owner completes (reference foreground
                # guarantee), so dependents foreground-delete too.
                await self.client.delete(
                    dep_plural, dep.metadata.namespace,
                    dep.metadata.name, uid=dep.metadata.uid,
                    propagation_policy="Foreground")
            except (errors.NotFoundError, errors.ConflictError):
                pass
        if not deps:
            await self._clear_finalizer(plural, obj, FINALIZER_FOREGROUND)

    async def _clear_finalizer(self, plural: str, obj, fin: str) -> None:
        patched = deepcopy(obj)
        patched.metadata.finalizers = [
            f for f in patched.metadata.finalizers if f != fin]
        try:
            await self.client.update(patched)
        except (errors.ConflictError, errors.NotFoundError):
            pass  # next sweep retries against fresh state

    async def sweep_once(self) -> None:
        await self._process_propagation()
        live = self._live_uids()
        for plural, inf in self._informers_by_plural.items():
            for obj in inf.list():
                refs = obj.metadata.owner_references
                if not refs or obj.metadata.deletion_timestamp is not None:
                    continue
                # block_owner_deletion refs aside, an object whose owners
                # are ALL gone is garbage (reference: attemptToDeleteItem).
                if any(ref.uid in live for ref in refs):
                    continue
                # Caches say every owner is gone — confirm against the
                # API before acting on possibly-stale caches.
                confirmed_gone = True
                for ref in refs:
                    if await self._owner_alive(ref, obj.metadata.namespace):
                        confirmed_gone = False
                        break
                if not confirmed_gone:
                    continue
                try:
                    # uid precondition: a recreated same-name object with
                    # a live owner must not be collected off stale cache.
                    await self.client.delete(plural, obj.metadata.namespace,
                                             obj.metadata.name,
                                             uid=obj.metadata.uid)
                except (errors.NotFoundError, errors.ConflictError):
                    pass
