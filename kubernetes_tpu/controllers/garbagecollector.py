"""Garbage collector — owner-reference cascading deletion.

Reference: ``pkg/controller/garbagecollector`` (1.9k LoC): a dependency
graph over ownerReferences; when an owner disappears, its dependents
are deleted (cascading background deletion). Here the graph is the
union of informer caches over every registered resource; on each sweep
(and on any delete event) dependents whose owners are all gone are
deleted. Simpler than the reference's event graph, same invariant:
no object outlives its controller owner.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ..api import errors
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller

#: Resources swept for dependents / consulted for owner uids. Events are
#: excluded (they reference owners informally and expire on their own).
DEFAULT_WATCHED = [
    "pods", "services", "endpoints", "configmaps", "secrets", "podgroups",
    "replicasets", "deployments", "statefulsets", "daemonsets", "jobs",
    "cronjobs", "horizontalpodautoscalers", "poddisruptionbudgets",
    "resourcequotas", "limitranges", "leases", "nodes", "namespaces",
]

_KIND_TO_PLURAL: dict[str, str] = {}


def _plural_by_kind() -> dict[str, str]:
    """kind -> plural, derived from the registry's resource specs (the
    same table ``client.rest`` builds) rather than naive pluralization."""
    if not _KIND_TO_PLURAL:
        from ..apiserver.registry import builtin_resources
        for spec in builtin_resources():
            _KIND_TO_PLURAL[spec.kind] = spec.plural
    return _KIND_TO_PLURAL


class GarbageCollector(Controller):
    name = "garbage-collector"

    def __init__(self, client: Client, factory: InformerFactory,
                 interval: float = 10.0, watched: Optional[list[str]] = None):
        super().__init__(client, factory, workers=1)
        self.interval = interval
        self.watched = list(watched or DEFAULT_WATCHED)
        self._informers_by_plural = {}
        for plural in self.watched:
            inf = self.watch(plural)
            self._informers_by_plural[plural] = inf
            # A deletion anywhere may orphan dependents: sweep soon.
            inf.add_handlers(on_delete=lambda obj: self.enqueue("sweep"))
        self._task: Optional[asyncio.Task] = None

    async def on_start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        await super().stop()

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            self.enqueue("sweep")

    async def sync(self, key: str) -> Optional[float]:
        await self.sweep_once()
        return None

    def _live_uids(self) -> set[str]:
        uids: set[str] = set()
        for inf in self._informers_by_plural.values():
            for obj in inf.list():
                if obj.metadata.deletion_timestamp is None:
                    uids.add(obj.metadata.uid)
        return uids

    async def _owner_alive(self, ref, namespace: str) -> bool:
        """Live-read an owner ref against the API (quorum read).

        Informer caches across resources have no ordering guarantee — a
        dependent can land in the pods cache before its just-created
        owner reaches the owners' cache. The reference's
        ``attemptToDeleteItem`` confirms absence with a live read before
        deleting; do the same here.
        """
        plural = _plural_by_kind().get(ref.kind)
        if plural is None or plural not in self._informers_by_plural:
            return True  # unknown kind: never cascade on it
        try:
            owner = await self.client.get(plural, namespace, ref.name)
        except errors.NotFoundError:
            return False
        except Exception:  # noqa: BLE001 — transport/5xx/bad-ref errors
            # must not wedge the sweep; be conservative and keep the
            # dependent until a later pass can confirm.
            return True
        return (owner.metadata.uid == ref.uid
                and owner.metadata.deletion_timestamp is None)

    async def sweep_once(self) -> None:
        live = self._live_uids()
        for plural, inf in self._informers_by_plural.items():
            for obj in inf.list():
                refs = obj.metadata.owner_references
                if not refs or obj.metadata.deletion_timestamp is not None:
                    continue
                # block_owner_deletion refs aside, an object whose owners
                # are ALL gone is garbage (reference: attemptToDeleteItem).
                if any(ref.uid in live for ref in refs):
                    continue
                # Caches say every owner is gone — confirm against the
                # API before acting on possibly-stale caches.
                confirmed_gone = True
                for ref in refs:
                    if await self._owner_alive(ref, obj.metadata.namespace):
                        confirmed_gone = False
                        break
                if not confirmed_gone:
                    continue
                try:
                    # uid precondition: a recreated same-name object with
                    # a live owner must not be collected off stale cache.
                    await self.client.delete(plural, obj.metadata.namespace,
                                             obj.metadata.name,
                                             uid=obj.metadata.uid)
                except (errors.NotFoundError, errors.ConflictError):
                    pass
