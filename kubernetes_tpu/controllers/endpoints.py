"""Endpoints controller — Service selector -> ready pod addresses.

Reference: ``pkg/controller/endpoint``: for every Service with a
selector, maintain an Endpoints object listing the IPs of ready pods
(unready pods are excluded so traffic never hits a worker that has not
finished jax init). Headless services (cluster_ip: "None") get the same
treatment — their Endpoints back the stable DNS identity StatefulSet
ranks rely on.
"""
from __future__ import annotations

from typing import Optional

from ..api import errors
from ..api import types as t
from ..api.meta import ObjectMeta, controller_ref
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller, is_pod_active, is_pod_ready


class EndpointsController(Controller):
    name = "endpoints-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 workers: int = 2):
        super().__init__(client, factory, workers)
        self.svc_informer = self.watch("services")
        self.pod_informer = self.watch("pods")
        self.svc_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n),
            on_delete=self.enqueue_obj)
        self.pod_informer.add_handlers(
            on_add=lambda p: self._enqueue_pod_services(p),
            on_update=lambda o, n: self._enqueue_pod_services(n),
            on_delete=lambda p: self._enqueue_pod_services(p))

    def _enqueue_pod_services(self, pod: t.Pod) -> None:
        for svc in self.svc_informer.list():
            if svc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = svc.spec.selector
            if sel and all(pod.metadata.labels.get(k) == v
                           for k, v in sel.items()):
                self.enqueue_obj(svc)

    async def sync(self, key: str) -> Optional[float]:
        svc = self.svc_informer.get(key)
        ns, name = (key.split("/", 1) + [""])[:2]
        if svc is None:
            # Service gone: its Endpoints goes too (also handled by GC,
            # but doing it here keeps the pair atomic-ish).
            try:
                await self.client.delete("endpoints", ns, name)
            except errors.NotFoundError:
                pass
            return None
        if not svc.spec.selector:
            return None  # manually-managed endpoints
        addresses, not_ready = [], []
        for pod in self.pod_informer.list():
            if pod.metadata.namespace != svc.metadata.namespace:
                continue
            if not all(pod.metadata.labels.get(k) == v
                       for k, v in svc.spec.selector.items()):
                continue
            if not is_pod_active(pod) or not pod.status.pod_ip:
                continue
            addr = t.EndpointAddress(
                ip=pod.status.pod_ip, node_name=pod.spec.node_name,
                hostname=pod.spec.hostname,
                target_ref=t.ObjectReference(
                    kind="Pod", namespace=pod.metadata.namespace,
                    name=pod.metadata.name, uid=pod.metadata.uid))
            (addresses if is_pod_ready(pod) else not_ready).append(addr)
        ports = [t.EndpointPort(name=p.name, port=p.target_port or p.port,
                                protocol=p.protocol)
                 for p in svc.spec.ports]
        subset = t.EndpointSubset(addresses=addresses,
                                  not_ready_addresses=not_ready, ports=ports)
        desired = t.Endpoints(
            metadata=ObjectMeta(
                name=svc.metadata.name, namespace=svc.metadata.namespace,
                owner_references=[controller_ref(svc, "core/v1", "Service")]),
            subsets=[subset] if (addresses or not_ready) else [])
        try:
            current = await self.client.get("endpoints", svc.metadata.namespace,
                                            svc.metadata.name)
            if current.subsets == desired.subsets:
                return None
            current.subsets = desired.subsets
            await self.client.update(current)
        except errors.NotFoundError:
            try:
                await self.client.create(desired)
            except errors.AlreadyExistsError:
                pass
        return None
