"""TrainJob controller — gang-scheduled multi-host training runs.

Reference shape: the Kubeflow training-operator's TrainJob/JobSet
reconciler fused with this tree's gang semantics. One TrainJob becomes:

- a **headless Service** (per-rank DNS identity — ``net/dns.py``
  answers ``<hostname>.<svc>.<ns>.svc.<domain>`` from Endpoints, so
  ``workloads/rendezvous.py`` can resolve rank 0's pod IP with no
  external coordinator),
- a **PodGroup** (all-or-nothing placement, queue/priority/elastic/
  checkpoint passthrough), and
- an **indexed worker pod set** (one pod per rank, Indexed-Job-style:
  stable hostname + ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES``/
  ``KTPU_COORD_PORT`` env) running ``workloads/trainer.py``.

**Gang recovery**: a failed member tears down the whole round —
every worker is deleted and the next sync recreates the full set
(counted against ``spec.backoff_limit``). Because the trainer
checkpoints periodically to the shared PV (the PR 7 contract), the
recreated gang *resumes* from the last completed step instead of
restarting; ``status.restart_rounds`` / ``status.resumes`` /
``status.last_checkpoint_step`` make the round durable in the API
object (rides the WAL — a restarted controller can never re-count a
round or forget one).

Everything is inert while the ``TrainJobController`` gate is off —
no API traffic, byte-identical to the ungated build.
"""
from __future__ import annotations

import logging
import os
from typing import Optional

from ..api import errors
from ..api import training as tr
from ..api import types as t
from ..api.meta import controller_ref, is_controlled_by, now
from ..api.scheme import deepcopy
from ..client.informer import InformerFactory
from ..client.interface import Client
from ..metrics.registry import Counter, Gauge
from .base import (Controller, PodControl, is_pod_active, is_pod_ready,
                   merge_container_env, rank_hostnames)

log = logging.getLogger("train")

#: trainjob_* metric families (the tpuvet fixture set): restart
#: rounds, checkpoint resumes, last durable step, and the rank-ready
#: gauge the smoke/bench read.
ROUNDS_TOTAL = Counter("trainjob_restart_rounds_total",
                       "completed gang recovery rounds",
                       labels=("trainjob",))
RESUMES_TOTAL = Counter("trainjob_resumes_total",
                        "recovery rounds that resumed from a checkpoint",
                        labels=("trainjob",))
LAST_CKPT_STEP = Gauge("trainjob_last_checkpoint_step",
                       "highest completed checkpoint step (-1 = none)",
                       labels=("trainjob",))
WORKERS_READY = Gauge("trainjob_workers_ready",
                      "worker pods currently ready",
                      labels=("trainjob",))


def _gated() -> bool:
    from ..util.features import GATES
    return GATES.enabled("TrainJobController")


def group_name(tj: tr.TrainJob) -> str:
    """Gang name — and therefore the checkpoint-directory key
    (``<base>/<ns>/<gang>`` via the agent's KTPU_JOB_NAME injection).
    UID-suffixed so the delete-and-recreate workflow the immutability
    validators mandate gets a FRESH checkpoint directory: resuming a
    new incarnation from the old job's (possibly reshaped) Orbax tree
    would crash every rank through the whole backoff budget."""
    return f"train-{tj.metadata.name}-{tj.metadata.uid[:6]}"


def service_name(tj: tr.TrainJob) -> str:
    return f"{tj.metadata.name}-workers"


class TrainJobController(Controller):
    name = "train-controller"

    def __init__(self, client: Client, factory: InformerFactory):
        super().__init__(client, factory, workers=1)
        self.pod_control = PodControl(client, self.recorder)
        #: TrainJob key -> resolved checkpoint host path. The PVC->PV
        #: host-path mapping is immutable once Bound, so re-deriving
        #: it with two API GETs on every 1s resync is pure waste;
        #: unresolved ("") results are NOT cached (binding is pending).
        self._ckpt_base: dict[str, str] = {}
        #: TrainJob keys whose headless Service is known to exist —
        #: same rationale: a per-tick existence GET per live job is
        #: pure churn for an object created once and never reconciled.
        self._svc_ensured: dict[str, None] = {}
        self.tj_informer = self.watch("trainjobs")
        self.pod_informer = self.watch("pods")
        self.group_informer = self.watch("podgroups")
        self.tj_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n),
            on_delete=self._drop_series)
        self.pod_informer.add_handlers(
            on_add=self._pod_event, on_delete=self._pod_event,
            on_update=lambda o, n: self._pod_event(n))
        self.group_informer.add_handlers(
            on_update=lambda o, n: self._group_event(n))

    def _pod_event(self, pod: t.Pod) -> None:
        tj = pod.metadata.labels.get(tr.TRAINJOB_LABEL)
        if tj:
            self.enqueue(f"{pod.metadata.namespace}/{tj}")

    def _group_event(self, group: t.PodGroup) -> None:
        self.enqueue_owner(group, "TrainJob")

    def _drop_series(self, tj) -> None:
        self._ckpt_base.pop(tj.key(), None)
        self._svc_ensured.pop(tj.key(), None)
        for m in (LAST_CKPT_STEP, WORKERS_READY,
                  ROUNDS_TOTAL, RESUMES_TOTAL):
            m.remove(trainjob=tj.key())

    # -- reconcile --------------------------------------------------------

    async def sync(self, key: str) -> Optional[float]:
        if not _gated():
            return None
        tj = self.tj_informer.get(key)
        if tj is None or tj.metadata.deletion_timestamp is not None:
            return None  # owner refs cascade Service/PodGroup/pods
        if tj.status.phase in (tr.TRAIN_SUCCEEDED, tr.TRAIN_FAILED):
            # Level-triggered teardown: the podgroup delete AND the
            # worker deletes in the terminal transition can be lost to
            # a crash — re-issuing keeps a finished gang from holding
            # its queue slot or leaking still-running members (a
            # Failed write can land with the teardown loop unexecuted).
            await self._delete_podgroup(tj)
            for p in self._member_pods(tj):
                if is_pod_active(p):
                    await self.pod_control.delete_pod(tj, p)
            return None
        await self._ensure_service(tj)
        await self._ensure_podgroup(tj)
        return await self._sync_workers(tj)

    # -- discovery substrate ---------------------------------------------

    def _selector_labels(self, tj) -> dict:
        return {tr.TRAINJOB_LABEL: tj.metadata.name}

    async def _ensure_service(self, tj) -> None:
        ns = tj.metadata.namespace
        if tj.key() in self._svc_ensured:
            return
        try:
            # GET-first (the inference controller's pattern): sync
            # polls every second while the job lives, and a guaranteed
            # 409 POST per tick is pure apiserver churn. Existence is
            # then cached — one probe per controller incarnation.
            await self.client.get("services", ns, service_name(tj))
            self._svc_ensured[tj.key()] = None
            return
        except errors.NotFoundError:
            pass
        svc = t.Service(
            metadata=t.ObjectMeta(
                name=service_name(tj), namespace=ns,
                labels=self._selector_labels(tj),
                owner_references=[controller_ref(
                    tj, tr.TRAINING_V1, "TrainJob")]),
            spec=t.ServiceSpec(
                # Headless: DNS answers per-rank A records straight
                # from Endpoints — the rendezvous substrate, no VIP.
                cluster_ip="None",
                selector=dict(self._selector_labels(tj)),
                ports=[t.ServicePort(name="coord",
                                     port=tr.coord_port(tj.spec),
                                     target_port=tr.coord_port(tj.spec))]))
        try:
            await self.client.create(svc)
            self.recorder.event(tj, "Normal", "CreatedService",
                                f"created headless service "
                                f"{service_name(tj)}")
        except errors.AlreadyExistsError:
            pass
        self._svc_ensured[tj.key()] = None

    async def _ensure_podgroup(self, tj) -> None:
        ns, name = tj.metadata.namespace, group_name(tj)
        if self.group_informer.get(f"{ns}/{name}") is not None:
            return
        s = tj.spec
        # Explicit admission demand: the queue charge must reflect the
        # real per-worker chip/CPU footprint — without this a queued
        # gang using chips_per_worker (no gang_slice_shape to fall
        # back on) would admit at ZERO charge and bypass fair share.
        resources: dict[str, float] = {
            t.RESOURCE_CPU: s.cpu_per_worker * s.num_workers}
        chips_total = tr.worker_chips(s) * s.num_workers
        if chips_total:
            resources[t.RESOURCE_TPU] = float(chips_total)
        group = t.PodGroup(
            metadata=t.ObjectMeta(
                name=name, namespace=ns,
                owner_references=[controller_ref(
                    tj, tr.TRAINING_V1, "TrainJob")]),
            spec=t.PodGroupSpec(
                # Elastic gangs quorum at their minimum viable size
                # (validation requires min_member <= min_replicas);
                # fixed gangs are all-or-nothing at full size.
                min_member=s.min_workers or s.num_workers,
                slice_shape=list(s.gang_slice_shape),
                priority=s.priority,
                queue=s.queue,
                resources=resources,
                min_replicas=s.min_workers,
                max_replicas=s.max_workers))
        if s.checkpoint.grace_seconds > 0:
            group.spec.checkpoint = t.CheckpointSpec(
                grace_seconds=s.checkpoint.grace_seconds)
        try:
            await self.client.create(group)
        except errors.AlreadyExistsError:
            pass

    async def _delete_podgroup(self, tj) -> None:
        """Terminal TrainJob: release the gang's QUEUE hold — a queued
        PodGroup's lifetime IS its quota charge (the Job controller's
        rule). Unqueued groups stay for observability (`ktl trace
        gang`, `describe podgroup`) and ride owner-ref GC when the
        TrainJob itself is deleted."""
        from ..util.features import GATES
        if not GATES.enabled("JobQueueing"):
            return  # no admission machinery = no quota hold to release
        ns = tj.metadata.namespace
        group = self.group_informer.get(f"{ns}/{group_name(tj)}")
        if group is None or not group.spec.queue:
            return
        try:
            await self.client.delete("podgroups", ns, group_name(tj))
        except errors.NotFoundError:
            pass

    # -- checkpoint contract ----------------------------------------------

    async def _checkpoint_base(self, tj) -> str:
        """Host path of the shared checkpoint volume (the PR 7
        contract): PVC -> bound PV -> host_path. "" while unbound or
        claimless — workers then fall back to the node-local default
        base and resume only survives same-node restarts."""
        claim = tj.spec.checkpoint.pvc
        if not claim:
            return ""
        cached = self._ckpt_base.get(tj.key())
        if cached:
            return cached
        try:
            pvc = await self.client.get(
                "persistentvolumeclaims", tj.metadata.namespace, claim)
        except errors.NotFoundError:
            return ""
        if pvc.status.phase != t.PVC_BOUND or not pvc.spec.volume_name:
            return ""
        try:
            pv = await self.client.get(
                "persistentvolumes", "", pvc.spec.volume_name)
        except errors.NotFoundError:
            return ""
        if pv.spec.host_path is not None:
            self._ckpt_base[tj.key()] = pv.spec.host_path.path
            return pv.spec.host_path.path
        return ""

    def _ckpt_dir(self, tj, base: str) -> str:
        """The exact path every worker computes (checkpoint.py
        checkpoint_dir: <base>/<KTPU_JOB_NAME>, job = <ns>/<gang>)."""
        from ..preemption import job_checkpoint_dir
        return job_checkpoint_dir(
            f"{tj.metadata.namespace}/{group_name(tj)}", base)

    def _marker_step(self, tj, base: str) -> int:
        """Best-effort read of the trainer-published checkpoint-
        complete marker on the shared volume (single-binary / co-hosted
        deployments; a remote controller-manager reads -1 here and
        falls back to the PodGroup's durable preemption step)."""
        if not base:
            return -1
        from ..preemption import read_marker
        step = read_marker(self._ckpt_dir(tj, base))  # None-safe reader
        return step if step is not None else -1

    # -- worker pods -------------------------------------------------------

    def _worker_pod(self, tj, rank: int, ckpt_base: str,
                    world: int) -> t.Pod:
        import sys
        s = tj.spec
        name, ns = tj.metadata.name, tj.metadata.namespace
        container = t.Container(
            name="trainer", image=s.image,
            command=[sys.executable, "-m",
                     "kubernetes_tpu.workloads.trainer"],
            resources=t.ResourceRequirements(
                requests={t.RESOURCE_CPU: s.cpu_per_worker}))
        chips = tr.worker_chips(s)
        pod_spec = t.PodSpec(
            restart_policy=t.RESTART_NEVER,
            hostname=f"{name}-{rank}",
            subdomain=service_name(tj),
            gang=group_name(tj),
            # Recovery rounds wait for the FULL old round to leave the
            # store before recreating; the trainer exits promptly on
            # SIGTERM (durability comes from the periodic saves + the
            # preemption protocol, not eviction grace), so the default
            # 30s would just stall every round restart.
            termination_grace_period_seconds=5,
            containers=[container])
        if chips > 0:
            pod_spec.tpu_resources = [t.PodTpuRequest(
                name="tpu", chips=chips, slice_shape=list(s.slice_shape))]
            container.tpu_requests = ["tpu"]
        if s.checkpoint.pvc:
            # The shared checkpoint volume rides the pod spec (a PVC
            # that never binds fails the start visibly — FailedMount —
            # instead of silently training without durability).
            pod_spec.volumes = [t.Volume(
                name="ckpt", persistent_volume_claim=t.
                PersistentVolumeClaimVolume(claim_name=s.checkpoint.pvc))]
            container.volume_mounts = [t.VolumeMount(
                name="ckpt", mount_path="/ckpt")]
        # Framework rank env (the rendezvous contract) goes FIRST:
        # spec.args is merged after, so a colliding user value can
        # never scramble a rank's identity or coordinator address.
        rank_env = [
            t.EnvVar(name="TPU_WORKER_ID", value=str(rank)),
            t.EnvVar(name="TPU_WORKER_HOSTNAMES", value=rank_hostnames(
                name, world, service_name(tj), ns)),
            t.EnvVar(name="KTPU_COORD_PORT",
                     value=str(tr.coord_port(s))),
            t.EnvVar(name="MODEL", value=s.model),
            t.EnvVar(name="TOTAL_STEPS", value=str(tr.total_steps(s))),
            t.EnvVar(name="CHECKPOINT_EVERY",
                     value=str(tr.checkpoint_every(s))),
        ]
        if s.batch > 0:
            rank_env.append(t.EnvVar(name="BATCH", value=str(s.batch)))
        if s.seq > 0:
            rank_env.append(t.EnvVar(name="SEQ", value=str(s.seq)))
        if ckpt_base:
            # Every member and every incarnation computes the same
            # <base>/<ns>/<gang> dir (workloads/checkpoint.py) — the
            # agent-injected KTPU_JOB_NAME supplies the tail.
            rank_env.append(t.EnvVar(name="KTPU_CHECKPOINT_DIR",
                                     value=ckpt_base))
        trace = os.environ.get("KTPU_TRACE", "")
        if trace:
            rank_env.append(t.EnvVar(name="KTPU_TRACE", value=trace))
        container.env = rank_env
        merge_container_env(
            [container],
            [t.EnvVar(name=k, value=v) for k, v in sorted(s.args.items())])
        return t.Pod(
            metadata=t.ObjectMeta(
                generate_name=f"{name}-{rank}-", namespace=ns,
                labels={**self._selector_labels(tj),
                        tr.RANK_LABEL: str(rank),
                        tr.WORLD_LABEL: str(world)},
                owner_references=[controller_ref(
                    tj, tr.TRAINING_V1, "TrainJob")]),
            spec=pod_spec)

    def _member_pods(self, tj) -> list[t.Pod]:
        name, ns = tj.metadata.name, tj.metadata.namespace
        return [p for p in self.pod_informer.list()
                if p.metadata.namespace == ns
                and p.metadata.labels.get(tr.TRAINJOB_LABEL) == name
                and is_controlled_by(p, tj)]

    def _elastic_world(self, tj) -> int:
        """The world size the NEXT gang round runs at: the PodGroup's
        elastic target (fair-share shrink lowers it, regrow raises it)
        clamped to [1, num_workers]; fixed-size gangs always run full.
        A shrunk round trains a smaller jax.distributed world resuming
        from the shared checkpoint — not a crash-looping full gang the
        scheduler will never fully bind."""
        s = tj.spec
        if not s.min_workers:
            return s.num_workers
        group = self.group_informer.get(
            f"{tj.metadata.namespace}/{group_name(tj)}")
        target = group.status.replicas if group is not None else 0
        if target <= 0:
            target = s.num_workers
        return max(1, min(int(target), s.num_workers))

    async def _sync_workers(self, tj) -> Optional[float]:
        s = tj.spec
        pods = self._member_pods(tj)
        active = [p for p in pods if is_pod_active(p)]
        failed = [p for p in pods if p.status.phase == t.POD_FAILED]
        ckpt_base = await self._checkpoint_base(tj)
        ckpt_step = self._progress_step(tj, ckpt_base)
        world = self._elastic_world(tj)

        # Completion: every rank OF THE ROUND'S WORLD has a Succeeded
        # record (a shrunk elastic gang completes at its shrunk size —
        # the checkpointed work, not the headcount, is the job).
        done_ranks = {p.metadata.labels.get(tr.RANK_LABEL)
                      for p in pods if p.status.phase == t.POD_SUCCEEDED}
        done_world = min(int(p.metadata.labels.get(tr.WORLD_LABEL,
                                                   s.num_workers))
                         for p in pods
                         if p.status.phase == t.POD_SUCCEEDED) \
            if done_ranks else s.num_workers
        if len(done_ranks) >= done_world:
            await self._update_status(tj, pods, tr.TRAIN_SUCCEEDED,
                                      ckpt_step, message="all ranks "
                                      "completed")
            self.recorder.event(tj, "Normal", "Completed",
                                f"all {done_world} ranks completed")
            await self._delete_podgroup(tj)
            return None

        # Gang recovery: a failed member kills the round. The status
        # write (rounds += 1, phase=Recovering) is the DURABLE round
        # marker and lands BEFORE any delete — a controller crash
        # mid-teardown resumes the round instead of re-counting it.
        if failed:
            if tj.status.phase != tr.TRAIN_RECOVERING:
                if tj.status.restart_rounds + 1 > s.backoff_limit:
                    tj = await self._update_status(
                        tj, pods, tr.TRAIN_FAILED, ckpt_step,
                        message=f"member failed and restart budget "
                                f"({s.backoff_limit}) is exhausted")
                    if tj.status.phase != tr.TRAIN_FAILED:
                        # Same discipline as the Recovering branch:
                        # the terminal phase must be DURABLE before
                        # any teardown — a conflict-lost write here
                        # would let the next sync recreate a gang
                        # past its restart budget.
                        return 0.05
                    self.recorder.event(tj, "Warning", "BackoffLimit",
                                        "gang restart budget exhausted")
                    for p in active:
                        await self.pod_control.delete_pod(tj, p)
                    await self._delete_podgroup(tj)
                    return None
                resumed = ckpt_step >= 0
                want_rounds = tj.status.restart_rounds + 1
                tj = await self._update_status(
                    tj, pods, tr.TRAIN_RECOVERING, ckpt_step,
                    rounds=want_rounds,
                    resumes=tj.status.resumes + (1 if resumed else 0),
                    message=f"member {failed[0].metadata.name} failed; "
                            f"restarting the gang"
                            + (f" (resuming from step {ckpt_step})"
                               if resumed else " (no checkpoint yet)"))
                if tj.status.restart_rounds != want_rounds:
                    return 0.05  # stale copy lost the write; re-sync
                ROUNDS_TOTAL.inc(trainjob=tj.key())
                if resumed:
                    RESUMES_TOTAL.inc(trainjob=tj.key())
                self.recorder.event(
                    tj, "Warning", "GangMemberFailed",
                    f"tearing down the gang for atomic restart "
                    f"(round {tj.status.restart_rounds})")
                if resumed:
                    self.recorder.event(
                        tj, "Normal", "ResumingFromCheckpoint",
                        f"round {tj.status.restart_rounds} will resume "
                        f"from checkpoint step {ckpt_step}")
            # The WHOLE round goes — succeeded ranks too: a recreated
            # gang rendezvouses at full world size (a missing "done"
            # rank would wedge every peer's initialize), and resume
            # from the shared checkpoint makes re-running them cheap.
            for p in pods:
                await self.pod_control.delete_pod(tj, p)
            return 0.5  # poll the teardown; recreate next pass

        # Mid-recovery: the WHOLE previous round must actually be gone
        # before any recreate. Creating replacements beside a still-
        # Terminating survivor would run two processes for one rank
        # (same checkpoint dir, and peers can dial the OLD coordinator
        # and wedge their rendezvous), and a lingering Succeeded pod
        # would hold its rank out of the new gang's world.
        if tj.status.phase == tr.TRAIN_RECOVERING and pods:
            for p in pods:
                await self.pod_control.delete_pod(tj, p)
            return 0.5

        # A declared checkpoint PVC must be BOUND before any worker
        # exists: the resolved host path rides the pod env, which is
        # frozen at creation — a pod created early would silently
        # checkpoint to the node-local default and resume would find
        # nothing on the shared volume after a recovery round.
        if s.checkpoint.pvc and not ckpt_base and not active:
            await self._update_status(
                tj, pods, tr.TRAIN_PENDING, ckpt_step,
                message=f"waiting for checkpoint pvc/"
                        f"{s.checkpoint.pvc} to bind")
            return 0.5

        # Elastic resize: a live gang built for a DIFFERENT world than
        # the current target restarts as a unit (world size is frozen
        # into every member's rendezvous env). Not counted against
        # backoff_limit — a reclaim shrink or an idle-quota regrow is
        # policy, not a failure; resume from the shared checkpoint
        # makes the restart cheap.
        stale_world = [p for p in active
                       if p.metadata.labels.get(tr.WORLD_LABEL)
                       not in ("", None, str(world))]
        if stale_world:
            if tj.status.phase != tr.TRAIN_RECOVERING:
                tj = await self._update_status(
                    tj, pods, tr.TRAIN_RECOVERING, ckpt_step,
                    message=f"resizing gang to {world} workers "
                            f"(elastic target moved)")
                if tj.status.phase != tr.TRAIN_RECOVERING:
                    return 0.05  # stale copy lost the write; re-sync
                self.recorder.event(
                    tj, "Normal", "GangResize",
                    f"restarting the gang at world size {world}")
            for p in pods:
                await self.pod_control.delete_pod(tj, p)
            return 0.5

        # Round teardown finished (or first pass): create missing ranks.
        live_ranks = {p.metadata.labels.get(tr.RANK_LABEL)
                      for p in active}
        # One live pod per rank: reap duplicates from stale-cache
        # double creates, oldest wins (the Job controller's rule).
        by_rank: dict[str, list] = {}
        for p in active:
            by_rank.setdefault(
                p.metadata.labels.get(tr.RANK_LABEL, ""), []).append(p)
        for rank, grp in by_rank.items():
            grp.sort(key=lambda p: (
                p.metadata.creation_timestamp.timestamp()
                if p.metadata.creation_timestamp else 0.0))
            for dup in grp[1:]:
                await self.pod_control.delete_pod(tj, dup)
        for rank in range(world):
            if str(rank) in live_ranks or str(rank) in done_ranks:
                continue
            pod = self._worker_pod(tj, rank, ckpt_base, world)
            await self.client.create(pod)
        # A rank counts toward the gang when it is RUNNING or already
        # finished — ranks exit independently after the final step, so
        # a half-complete healthy job must not regress to Pending.
        running_ranks = {p.metadata.labels.get(tr.RANK_LABEL)
                         for p in active
                         if p.status.phase == t.POD_RUNNING}
        phase = tr.TRAIN_RUNNING if (
            len(running_ranks | done_ranks) >= world
            and running_ranks) else tr.TRAIN_PENDING
        await self._update_status(tj, self._member_pods(tj), phase,
                                  ckpt_step)
        # Poll while live: the checkpoint marker advances outside the
        # API (shared volume), and completion needs a timely read.
        return 1.0

    def _progress_step(self, tj, ckpt_base: str) -> int:
        """Durable progress: the trainer's marker on the shared volume
        when readable, else the PodGroup's preemption checkpoint step;
        never below what status already recorded (monotonic)."""
        step = self._marker_step(tj, ckpt_base)
        group = self.group_informer.get(
            f"{tj.metadata.namespace}/{group_name(tj)}")
        if group is not None and group.status.preemption is not None:
            step = max(step, group.status.preemption.checkpoint_step)
        return max(step, tj.status.last_checkpoint_step)

    # -- status ------------------------------------------------------------

    async def _update_status(self, tj, pods, phase: str, ckpt_step: int,
                             rounds: Optional[int] = None,
                             resumes: Optional[int] = None,
                             message: str = ""):
        s = tj.spec
        states: dict[str, str] = {}
        for rank in range(s.num_workers):
            states[str(rank)] = "Missing"
        ready_ranks: set[str] = set()
        for p in sorted(pods, key=lambda p: (
                p.metadata.creation_timestamp.timestamp()
                if p.metadata.creation_timestamp else 0.0)):
            rank = p.metadata.labels.get(tr.RANK_LABEL, "")
            if rank not in states:
                continue
            if p.status.phase == t.POD_SUCCEEDED:
                states[rank] = "Succeeded"
            elif p.status.phase == t.POD_FAILED:
                if states[rank] == "Missing":
                    states[rank] = "Failed"
            elif is_pod_active(p):
                states[rank] = p.status.phase or "Pending"
                if is_pod_ready(p):
                    # Per RANK, not per pod: a not-yet-reaped
                    # duplicate must not inflate readiness past the
                    # gang size.
                    ready_ranks.add(rank)
        ready = len(ready_ranks)
        active = [p for p in pods if is_pod_active(p)]
        new = tr.TrainJobStatus(
            phase=phase,
            workers=len(active),
            ready_workers=ready,
            succeeded_workers=sum(
                1 for v in states.values() if v == "Succeeded"),
            worker_states=states,
            restart_rounds=(rounds if rounds is not None
                            else tj.status.restart_rounds),
            resumes=(resumes if resumes is not None
                     else tj.status.resumes),
            last_checkpoint_step=max(ckpt_step,
                                     tj.status.last_checkpoint_step),
            start_time=tj.status.start_time or now(),
            completion_time=tj.status.completion_time,
            message=message or tj.status.message)
        if phase in (tr.TRAIN_SUCCEEDED, tr.TRAIN_FAILED) \
                and new.completion_time is None:
            new.completion_time = now()
        LAST_CKPT_STEP.set(new.last_checkpoint_step, trainjob=tj.key())
        WORKERS_READY.set(ready, trainjob=tj.key())
        if new == tj.status:
            return tj
        fresh = deepcopy(tj)
        fresh.status = new
        try:
            updated = await self.client.update(fresh, subresource="status")
            return updated
        except (errors.ConflictError, errors.NotFoundError):
            return tj
