"""Horizontal pod autoscaler controller.

Reference: ``pkg/controller/podautoscaler`` (1.5k LoC): every sync
period read the scale target's current replica count and the pods' cpu
utilization, compute

    desired = ceil(current * currentUtilization / targetUtilization)

clamp to [min, max], and write the target's replicas. The reference
reads heapster; here the metrics source is pluggable — the default
reads the node agents' reported per-pod usage from a pod annotation
(``metrics.tpu/cpu-utilization-percent``), and the libtpu metrics
pipeline can swap in a real source.
"""
from __future__ import annotations

import math
from typing import Callable, Optional

from ..api import errors
from ..api import types as t
from ..api import workloads as w
from ..api.meta import now
from ..api.scheme import deepcopy
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller, is_pod_active

UTIL_ANNOTATION = "metrics.tpu/cpu-utilization-percent"

#: Scale only when desired/current departs from 1.0 by more than this
#: (reference: --horizontal-pod-autoscaler-tolerance, 0.1).
TOLERANCE = 0.1

MetricsSource = Callable[[t.Pod], Optional[float]]


def annotation_metrics(pod: t.Pod) -> Optional[float]:
    raw = pod.metadata.annotations.get(UTIL_ANNOTATION)
    try:
        return float(raw) if raw is not None else None
    except ValueError:
        return None


class HorizontalPodAutoscalerController(Controller):
    name = "horizontal-pod-autoscaler"

    def __init__(self, client: Client, factory: InformerFactory,
                 metrics: MetricsSource = annotation_metrics,
                 sync_period: float = 15.0):
        super().__init__(client, factory, workers=1)
        self.metrics = metrics
        self.sync_period = sync_period
        self.hpa_informer = self.watch("horizontalpodautoscalers")
        self.pod_informer = self.watch("pods")
        self.hpa_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n))

    async def sync(self, key: str) -> Optional[float]:
        hpa = self.hpa_informer.get(key)
        if hpa is None:
            return None
        ref = hpa.spec.scale_target_ref
        plural = {"Deployment": "deployments", "ReplicaSet": "replicasets",
                  "StatefulSet": "statefulsets"}.get(ref.kind)
        if plural is None:
            return None
        try:
            target = await self.client.get(plural, hpa.metadata.namespace,
                                           ref.name)
        except errors.NotFoundError:
            return self.sync_period
        current = target.spec.replicas
        selector = target.spec.selector
        utils = []
        matched = 0
        for pod in self.pod_informer.list():
            if pod.metadata.namespace != hpa.metadata.namespace:
                continue
            if selector is not None and not selector.matches(
                    pod.metadata.labels):
                continue
            if not is_pod_active(pod):
                continue
            matched += 1
            u = self.metrics(pod)
            if u is not None:
                utils.append(u)
        if not utils or current == 0:
            return self.sync_period
        target_util = max(hpa.spec.target_cpu_utilization_percentage, 1)
        avg = sum(utils) / len(utils)
        ratio = avg / target_util
        # Reference replica_calculator.go:122 GetResourceReplicas:
        # desired = ceil(usageRatio * measuredPodCount) — NOT
        # spec.replicas, which compounds the ratio while actual pods lag
        # desired and runs away to max. Pods without metrics are folded
        # back in conservatively: assumed 0% when scaling up and at
        # target when scaling down, so freshly-created pods that haven't
        # reported yet can't trigger a spurious scale-down (or amplify a
        # scale-up).
        missing = max(matched - len(utils), 0)
        if abs(ratio - 1.0) <= TOLERANCE:
            desired = current
        elif missing == 0:
            desired = math.ceil(len(utils) * ratio)
        else:
            assumed = 0.0 if ratio > 1.0 else float(target_util)
            total_pods = len(utils) + missing
            new_ratio = ((sum(utils) + assumed * missing)
                         / (total_pods * target_util))
            if abs(new_ratio - 1.0) <= TOLERANCE or \
                    (new_ratio > 1.0) != (ratio > 1.0):
                desired = current
            else:
                desired = math.ceil(total_pods * new_ratio)
        # Never scale DOWN on an over-target signal: while actual pods
        # lag spec.replicas (controller still creating them), the
        # measured count alone would shrink an overloaded workload (the
        # reference gates this with a downscale-stabilization window).
        if ratio > 1.0 + TOLERANCE:
            desired = max(desired, current)
        desired = max(hpa.spec.min_replicas, min(hpa.spec.max_replicas,
                                                 desired))
        if desired != current:
            fresh = deepcopy(target)
            fresh.spec.replicas = desired
            try:
                await self.client.update(fresh)
                self.recorder.event(
                    hpa, "Normal", "SuccessfulRescale",
                    f"scaled {ref.kind}/{ref.name} {current} -> {desired} "
                    f"(cpu {avg:.0f}%)")
            except (errors.ConflictError, errors.NotFoundError):
                return 0.5
        fresh_hpa = deepcopy(hpa)
        fresh_hpa.status = w.HorizontalPodAutoscalerStatus(
            current_replicas=current, desired_replicas=desired,
            current_cpu_utilization_percentage=int(avg),
            last_scale_time=now() if desired != current
            else hpa.status.last_scale_time)
        if fresh_hpa.status != hpa.status:
            try:
                await self.client.update(fresh_hpa, subresource="status")
            except (errors.ConflictError, errors.NotFoundError):
                pass
        return self.sync_period
