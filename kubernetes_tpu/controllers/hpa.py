"""Horizontal pod autoscaler controller.

Reference: ``pkg/controller/podautoscaler`` (1.5k LoC): every sync
period read the scale target's current replica count and the pods' cpu
utilization, compute

    desired = ceil(current * currentUtilization / targetUtilization)

clamp to [min, max], and write the target's replicas. The reference
reads heapster; here the DEFAULT source is the real pipeline — the
node agents' ``/stats/summary`` scraped through DaemonEndpoints, the
same path ``ktl top`` uses — with utilization derived as
rate(cpu_seconds) over the pod's requested cores. The annotation
source (``metrics.tpu/cpu-utilization-percent``) remains for tests
and simulations.
"""
from __future__ import annotations

import inspect
import logging
import math
import time
from typing import Awaitable, Callable, Optional, Union

from ..api import errors
from ..api import types as t
from ..api import workloads as w
from ..api.meta import now
from ..api.scheme import deepcopy
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller, is_pod_active

log = logging.getLogger("hpa")

UTIL_ANNOTATION = "metrics.tpu/cpu-utilization-percent"

#: Scale only when desired/current departs from 1.0 by more than this
#: (reference: --horizontal-pod-autoscaler-tolerance, 0.1).
TOLERANCE = 0.1

#: Sync (annotation/tests) or async (real scrape) per-pod utilization%.
MetricsSource = Callable[[t.Pod],
                         Union[Optional[float],
                               Awaitable[Optional[float]]]]


def annotation_metrics(pod: t.Pod) -> Optional[float]:
    """Test/simulation source: utilization% from a pod annotation."""
    raw = pod.metadata.annotations.get(UTIL_ANNOTATION)
    try:
        return float(raw) if raw is not None else None
    except ValueError:
        return None


class SummaryMetricsSource:
    """The real pipeline: per-pod cpu_seconds from each node agent's
    ``/stats/summary`` (found via Node.status.daemon_endpoints — the
    ``ktl top`` path), utilization% = Δcpu_seconds/Δwall over the
    pod's requested cores. Needs two samples before it reports (rate,
    not level); node scrapes are cached ``ttl`` seconds so N pods on
    one node cost one GET per sync wave.

    ``ssl_context``: cluster credentials for TLS node servers; when
    absent, ``client.ssl_context`` is used, and a TLS node with NO
    credentials is refused (nodeaccess policy) — fabricated metrics
    from an unverified channel are worse than none.
    """

    def __init__(self, client: Client, ssl_context=None, ttl: float = 10.0):
        self.client = client
        if ssl_context is not None:
            # nodeaccess reads credentials off the client; an
            # EXPLICIT context always wins (the composer builds it for
            # node-serving-cert specifics, e.g. hostname policy).
            client = _ClientWithSSL(client, ssl_context)
            self.client = client
        self.ttl = ttl
        #: node name -> (scrape monotonic ts, {pod uid: cpu_seconds})
        self._scrapes: dict[str, tuple[float, dict]] = {}
        #: pod uid -> (sample scrape ts, cpu_seconds) previous sample —
        #: keyed by the SCRAPE timestamp, so a re-read inside the cache
        #: TTL yields "no new sample" (None), never a spurious 0% rate.
        self._prev: dict[str, tuple[float, float]] = {}

    async def _node_pods_cpu(self, node_name: str) -> tuple[float, dict]:
        cached = self._scrapes.get(node_name)
        if cached is not None and time.monotonic() - cached[0] < self.ttl:
            return cached
        from ..client.nodeaccess import resolve_node_agent, ssl_kw
        usage: dict[str, float] = {}
        conn = await resolve_node_agent(self.client, node_name)
        if conn is not None:
            base, ssl_ctx = conn
            import aiohttp
            try:
                async with aiohttp.ClientSession() as s:
                    async with s.get(f"{base}/stats/summary",
                                     timeout=aiohttp.ClientTimeout(total=3),
                                     **ssl_kw(ssl_ctx)) as r:
                        if r.status == 200:
                            summary = await r.json()
                            for p in summary.get("pods", []):
                                usage[p["pod"]["uid"]] = float(
                                    p.get("cpu_seconds", 0.0))
            except Exception as e:  # noqa: BLE001 — node unreachable
                log.warning("hpa: stats scrape of node %s failed, no "
                            "samples this round: %s", node_name, e)
        entry = (time.monotonic(), usage)
        self._scrapes[node_name] = entry
        # Prune: stale node scrapes first (departed nodes must not pin
        # their dead pods as "live"), then rate state for pods absent
        # from every fresh scrape — long-running managers must not
        # leak one entry per pod uid ever seen.
        now_m = time.monotonic()
        for name in [n for n, (ts, _) in self._scrapes.items()
                     if now_m - ts > 5 * self.ttl]:
            del self._scrapes[name]
        if len(self._prev) > 4096:
            live = {uid for _, u in self._scrapes.values() for uid in u}
            for uid in [u for u in self._prev if u not in live]:
                del self._prev[uid]
        return entry

    async def __call__(self, pod: t.Pod) -> Optional[float]:
        if not pod.spec.node_name:
            return None
        requested = t.pod_resource_requests(pod).get(t.RESOURCE_CPU, 0.0)
        if requested <= 0:
            return None  # reference: no request, no utilization%
        scrape_ts, usage = await self._node_pods_cpu(pod.spec.node_name)
        cpu_s = usage.get(pod.metadata.uid)
        if cpu_s is None:
            return None
        prev = self._prev.get(pod.metadata.uid)
        if prev is not None and prev[0] == scrape_ts:
            return None  # same sample as last time: no rate yet
        self._prev[pod.metadata.uid] = (scrape_ts, cpu_s)
        if prev is None or scrape_ts - prev[0] <= 0:
            return None  # first sample: a rate needs two points
        if cpu_s < prev[1]:
            # Counter RESET (agent/container restart): a fabricated 0%
            # would read as a real measurement and could scale down a
            # busy workload — report "no sample" instead.
            return None
        rate = (cpu_s - prev[1]) / (scrape_ts - prev[0])
        return 100.0 * rate / requested


class _ClientWithSSL:
    """Wrap a Client with an explicit ssl_context attribute for
    nodeaccess (LocalClient has none; the composer supplies creds)."""

    def __init__(self, inner, ssl_context):
        self._inner = inner
        self.ssl_context = ssl_context

    def __getattr__(self, name):
        return getattr(self._inner, name)


class HorizontalPodAutoscalerController(Controller):
    name = "horizontal-pod-autoscaler"

    def __init__(self, client: Client, factory: InformerFactory,
                 metrics: Optional[MetricsSource] = None,
                 sync_period: float = 15.0):
        super().__init__(client, factory, workers=1)
        #: Default: the REAL pipeline (node /stats/summary). Pass
        #: ``annotation_metrics`` for tests/simulations.
        self.metrics = metrics or SummaryMetricsSource(
            client, ssl_context=getattr(client, "ssl_context", None))
        self.sync_period = sync_period
        self.hpa_informer = self.watch("horizontalpodautoscalers")
        self.pod_informer = self.watch("pods")
        self.hpa_informer.add_handlers(
            on_add=self.enqueue_obj,
            on_update=lambda o, n: self.enqueue_obj(n))

    async def sync(self, key: str) -> Optional[float]:
        hpa = self.hpa_informer.get(key)
        if hpa is None:
            return None
        ref = hpa.spec.scale_target_ref
        plural = {"Deployment": "deployments", "ReplicaSet": "replicasets",
                  "StatefulSet": "statefulsets"}.get(ref.kind)
        if plural is None:
            return None
        try:
            target = await self.client.get(plural, hpa.metadata.namespace,
                                           ref.name)
        except errors.NotFoundError:
            return self.sync_period
        current = target.spec.replicas
        selector = target.spec.selector
        utils = []
        matched = 0
        for pod in self.pod_informer.list():
            if pod.metadata.namespace != hpa.metadata.namespace:
                continue
            if selector is not None and not selector.matches(
                    pod.metadata.labels):
                continue
            if not is_pod_active(pod):
                continue
            matched += 1
            u = self.metrics(pod)
            if inspect.isawaitable(u):  # async source (real scrape)
                u = await u
            if u is not None:
                utils.append(u)
        if not utils or current == 0:
            return self.sync_period
        target_util = max(hpa.spec.target_cpu_utilization_percentage, 1)
        avg = sum(utils) / len(utils)
        ratio = avg / target_util
        # Reference replica_calculator.go:122 GetResourceReplicas:
        # desired = ceil(usageRatio * measuredPodCount) — NOT
        # spec.replicas, which compounds the ratio while actual pods lag
        # desired and runs away to max. Pods without metrics are folded
        # back in conservatively: assumed 0% when scaling up and at
        # target when scaling down, so freshly-created pods that haven't
        # reported yet can't trigger a spurious scale-down (or amplify a
        # scale-up).
        missing = max(matched - len(utils), 0)
        if abs(ratio - 1.0) <= TOLERANCE:
            desired = current
        elif missing == 0:
            desired = math.ceil(len(utils) * ratio)
        else:
            assumed = 0.0 if ratio > 1.0 else float(target_util)
            total_pods = len(utils) + missing
            new_ratio = ((sum(utils) + assumed * missing)
                         / (total_pods * target_util))
            if abs(new_ratio - 1.0) <= TOLERANCE or \
                    (new_ratio > 1.0) != (ratio > 1.0):
                desired = current
            else:
                desired = math.ceil(total_pods * new_ratio)
        # Never scale DOWN on an over-target signal: while actual pods
        # lag spec.replicas (controller still creating them), the
        # measured count alone would shrink an overloaded workload (the
        # reference gates this with a downscale-stabilization window).
        if ratio > 1.0 + TOLERANCE:
            desired = max(desired, current)
        desired = max(hpa.spec.min_replicas, min(hpa.spec.max_replicas,
                                                 desired))
        if desired != current:
            fresh = deepcopy(target)
            fresh.spec.replicas = desired
            try:
                await self.client.update(fresh)
                self.recorder.event(
                    hpa, "Normal", "SuccessfulRescale",
                    f"scaled {ref.kind}/{ref.name} {current} -> {desired} "
                    f"(cpu {avg:.0f}%)")
            except (errors.ConflictError, errors.NotFoundError):
                return 0.5
        fresh_hpa = deepcopy(hpa)
        fresh_hpa.status = w.HorizontalPodAutoscalerStatus(
            current_replicas=current, desired_replicas=desired,
            current_cpu_utilization_percentage=int(avg),
            last_scale_time=now() if desired != current
            else hpa.status.last_scale_time)
        if fresh_hpa.status != hpa.status:
            try:
                await self.client.update(fresh_hpa, subresource="status")
            except (errors.ConflictError, errors.NotFoundError):
                pass
        return self.sync_period
