"""QueueController — multi-tenant fair-share admission for gang jobs.

The Kueue-analog admission layer (ISSUE 5 / arXiv:2510.01256): every
PodGroup carrying ``spec.queue`` is born SUSPENDED — the scheduler's
gang staging never releases it into the heap (scheduler/queue.py) —
until this controller admits it against its tenant's ClusterQueue
quota. One global admission pass (single worker, so ordering is never
raced) per event batch:

1. snapshot ClusterQueues/LocalQueues/PodGroups from informers into
   the pure :mod:`~kubernetes_tpu.queueing.fairshare` state;
2. order pending gangs by DRF dominant share across tenants;
3. admit in order — nominal first, then cohort borrowing; a gang whose
   nominal quota is held by borrowers triggers gang-aware RECLAIM
   (cheapest borrowed gang unadmitted + its bound pods evicted, same
   victim pricing as scheduler gang preemption);
4. when the head blocks, EASY-backfill later gangs that fit outright,
   complete before the blocker's shadow time, and — when the composer
   wired ``fits_probe`` (cluster/local.py → scheduler cache) — whose
   slice box fits current free fragmentation.

Admission state lives in PodGroup.status (admitted/admission_mode/
admitted_time): durable through the MVCC WAL, so a restarted
controller rebuilds usage exactly and never double-admits.

With the ``JobQueueing`` gate off the controller starts no informers
and does nothing — scheduling behavior is byte-identical to the
ungated build.
"""
from __future__ import annotations

import asyncio
import dataclasses
import logging
import math
from typing import Callable, Optional

from ..analysis import interleave, invariants
from ..api import errors, types as t
from ..api.meta import now as meta_now
from ..api.queueing import RUNTIME_ANNOTATION
from ..client.informer import InformerFactory
from ..client.interface import Client
from ..queueing import fairshare as fs
from ..queueing import metrics as qm
from .base import Controller

log = logging.getLogger("queue-controller")

#: The one sync key: admission is a global ordering problem, so every
#: informer event folds into a single full pass.
ADMIT_KEY = "::admission"

#: Pass cadence while gated on — backfill shadow times move with the
#: wall clock even without API events.
RESYNC_SECONDS = 1.0

#: Floor between two admission passes. During a wave every admission's
#: own status writes (PodGroup, CQ, LQ) come straight back as informer
#: events, each re-dirtying the sync key — without a floor the worker
#: runs passes back-to-back at loop speed (one per ~2 events) and the
#: O(groups) passes themselves become the admission bottleneck. The
#: throttle lives in sync() (not the kick path) because a kick during
#: a pass re-queues the key REGARDLESS of any enqueue-side delay.
MIN_PASS_INTERVAL = 0.1


def group_demand(group: t.PodGroup,
                 replicas: Optional[int] = None) -> dict[str, float]:
    """Gang demand charged against quota: explicit ``spec.resources``,
    with chips defaulted from the slice shape so admission never waits
    for member pods to exist.

    Elastic gangs (GracefulPreemption + spec.max_replicas): the spec
    describes the FULL size; the charge scales linearly with the
    current target (``replicas`` override, else status.replicas, else
    max) — a shrunken gang charges only what it still holds. Mirrored
    by analysis/invariants.py:_demand; keep the two in sync."""
    demand = dict(group.spec.resources)
    if t.RESOURCE_TPU not in demand and group.spec.slice_shape:
        demand[t.RESOURCE_TPU] = float(math.prod(group.spec.slice_shape))
    from .. import preemption as gp
    if gp.enabled() and group.spec.max_replicas:
        r = replicas if replicas is not None else (
            group.status.replicas or group.spec.max_replicas)
        r = max(group.spec.min_replicas, min(r, group.spec.max_replicas))
        frac = r / group.spec.max_replicas
        demand = {res: amt * frac for res, amt in demand.items()}
    return demand


def group_runtime(group: t.PodGroup) -> Optional[float]:
    raw = group.metadata.annotations.get(RUNTIME_ANNOTATION)
    if not raw:
        return None
    try:
        sec = float(raw)
    except ValueError:
        return None
    return sec if sec > 0 else None


def _group_active(group: t.PodGroup) -> bool:
    return (group.metadata.deletion_timestamp is None
            and group.status.phase != t.PODGROUP_FAILED)


class QueueController(Controller):
    name = "queue-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 fits_probe: Optional[Callable[[t.PodGroup], bool]] = None):
        # Exactly one worker — not configurable: two concurrent
        # admission passes would race each other's charges.
        super().__init__(client, factory, workers=1)
        #: Optional composer hook answering "does a free contiguous box
        #: of this gang's shape exist right now?" (cluster/local.py
        #: wires the live scheduler cache). Backfill-only: quota-based
        #: admission must stay placement-agnostic.
        self.fits_probe = fits_probe
        #: Gangs reclaimed but possibly still holding chips: a member
        #: bind that was in flight when _unadmit listed pods escapes
        #: the one-shot eviction, so reclaimed gangs are swept
        #: level-triggered every pass until no bound member remains.
        self._reclaim_sweep: set[str] = set()
        #: Admissions WRITTEN but not yet reflected by the informer:
        #: key -> (mode, admitted_at, cluster_queue). Without this
        #: overlay every pass re-walks the informer-stale "pending"
        #: gangs with a live client.get each — O(n²) API reads across
        #: an n-gang wave, which made admission the bench bottleneck
        #: (the controller analog of the replicaset expectations
        #: cache). Entries drop once the informer catches up, on
        #: reclaim, and on deletion.
        self._admitted_overlay: dict[str, tuple[str, float, str]] = {}
        #: The unadmit mirror: reclaims WRITTEN but not yet reflected
        #: by the informer. Without it a just-reclaimed gang's stale
        #: admitted=True copy is re-charged on the next pass, the
        #: lender's demand computes a phantom cohort shortfall, and a
        #: SECOND healthy borrower gets evicted before the watch
        #: catches up.
        self._unadmit_overlay: set[str] = set()
        #: The elastic mirror: shrink/regrow target writes not yet
        #: reflected by the informer (key -> replicas). Same phantom-
        #: shortfall argument as _unadmit_overlay — a just-shrunk
        #: gang's stale full-size copy must not be re-charged whole.
        self._replicas_overlay: dict[str, int] = {}
        #: Per-group Workload snapshot, keyed on key ->
        #: (resource_version, Workload). The admission pass runs on
        #: every event burst and rebuilding demand/runtime/timestamps
        #: for EVERY group each pass made the pass O(n) in python-dict
        #: work — at a 768-gang wave the passes themselves were the
        #: admission bottleneck. An entry is reused only while rv AND
        #: the resolved ClusterQueue match (an LQ rebind changes the
        #: charge target with no rv bump on the group); _admit's
        #: mode/admitted_at writes mutate the cached instance, which
        #: stays consistent because they mirror the overlay until the
        #: informer delivers the new rv and forces a rebuild.
        self._wl_cache: dict[str, tuple[str, fs.Workload]] = {}
        #: loop.time() of the last real admission pass (MIN_PASS_INTERVAL).
        self._last_pass = 0.0
        #: loop.time() of the last CQ/LQ status publication. Status is
        #: observability, not decision input — during a wave every pass
        #: would otherwise rewrite every queue's usage/tenant breakdown
        #: (4+ API writes per pass). A 0.25s cadence bounds that; the
        #: RESYNC pass guarantees convergence after the wave quiets.
        self._last_publish = -1e9
        #: Gangs already warned Inadmissible: the condition persists
        #: until quota config changes, and the pass runs at 1 Hz — the
        #: event must fire on TRANSITION, not every pass (Warning
        #: events bypass the recorder's Normal-only rate limiter).
        self._inadmissible: set[str] = set()
        # Gate read at CONSTRUCTION (like the informer wiring it
        # guards): flipping JobQueueing at runtime needs a manager
        # restart — the scheduler reads the gate live, so a post-start
        # flip would otherwise suspend gangs nobody admits.
        from ..util.features import GATES
        self.enabled = GATES.enabled("JobQueueing")
        if not self.enabled:
            return
        self.cq_informer = self.watch("clusterqueues")
        self.lq_informer = self.watch("localqueues")
        self.pg_informer = self.watch("podgroups")
        kick = lambda *_a: self.enqueue(ADMIT_KEY)  # noqa: E731
        for inf in (self.cq_informer, self.lq_informer, self.pg_informer):
            inf.add_handlers(on_add=kick, on_delete=kick)
        # Update events are filtered to admission-RELEVANT changes:
        # most update traffic during a wave is the controller's own
        # CQ/LQ status publishes and the scheduler's per-gang phase
        # progress, none of which move an admission decision — kicking
        # on them turns every pass's writes into the next pass's
        # trigger and the controller livelocks at one pass per event
        # burst (observed as the --queued bench bottleneck).
        self.pg_informer.add_handlers(on_update=self._pg_updated)
        for inf in (self.cq_informer, self.lq_informer):
            inf.add_handlers(on_update=self._queue_updated)

    def _queue_updated(self, old, new) -> None:
        if old.spec != new.spec:
            self.enqueue(ADMIT_KEY)

    def _pg_updated(self, old, new) -> None:
        # NOTE: our own admit writes echo back here and re-kick the
        # pass. Filtering them via the overlay was tried and REVERTED:
        # passes are cheap (informer snapshots, no API reads) and the
        # echo pressure keeps tail admission latency low through the
        # bench's bind bursts (p99 halves with it).
        if (old.spec != new.spec
                or old.status.admitted != new.status.admitted
                # Elastic target moves the gang's quota charge.
                or old.status.replicas != new.status.replicas
                or old.metadata.deletion_timestamp
                != new.metadata.deletion_timestamp
                or (old.status.phase == t.PODGROUP_FAILED)
                != (new.status.phase == t.PODGROUP_FAILED)
                or old.metadata.annotations.get(RUNTIME_ANNOTATION)
                != new.metadata.annotations.get(RUNTIME_ANNOTATION)):
            self.enqueue(ADMIT_KEY)

    async def on_start(self) -> None:
        if not self.enabled:
            return
        # Rebuild the reclaim sweep from observable state: an
        # unadmitted queued gang holding bound members is an invariant
        # violation whatever its origin (a crash between _unadmit's
        # one-shot eviction and the racing bind landing, most likely) —
        # the sweep is a pure repair loop, so seeding it with every
        # unadmitted GOVERNED group is safe and self-clearing. A gang
        # whose spec.queue does not resolve (dangling ref from a
        # gate-off run) is one _snapshot suspends rather than admits,
        # so seeding it would evict a running gang no pass can ever
        # retro-admit; it stays untouched until a queue governs it.
        lqs = {lq.key(): lq for lq in self.lq_informer.list()}
        cq_names = {cq.metadata.name for cq in self.cq_informer.list()}
        for group in self.pg_informer.list():
            if not group.spec.queue or not _group_active(group):
                continue
            lq = lqs.get(f"{group.metadata.namespace}/{group.spec.queue}")
            if lq is None or lq.spec.cluster_queue not in cq_names:
                continue
            st = group.status.preemption
            if st is not None and st.phase in (t.PREEMPT_SIGNALED,
                                               t.PREEMPT_CHECKPOINTING):
                # A restart mid graceful round (shrink OR reclaim):
                # its finisher died with the old process; the sweep's
                # finish_stale_round completes it past the deadline.
                self._reclaim_sweep.add(group.key())
                continue
            if not group.status.admitted:
                self._reclaim_sweep.add(group.key())
        self.enqueue(ADMIT_KEY)

    async def sync(self, key: str) -> Optional[float]:
        if not self.enabled:
            return None
        loop = asyncio.get_running_loop()
        wait = self._last_pass + MIN_PASS_INTERVAL - loop.time()
        if wait > 0:
            # Mid-burst: skip the pass, come back when the floor
            # clears (add_after keeps the wakeup even if no further
            # event re-dirties the key).
            return wait
        self._last_pass = loop.time()
        await self._admission_pass()
        return RESYNC_SECONDS

    # -- snapshot ---------------------------------------------------------

    def _snapshot(self):
        """Informer state -> fairshare state. Returns (queues,
        admitted, pending, groups_by_key, lq_of_group, cqs_by_name,
        lqs_by_key)."""
        cqs = {cq.metadata.name: cq for cq in self.cq_informer.list()}
        queues = {
            name: fs.QueueState(name=name, cohort=cq.spec.cohort,
                                nominal=dict(cq.spec.nominal_quota),
                                borrowing_limit=dict(cq.spec.borrowing_limit))
            for name, cq in cqs.items()}
        lqs = {lq.key(): lq for lq in self.lq_informer.list()}
        admitted: list[fs.Workload] = []
        pending: list[fs.Workload] = []
        groups: dict[str, t.PodGroup] = {}
        lq_of: dict[str, str] = {}
        seen: set[str] = set()
        for group in self.pg_informer.list():
            gk = group.key()
            seen.add(gk)
            if not group.spec.queue or not _group_active(group):
                continue
            overlay = self._admitted_overlay.get(gk)
            if group.status.admitted and overlay is not None:
                overlay = None  # informer caught up
                self._admitted_overlay.pop(gk, None)
            if not group.status.admitted:
                self._unadmit_overlay.discard(gk)  # informer caught up
            is_admitted = (group.status.admitted or overlay is not None) \
                and gk not in self._unadmit_overlay
            lq_key = f"{group.metadata.namespace}/{group.spec.queue}"
            lq = lqs.get(lq_key)
            if is_admitted:
                # The charge target was resolved AT ADMISSION and
                # stamped in status (or held in the overlay for a write
                # the informer hasn't delivered yet): deleting the
                # LocalQueue afterwards must not vanish admitted usage
                # (the gang still holds chips). Legacy groups without
                # the stamp fall back to the live binding.
                cq_name = group.status.admission_cluster_queue or (
                    overlay[2] if overlay is not None else "") or (
                    lq.spec.cluster_queue if lq is not None else "")
            else:
                if lq is None or lq.spec.cluster_queue not in queues:
                    continue  # dangling ref: suspended, heals on queue add
                cq_name = lq.spec.cluster_queue
            if cq_name not in queues:
                continue  # ClusterQueue itself deleted: nothing governs
            rep_ov = self._replicas_overlay.get(gk)
            if rep_ov is not None and (group.status.replicas or 0) == rep_ov:
                rep_ov = None  # informer caught up
                self._replicas_overlay.pop(gk, None)
            rv = group.metadata.resource_version
            ent = self._wl_cache.get(gk)
            if ent is not None and ent[0] == rv \
                    and ent[1].queue == cq_name and rep_ov is None:
                w = ent[1]
                if overlay is not None:
                    w.mode, w.admitted_at = overlay[0], overlay[1]
            else:
                created = group.metadata.creation_timestamp
                adm = group.status.admitted_time
                w = fs.Workload(
                    key=gk, queue=cq_name,
                    demand=group_demand(group, replicas=rep_ov),
                    priority=group.spec.priority or 0,
                    created=created.timestamp() if created else 0.0,
                    runtime=group_runtime(group),
                    admitted_at=(adm.timestamp() if adm else None)
                    if overlay is None else overlay[1],
                    mode=group.status.admission_mode
                    if overlay is None else overlay[0],
                    min_demand=self._shrinkable_to(group, rep_ov))
                if rep_ov is None:
                    self._wl_cache[gk] = (rv, w)
            groups[gk] = group
            lq_of[gk] = lq_key
            if is_admitted:
                fs.charge(queues[w.queue], w.demand)
                admitted.append(w)
            else:
                pending.append(w)
        # Deleted gangs must not pin overlay or cache entries forever.
        for key in [k for k in self._admitted_overlay if k not in seen]:
            del self._admitted_overlay[key]
        self._unadmit_overlay &= seen
        for key in [k for k in self._replicas_overlay if k not in seen]:
            del self._replicas_overlay[key]
        for key in [k for k in self._wl_cache if k not in seen]:
            del self._wl_cache[key]
        return queues, admitted, pending, groups, lq_of, cqs, lqs

    @staticmethod
    def _shrinkable_to(group: t.PodGroup,
                       rep_ov: Optional[int]) -> Optional[dict]:
        """min_replicas demand for an elastic gang still above min —
        the reclaim planner's shrink option. None otherwise."""
        from .. import preemption as gp
        if not gp.enabled() or not group.spec.max_replicas:
            return None
        cur = rep_ov if rep_ov is not None else (
            group.status.replicas or group.spec.max_replicas)
        if cur <= group.spec.min_replicas:
            return None
        return group_demand(group, replicas=group.spec.min_replicas)

    # -- the pass ---------------------------------------------------------

    async def _admission_pass(self) -> None:
        interleave.touch("queue:admission")  # tpusan DPOR hint
        queues, admitted, pending, groups, lq_of, cqs, lqs = self._snapshot()
        wall = meta_now().timestamp()
        order = fs.drf_order(queues, pending)
        # Head-of-line blocking is scoped per COHORT (capacity is):
        # a blocked gang in one cohort must not freeze admission for
        # queues whose capacity it cannot even touch.
        blockers: dict[str, tuple[fs.Workload, float]] = {}
        # Admission DECISIONS are made synchronously during the walk
        # (charging the pass state optimistically so later decisions see
        # the usage); the status WRITES are batched and fired
        # concurrently after it — serialized per-admit round trips were
        # the measured wave-rate gap vs the unqueued bench stanza.
        to_admit: list[tuple[t.PodGroup, fs.Workload, str, bool]] = []
        pending_writes: set[str] = set()

        def decide_admit(w: fs.Workload, mode: str, backfilled: bool):
            w.mode = mode
            w.admitted_at = wall  # refined to the write stamp in _admit
            fs.charge(queues[w.queue], w.demand)
            admitted.append(w)
            pending_writes.add(w.key)
            to_admit.append((groups[w.key], w, mode, backfilled))

        for w in order:
            q = queues[w.queue]
            cohort = [m for m in queues.values()
                      if q.cohort and m.cohort == q.cohort] or [q]
            ck = q.cohort or q.name
            mode, needs_reclaim = fs.admission_mode(q, cohort, w.demand)
            if ck not in blockers:
                if mode is None and needs_reclaim:
                    # Same-pass decisions whose writes haven't landed
                    # are NOT reclaim candidates: _unadmit on an
                    # unwritten admission would release quota the
                    # deferred write then re-spends. Reclaim sees them
                    # next pass, once written.
                    decisions = fs.plan_reclaim(
                        q, w.demand, cohort,
                        [a for a in admitted
                         if a.key not in pending_writes])
                    for v, action in decisions:
                        if action == fs.RECLAIM_SHRINK:
                            # Elastic borrower: give back the borrowed
                            # delta, keep training at min_replicas.
                            await self._shrink(groups[v.key], v, queues)
                        else:
                            await self._unadmit(groups[v.key], v, queues)
                            admitted.remove(v)
                    if decisions:
                        mode, _ = fs.admission_mode(q, cohort, w.demand)
                if mode is not None:
                    decide_admit(w, mode, False)
                    continue
                if not fs.structurally_admissible(q, cohort, w.demand):
                    # Can NEVER fit at current quota config: sideline it
                    # (Kueue's Inadmissible) instead of letting it
                    # blocker-starve the whole cohort.
                    if w.key not in self._inadmissible:
                        self._inadmissible.add(w.key)
                        self.recorder.event(
                            groups[w.key], "Warning", "Inadmissible",
                            f"demand {w.demand} exceeds queue {w.queue}'s "
                            f"admissible ceiling; fix quota or the gang")
                    continue
                self._inadmissible.discard(w.key)
                blockers[ck] = (w, fs.shadow_time(w, queues, admitted, wall))
                continue
            # Cohort head blocked: EASY backfill for the rest of its
            # order — fit outright, end before the blocker's shadow.
            _bw, shadow = blockers[ck]
            if mode is None:
                continue
            if not fs.backfill_ok(w, shadow, wall):
                continue
            if self.fits_probe is not None and not self.fits_probe(
                    groups[w.key]):
                continue
            # Label: the quota position (a within-nominal jumper is NOT
            # a reclaim candidate); the jump itself shows in the event.
            label = "Backfill" if mode == "Borrowed" else mode
            decide_admit(w, label, True)
        if to_admit:
            results = await asyncio.gather(
                *(self._admit(g, w, m, backfilled=b)
                  for g, w, m, b in to_admit),
                return_exceptions=True)
            first_err = None
            for (g, w, m, b), ok in zip(to_admit, results):
                if isinstance(ok, BaseException) or not ok:
                    fs.release(queues[w.queue], w.demand)
                    if w in admitted:
                        admitted.remove(w)
                    if isinstance(ok, BaseException) and first_err is None:
                        first_err = ok
            if first_err is not None:
                raise first_err  # e.g. ConflictError: requeue the pass
        self._inadmissible &= set(groups)  # deleted gangs drop out
        # Regrow AFTER pending admissions: an elastic gang takes back
        # released quota only when no pending gang (blocker) wants it.
        await self._regrow(queues, admitted, groups, blockers)
        # Sweep AFTER admitting: a gang bound while the gate was off
        # (or whose admission record raced a crash) gets retro-admitted
        # above if quota allows — only gangs still unadmitted after the
        # pass lose their members. Running the sweep first would evict
        # healthy running gangs the very pass that was about to admit
        # them.
        await self._sweep_reclaimed()
        now_m = asyncio.get_running_loop().time()
        if now_m - self._last_publish >= 0.25:
            self._last_publish = now_m
            reclaiming: dict[str, int] = {}
            queue_of = {x.key: x.queue for x in admitted}
            queue_of.update((x.key, x.queue) for x in pending)
            for gk, group in groups.items():
                st = group.status.preemption
                mid_round = st is not None and st.phase in (
                    t.PREEMPT_SIGNALED, t.PREEMPT_CHECKPOINTING)
                if (mid_round or gk in self._reclaim_sweep) \
                        and gk in queue_of:
                    reclaiming[queue_of[gk]] = \
                        reclaiming.get(queue_of[gk], 0) + 1
            await self._publish_status(queues, admitted, pending,
                                       lq_of, cqs, lqs, reclaiming)

    # -- admission state transitions --------------------------------------

    async def _admit(self, group: t.PodGroup, w: fs.Workload, mode: str,
                     backfilled: bool = False) -> bool:
        """Write one admission decided during the pass walk. The caller
        already charged the pass state and appended to ``admitted`` —
        on False (gang deleted under us) or an exception it releases
        both."""
        # No probing GET: the informer copy + overlay already said
        # "not admitted", and the rv-checked status write is the real
        # arbiter — a stale read loses the write with ConflictError and
        # the pass retries on fresh informer state. (The GET was a
        # third of the per-admission cost at wave scale.)
        # dataclasses.replace leaves the informer's cached instance
        # untouched (cache-mutation discipline).
        stamped = meta_now()
        cur = dataclasses.replace(group, status=dataclasses.replace(
            group.status, admitted=True, admission_mode=mode,
            admitted_time=stamped, admission_cluster_queue=w.queue))
        try:
            await self.client.update_status(cur)  # ConflictError -> retry
        except errors.NotFoundError:
            return False  # deleted under us: nothing charged
        qm.ADMISSIONS.inc(queue=w.queue, mode=mode)
        created = group.metadata.creation_timestamp
        if created is not None:
            qm.ADMISSION_WAIT.observe(
                max(0.0, (stamped - created).total_seconds()))
        self.recorder.event(
            cur, "Normal", "Admitted",
            f"queue {w.queue}: mode={mode}"
            + (" (backfilled past the blocked head)" if backfilled
               else "")
            + f", demand={ {r: round(a, 3) for r, a in w.demand.items()} }")
        w.mode = mode
        w.admitted_at = stamped.timestamp()
        self._admitted_overlay[w.key] = (mode, w.admitted_at, w.queue)
        self._unadmit_overlay.discard(w.key)
        return True

    async def _shrink(self, group: t.PodGroup, w: fs.Workload,
                      queues: dict[str, fs.QueueState]) -> None:
        """Reclaim's elastic alternative to :meth:`_unadmit`: lower the
        gang's target to min_replicas (releasing the borrowed delta of
        its charge), then gracefully preempt the surplus bound members
        — the gang keeps training small instead of dying, and regrows
        when quota allows."""
        from .. import preemption as gp
        target = group.spec.min_replicas
        ns, name = group.metadata.namespace, group.metadata.name
        delta = {r: max(0.0, a - (w.min_demand or {}).get(r, 0.0))
                 for r, a in w.demand.items()}
        cur = dataclasses.replace(group, status=dataclasses.replace(
            group.status, replicas=target))
        try:
            await self.client.update_status(cur)  # ConflictError -> retry
        except errors.NotFoundError:
            return
        fs.release(queues[w.queue], delta)
        self._replicas_overlay[w.key] = target
        w.demand = dict(w.min_demand or {})
        w.min_demand = None
        # Crash backstop: the sweep finishes a stale shrink round
        # (finish_stale_round) if this controller dies before the
        # engine's finisher evicts the surplus members.
        self._reclaim_sweep.add(w.key)
        gp.SHRINKS.inc()
        self.recorder.event(
            cur, "Warning", "ElasticShrunk",
            f"cohort reclaim: shrinking to {target} members; the "
            f"borrowed slice is released after checkpoint")
        pods, _ = await self.client.list(
            "pods", ns, field_selector=f"spec.gang={name}")
        bound = sorted((p for p in pods
                        if p.spec.node_name and t.is_pod_active(p)),
                       key=lambda p: p.metadata.name)
        surplus = bound[target:]
        if not surplus:
            return
        if not await gp.signal_gang(self.client, cur, surplus,
                                    reason="reclaim-shrink",
                                    recorder=self.recorder):
            for pod in surplus:  # not checkpoint-opted: legacy kill
                try:
                    await self.client.evict(
                        pod.metadata.namespace, pod.metadata.name,
                        t.Eviction(override_budget=True))
                except errors.StatusError as e:
                    log.warning("shrink evict %s failed: %s", pod.key(), e)

    async def _regrow(self, queues: dict[str, fs.QueueState],
                      admitted: list[fs.Workload],
                      groups: dict[str, t.PodGroup],
                      blockers: dict) -> None:
        """Elastic regrow — the backfill half of shrink: a shrunken
        gang takes its target back toward max_replicas when the quota
        fits, unless its cohort has a blocked pending gang (pending
        demand outranks regrowth). The scheduler's elastic cap reads
        the raised target on the parked members' next requeue."""
        from .. import preemption as gp
        if not gp.enabled():
            return
        for w in admitted:
            group = groups.get(w.key)
            if group is None or not group.spec.max_replicas:
                continue
            cur_target = self._replicas_overlay.get(
                w.key, group.status.replicas or group.spec.max_replicas)
            if cur_target >= group.spec.max_replicas:
                continue
            q = queues[w.queue]
            if (q.cohort or q.name) in blockers:
                continue
            cohort = [m for m in queues.values()
                      if q.cohort and m.cohort == q.cohort] or [q]
            for target in range(group.spec.max_replicas, cur_target, -1):
                full = group_demand(group, replicas=target)
                delta = {r: max(0.0, a - w.demand.get(r, 0.0))
                         for r, a in full.items()}
                mode, _ = fs.admission_mode(q, cohort, delta)
                if mode is None:
                    continue
                fresh = dataclasses.replace(
                    group, status=dataclasses.replace(
                        group.status, replicas=target))
                try:
                    await self.client.update_status(fresh)
                except errors.StatusError:
                    break  # opportunistic: informer refresh retries
                fs.charge(q, delta)
                self._replicas_overlay[w.key] = target
                w.demand = full
                self.recorder.event(
                    fresh, "Normal", "ElasticRegrown",
                    f"quota allows: target raised to {target} members")
                break

    async def _unadmit(self, group: t.PodGroup, w: fs.Workload,
                       queues: dict[str, fs.QueueState]) -> None:
        """Reclaim one borrowed gang: flip it back to pending FIRST (the
        scheduler re-suspends it before its pods requeue), then evict
        its bound members so the borrowed chips actually free. The
        PodGroup itself survives — preempted and requeued, never
        orphaned."""
        ns, name = group.metadata.namespace, group.metadata.name
        # Announce the unadmit BEFORE any write lands: tpusan's
        # admission-monotonicity invariant treats an unannounced
        # admitted->pending flip as a violation.
        invariants.note_reclaim(w.key)
        interleave.touch(f"gang:{w.key}")
        self._admitted_overlay.pop(w.key, None)
        try:
            cur = await self.client.get("podgroups", ns, name)
        except errors.NotFoundError:
            fs.release(queues[w.queue], w.demand)
            self._unadmit_overlay.add(w.key)  # stale copy may linger
            return
        if cur.status.admitted:
            cur.status.admitted = False
            cur.status.admission_mode = ""
            cur.status.admitted_time = None
            cur.status.admission_cluster_queue = ""
            cur.status.phase = t.PODGROUP_PENDING
            await self.client.update_status(cur)
            qm.RECLAIMS.inc(queue=w.queue)
            self.recorder.event(
                cur, "Warning", "QuotaReclaimed",
                f"borrowed quota reclaimed by cohort; gang requeued")
        fs.release(queues[w.queue], w.demand)
        self._unadmit_overlay.add(w.key)
        # Graceful path (preemption.py): a checkpoint-opted gang is
        # SIGNALED and keeps its chips for its grace budget while it
        # checkpoints; the engine's finisher evicts it after. The
        # quota was already released above, so the beneficiary admits
        # now and binds once the chips free — reclaim costs one
        # checkpoint interval. Gate off / not opted in: evict now,
        # exactly the legacy path.
        from .. import preemption as gp
        graceful = False
        if gp.eligible(cur):
            pods, _ = await self.client.list(
                "pods", ns, field_selector=f"spec.gang={name}")
            bound = [p for p in pods
                     if p.spec.node_name and t.is_pod_active(p)]
            graceful = await gp.signal_gang(
                self.client, cur, bound, reason="reclaim",
                recorder=self.recorder)
        if not graceful:
            await self._evict_bound_members(ns, name)
        self._reclaim_sweep.add(w.key)

    async def _evict_bound_members(self, ns: str, name: str) -> bool:
        """Evict the gang's bound, active members; True when any were
        still holding chips."""
        pods, _ = await self.client.list(
            "pods", ns, field_selector=f"spec.gang={name}")
        holding = False
        for pod in pods:
            if not pod.spec.node_name or not t.is_pod_active(pod):
                continue
            holding = True
            try:
                await self.client.evict(
                    pod.metadata.namespace, pod.metadata.name,
                    t.Eviction(override_budget=True))
            except errors.StatusError as e:
                log.warning("reclaim evict %s failed: %s", pod.key(), e)
        return holding

    async def _sweep_reclaimed(self) -> None:
        """Level-triggered reclaim completion: a bind racing _unadmit's
        pod listing can land AFTER the one-shot eviction, leaving an
        unadmitted gang holding chips the cohort thinks are free. Sweep
        each reclaimed gang until no bound member remains (or it was
        re-admitted / deleted)."""
        from .. import preemption as gp
        for key in list(self._reclaim_sweep):
            ns, name = key.split("/", 1)
            try:
                group = await self.client.get("podgroups", ns, name)
            except errors.NotFoundError:
                self._reclaim_sweep.discard(key)
                continue
            st = group.status.preemption
            mid_round = st is not None and st.phase in (
                t.PREEMPT_SIGNALED, t.PREEMPT_CHECKPOINTING)
            if group.status.admitted:
                # An ADMITTED gang is swept only for a stale SHRINK
                # round (its finisher died before evicting the
                # surplus); a healthy admitted gang drops out.
                if mid_round:
                    await gp.finish_stale_round(self.client, group)
                else:
                    self._reclaim_sweep.discard(key)
                continue
            if mid_round:
                # Graceful round in flight: its finisher evicts at
                # quorum/deadline — sweeping now would hard-kill a
                # checkpointing gang. Past-deadline rounds whose
                # finisher died are finished here (the crash backstop).
                if not await gp.finish_stale_round(self.client, group):
                    continue
            if not await self._evict_bound_members(ns, name):
                self._reclaim_sweep.discard(key)

    # -- status fan-out ---------------------------------------------------

    async def _publish_status(self, queues, admitted, pending,
                              lq_of, cqs, lqs,
                              reclaiming: Optional[dict] = None) -> None:
        reclaiming = reclaiming or {}
        by_cq_pending: dict[str, int] = {}
        by_cq_admitted: dict[str, int] = {}
        by_lq: dict[str, list[int]] = {}
        tenant_usage: dict[str, dict[str, dict[str, float]]] = {}
        for w in pending:
            by_cq_pending[w.queue] = by_cq_pending.get(w.queue, 0) + 1
            by_lq.setdefault(lq_of[w.key], [0, 0])[0] += 1
        for w in admitted:
            by_cq_admitted[w.queue] = by_cq_admitted.get(w.queue, 0) + 1
            by_lq.setdefault(lq_of[w.key], [0, 0])[1] += 1
            tu = tenant_usage.setdefault(w.queue, {}).setdefault(
                lq_of[w.key], {})
            for res, amt in w.demand.items():
                tu[res] = tu.get(res, 0.0) + amt
        # Gauges for queues that no longer exist must stop exporting,
        # not freeze at their last value.
        for key in qm.QUEUE_PENDING.labeled_keys():
            if key[0] not in queues:
                qm.QUEUE_PENDING.remove(queue=key[0])
                qm.QUEUE_ADMITTED.remove(queue=key[0])
        for gauge in (qm.QUEUE_BORROWED, qm.QUEUE_USAGE):
            for key in gauge.labeled_keys():
                if key[0] not in queues:
                    gauge.remove(queue=key[0], resource=key[1])
        for name, q in queues.items():
            pending_n = by_cq_pending.get(name, 0)
            admitted_n = by_cq_admitted.get(name, 0)
            qm.QUEUE_PENDING.set(float(pending_n), queue=name)
            qm.QUEUE_ADMITTED.set(float(admitted_n), queue=name)
            borrowed_now = fs.borrowed(q)
            # Every governed resource gets a sample (zero included):
            # "stopped borrowing" must read 0, not the last peak.
            for res in q.nominal:
                qm.QUEUE_BORROWED.set(borrowed_now.get(res, 0.0),
                                      queue=name, resource=res)
                qm.QUEUE_USAGE.set(q.usage.get(res, 0.0),
                                   queue=name, resource=res)
            cq = cqs.get(name)
            if cq is None:
                continue
            st = cq.status
            want = (pending_n, admitted_n, q.usage, fs.borrowed(q),
                    tenant_usage.get(name, {}), reclaiming.get(name, 0))
            have = (st.pending, st.admitted, st.usage, st.borrowed,
                    st.tenant_usage, st.reclaiming)
            if want == have:
                continue
            try:
                cur = await self.client.get("clusterqueues", "", name)
                cur.status.pending, cur.status.admitted = pending_n, admitted_n
                cur.status.usage = dict(q.usage)
                cur.status.borrowed = fs.borrowed(q)
                cur.status.tenant_usage = tenant_usage.get(name, {})
                cur.status.reclaiming = reclaiming.get(name, 0)
                await self.client.update_status(cur)
            except errors.StatusError:
                pass  # informer refresh heals on the next pass
        for lq_key, lq in lqs.items():
            # Every LocalQueue, not just the populated ones — counts
            # must fall back to zero when the last gang drains.
            pend, adm = by_lq.get(lq_key, (0, 0))
            if (lq.status.pending, lq.status.admitted) == (pend, adm):
                continue
            try:
                cur = await self.client.get(
                    "localqueues", lq.metadata.namespace, lq.metadata.name)
                cur.status.pending, cur.status.admitted = pend, adm
                await self.client.update_status(cur)
            except errors.StatusError:
                pass
