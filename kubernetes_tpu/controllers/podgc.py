"""Pod garbage collector.

Reference: ``pkg/kubelet``'s counterpart ``pkg/controller/podgc``:
- force-delete pods bound to nodes that no longer exist (their node
  agent can never confirm graceful termination);
- trim terminated (Succeeded/Failed) pods beyond a threshold, oldest
  first, so the store does not grow without bound;
- force-delete pods stuck terminating on unreachable (Ready=Unknown)
  nodes past their grace period — the step that actually frees a gang's
  chips for rescheduling when a TPU host dies.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ..api import errors
from ..api import types as t
from ..api.meta import now
from ..client.informer import InformerFactory
from ..client.interface import Client
from .base import Controller


class PodGCController(Controller):
    name = "podgc-controller"

    def __init__(self, client: Client, factory: InformerFactory,
                 terminated_pod_threshold: int = 1000,
                 interval: float = 20.0):
        super().__init__(client, factory, workers=1)
        self.threshold = terminated_pod_threshold
        self.interval = interval
        self.pod_informer = self.watch("pods")
        self.node_informer = self.watch("nodes")
        self._task: Optional[asyncio.Task] = None

    async def on_start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        await super().stop()

    async def _loop(self) -> None:
        while True:
            try:
                await self.gc_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                import logging
                logging.getLogger("controller").exception("pod gc failed")
            await asyncio.sleep(self.interval)

    async def sync(self, key: str) -> Optional[float]:  # queue unused
        return None

    async def gc_once(self) -> None:
        pods = self.pod_informer.list()
        nodes = {n.metadata.name for n in self.node_informer.list()}
        unknown = {n.metadata.name for n in self.node_informer.list()
                   if (t.get_node_condition(n.status, t.NODE_READY) or
                       t.NodeCondition()).status == "Unknown"}

        # Orphaned: bound to a node that is gone.
        for pod in pods:
            if pod.spec.node_name and pod.spec.node_name not in nodes:
                await self._force_delete(pod, "node is gone")

        # Stuck terminating on an unreachable node past grace.
        ts = now()
        for pod in pods:
            if (pod.metadata.deletion_timestamp is not None
                    and pod.spec.node_name in unknown):
                grace = pod.spec.termination_grace_period_seconds or 0
                age = (ts - pod.metadata.deletion_timestamp).total_seconds()
                if age > grace:
                    await self._force_delete(pod, "node unreachable")

        # Terminated beyond threshold, oldest first.
        terminated = [p for p in pods
                      if p.status.phase in (t.POD_SUCCEEDED, t.POD_FAILED)
                      and p.metadata.deletion_timestamp is None]
        excess = len(terminated) - self.threshold
        if excess > 0:
            terminated.sort(key=lambda p: (
                p.metadata.creation_timestamp.timestamp()
                if p.metadata.creation_timestamp else 0.0))
            for pod in terminated[:excess]:
                await self._force_delete(pod, "terminated pod threshold")

    async def _force_delete(self, pod: t.Pod, why: str) -> None:
        try:
            await self.client.delete("pods", pod.metadata.namespace,
                                     pod.metadata.name,
                                     grace_period_seconds=0)
            self.recorder.event(pod, "Normal", "PodGC", f"force-deleted: {why}")
        except errors.NotFoundError:
            pass
