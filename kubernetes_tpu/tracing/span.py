"""Spans — the unit of the pod-lifecycle trace.

A :class:`Span` is one named, timed stage (``create``, ``queue``,
``schedule``, ``bind``, ``pull``, ``start``, ``startup``) attributed to
a component (apiserver/scheduler/node/...). Finished spans land in the
bounded in-process collector (collector.py); live ones cost two floats
and a couple of dict slots.

Zero-overhead-when-off contract: :func:`start_span` returns the shared
:data:`NOOP_SPAN` singleton unless tracing is armed AND the parent
context is sampled — every call site can therefore use spans
unconditionally (``span.event(...)``, ``span.end()``) without its own
gating, and the disarmed cost is one module-bool check.
"""
from __future__ import annotations

import time
from typing import Optional

from . import context as tc
from .context import TraceContext

_SENTINEL = object()


class Span:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "component",
                 "start", "_t0", "attrs", "events", "_ended", "_token")

    def __init__(self, name: str, component: str, parent: TraceContext,
                 attrs: Optional[dict] = None):
        self.trace_id = parent.trace_id
        self.span_id = tc.new_span_id()
        self.parent_id = parent.span_id
        self.name = name
        self.component = component
        self.start = time.time()
        self._t0 = time.perf_counter()
        self.attrs = dict(attrs) if attrs else {}
        self.events: list[tuple[float, str]] = []
        self._ended = False
        self._token = None

    @property
    def noop(self) -> bool:
        return False

    def context(self) -> TraceContext:
        """This span's context — children parent on it, and the
        annotation stamp persists it."""
        return TraceContext(self.trace_id, self.span_id, True)

    def event(self, msg: str) -> None:
        self.events.append((self.start + (time.perf_counter() - self._t0),
                            msg))

    def set(self, key: str, value) -> None:
        self.attrs[key] = value

    def activate(self) -> "Span":
        """Make this span's context the current one until :meth:`end`
        (server-span pattern: everything the handler does nests)."""
        if self._token is None:
            self._token = tc.attach(self.context())
        return self

    def end(self, **attrs) -> None:
        """Idempotent finish: stamp duration, hand to the collector,
        restore any activated context."""
        if self._ended:
            return
        self._ended = True
        if attrs:
            self.attrs.update(attrs)
        if self._token is not None:
            tc.detach(self._token)
            self._token = None
        end = self.start + (time.perf_counter() - self._t0)
        from .collector import COLLECTOR
        COLLECTOR.add({
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "component": self.component,
            "start": self.start,
            "end": end,
            "duration_ms": round((end - self.start) * 1e3, 3),
            "attrs": self.attrs,
            "events": [[round(ts, 6), msg] for ts, msg in self.events],
        })

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is not None:
            self.attrs.setdefault("error", repr(exc))
        self.end()


class _NoopSpan:
    """The disarmed/unsampled stand-in — every method is a no-op, so
    call sites never branch on tracing state themselves."""
    __slots__ = ()

    @property
    def noop(self) -> bool:
        return True

    def context(self) -> Optional[TraceContext]:
        return None

    def event(self, msg: str) -> None:
        pass

    def set(self, key: str, value) -> None:
        pass

    def activate(self) -> "_NoopSpan":
        return self

    def end(self, **attrs) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOOP_SPAN = _NoopSpan()


def start_span(name: str, component: str = "", parent=_SENTINEL,
               attrs: Optional[dict] = None):
    """A child span under ``parent`` (default: the current context).
    Returns :data:`NOOP_SPAN` when tracing is disarmed, there is no
    parent, or the parent is unsampled — spans exist only inside
    sampled traces; roots are minted by :func:`root_span` (or the
    apiserver's create stamp) where the sampling decision lives."""
    if not tc.armed():
        return NOOP_SPAN
    if parent is _SENTINEL:
        parent = tc.current()
    if parent is None or not getattr(parent, "sampled", False):
        return NOOP_SPAN
    return Span(name, component, parent, attrs)


def root_span(name: str, component: str = "",
              attrs: Optional[dict] = None):
    """Start a NEW trace (subject to the sample rate) — harnesses and
    ktl verbs use this so their server-side effects share one trace."""
    ctx = tc.sample_root()
    if ctx is None:
        return NOOP_SPAN
    span = Span(name, component, ctx, attrs)
    # The minted root context's span id IS this span (sample_root made
    # a placeholder id; the span is the trace's real root).
    span.parent_id = ""
    return span
