"""Trace context — W3C-traceparent-style ids + contextvar propagation.

Reference: the ``traceparent`` header of the W3C Trace Context spec
(``00-<32 hex trace id>-<16 hex span id>-<2 hex flags>``) — the same
wire shape OpenTelemetry's kube-apiserver tracing emits — carried here
on three channels:

- HTTP requests (``client/rest.py`` stamps the header, the apiserver
  middleware decodes it);
- object annotations (``trace.tpu/traceparent`` on Pods/PodGroups,
  stamped by ``Registry.create``) — the durable channel: the id rides
  MVCC watch events to every informer, so components that never saw
  the originating request still join the pod's trace;
- an asyncio :class:`contextvars.ContextVar` inside each process (the
  in-task channel informers re-attach on handler delivery).

Arming: ``KTPU_TRACE`` env, same opt-in style as TPU_CHAOS/TPU_SAN.
``1``/``on``/``true`` arms at the DEFAULT sample rate (0.01 — one pod
in a hundred pays for spans; the other 99 cost one rng call at create
and nothing after); an explicit float (``0.5``, ``1.0``) arms at that
rate; unset/``0``/``off`` disarms — the hot path then pays a single
module-bool check per seam.
"""
from __future__ import annotations

import contextlib
import os
import random
from contextvars import ContextVar
from dataclasses import dataclass
from typing import Optional

#: Durable per-object trace pointer (Pods/PodGroups): the full
#: traceparent of the create span, so later components parent on it.
TRACEPARENT_ANNOTATION = "trace.tpu/traceparent"
#: Event breadcrumb (client/record.py): bare trace id, so ``ktl trace
#: pod`` can interleave the pod's Events with its spans.
TRACE_ID_ANNOTATION = "trace.tpu/trace-id"
#: HTTP header (client/rest.py -> apiserver middleware).
TRACEPARENT_HEADER = "traceparent"

DEFAULT_SAMPLE_RATE = 0.01

#: Id source: a private Random so tracing never perturbs globally
#: seeded streams (chaos/tpusan own their Random instances; the global
#: module rng belongs to jitter/backoff callers).
_rng = random.Random(os.urandom(8))

_CURRENT: ContextVar[Optional["TraceContext"]] = ContextVar(
    "ktpu_trace_ctx", default=None)


@dataclass(frozen=True)
class TraceContext:
    trace_id: str  # 32 lowercase hex chars
    span_id: str   # 16 lowercase hex chars
    sampled: bool = True


def _parse_rate(raw: str) -> float:
    raw = raw.strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return 0.0
    if raw in ("1", "on", "true", "yes"):
        return DEFAULT_SAMPLE_RATE
    try:
        rate = float(raw)
    except ValueError:
        # Malformed values DISARM (and say so): "0.5x" must not
        # silently arm at a rate the operator never chose — the
        # documented contract is that only recognized values arm.
        import logging
        logging.getLogger("tracing").warning(
            "KTPU_TRACE=%r is not a recognized value; tracing stays "
            "OFF (use 1/on for the default %.2f rate, or a float)",
            raw, DEFAULT_SAMPLE_RATE)
        return 0.0
    return min(max(rate, 0.0), 1.0)


#: Effective sample rate; 0.0 = tracing disarmed entirely.
_RATE = _parse_rate(os.environ.get("KTPU_TRACE", ""))


def armed() -> bool:
    """True when tracing is on at all — the ONE check every hot-path
    seam makes before touching contexts or annotations."""
    return _RATE > 0.0


def sample_rate() -> float:
    return _RATE


def set_sample_rate(rate: float) -> float:
    """Re-arm at ``rate`` (tests/harnesses); returns the previous rate
    so callers can restore it."""
    global _RATE
    prev = _RATE
    _RATE = min(max(float(rate), 0.0), 1.0)
    return prev


def new_trace_id() -> str:
    return f"{_rng.getrandbits(128):032x}"


def new_span_id() -> str:
    return f"{_rng.getrandbits(64):016x}"


def sample_root() -> Optional[TraceContext]:
    """A fresh root context, subject to the sample rate: None means
    'this trace is not taken' — callers then stamp/open nothing, which
    IS the overhead gate (an unsampled pod costs one rng call here and
    zero work everywhere downstream)."""
    if _RATE <= 0.0 or (_RATE < 1.0 and _rng.random() >= _RATE):
        return None
    return TraceContext(new_trace_id(), new_span_id(), True)


def encode(ctx: TraceContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


def decode(header: Optional[str]) -> Optional[TraceContext]:
    """Strict-enough traceparent parse; None for anything malformed
    (a bad header must degrade to 'untraced', never to an error)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16 \
            or len(flags) != 2:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id.lower(), span_id.lower(),
                        bool(int(flags, 16) & 1))


# -- contextvar plumbing ---------------------------------------------------

def current() -> Optional[TraceContext]:
    return _CURRENT.get()


def attach(ctx: Optional[TraceContext]):
    """Set the current context; returns the token for :func:`detach`."""
    return _CURRENT.set(ctx)


def detach(token) -> None:
    _CURRENT.reset(token)


@contextlib.contextmanager
def use(ctx: Optional[TraceContext]):
    token = _CURRENT.set(ctx)
    try:
        yield ctx
    finally:
        _CURRENT.reset(token)


# -- object annotations ----------------------------------------------------

def context_of(obj) -> Optional[TraceContext]:
    """The trace context stamped on an API object (Pod/PodGroup), or
    None. Cheap by construction: one dict get + decode, and callers
    gate on :func:`armed` first."""
    try:
        raw = obj.metadata.annotations.get(TRACEPARENT_ANNOTATION)
    except AttributeError:
        return None
    return decode(raw)


def stamp(obj, ctx: TraceContext) -> None:
    obj.metadata.annotations[TRACEPARENT_ANNOTATION] = encode(ctx)
