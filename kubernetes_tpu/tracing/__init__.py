"""End-to-end pod-lifecycle tracing (the ktrace layer).

Zero-dependency span layer with W3C-traceparent-style context
propagation: ``RESTClient`` stamps outgoing requests, the apiserver
middleware opens a server span and ``Registry.create`` stamps sampled
Pods/PodGroups with a durable ``trace.tpu/traceparent`` annotation, the
annotation rides MVCC watch events to every informer (which re-attach
it around handler delivery), and the scheduler/queue/node-agent open
child spans — one pod's life (create -> queue -> schedule -> bind ->
pull -> start -> ready) reconstructs as a single trace.

Armed via ``KTPU_TRACE`` (see context.py); disarmed, every seam costs
one module-bool check. Finished spans land in the bounded in-process
:data:`COLLECTOR` (collector.py), surfaced by ``GET /debug/v1/traces``
and rendered by ``ktl trace pod|gang``.
"""
from .collector import COLLECTOR, SpanCollector  # noqa: F401
from .context import (  # noqa: F401
    DEFAULT_SAMPLE_RATE, TRACE_ID_ANNOTATION, TRACEPARENT_ANNOTATION,
    TRACEPARENT_HEADER, TraceContext, armed, attach, context_of, current,
    decode, detach, encode, sample_rate, sample_root, set_sample_rate,
    stamp, use)
from .span import NOOP_SPAN, Span, root_span, start_span  # noqa: F401
