"""Pod-lifecycle timeline reconstruction from spans.

The ONE place stage boundaries are defined — ``ktl trace pod``,
``hack/trace_smoke.sh``'s completeness gate, and the perf harnesses'
startup-breakdown stanzas all call :func:`pod_timeline` /
:func:`stage_breakdown`, so "what counts as the queue stage" cannot
drift between the CLI and the gates.

Stage model (create -> ready, every wall-clock moment attributed):

    create    trace start        -> queue span start
    queue     queue span start   -> schedule span start
    schedule  schedule start     -> bind span start
    bind      bind start         -> bind end
    start     bind end           -> startup span end (node: admit,
              image pull, container start, readiness — pull/start ride
              as child spans inside ``startup``)

Boundaries are span START times, so inter-component gaps (watch
delivery, informer dispatch) are charged to the stage that was
"holding" the pod — the sum of stage durations therefore equals the
trace's e2e latency BY CONSTRUCTION, and the smoke's 5% check verifies
the trace against an externally measured wall clock, not against
itself.
"""
from __future__ import annotations

from typing import Optional, Sequence

#: Spans that anchor stage boundaries, in lifecycle order.
ANCHOR_SPANS = ("create", "queue", "schedule", "bind", "startup")
#: Stages reported, in order.
STAGES = ("create", "queue", "schedule", "bind", "start")


def _first(spans: Sequence[dict], name: str) -> Optional[dict]:
    """Earliest span of ``name`` (requeues/retries re-open stages; the
    first occurrence anchors the boundary, repeats show as events)."""
    best = None
    for s in spans:
        if s.get("name") != name:
            continue
        if best is None or s.get("start", 0.0) < best.get("start", 0.0):
            best = s
    return best


def pod_timeline(spans: Sequence[dict]) -> Optional[dict]:
    """Reconstruct one pod's stage timeline from its trace's spans.

    Returns ``{"start", "end", "e2e_ms", "complete", "stages": [
    {"stage", "start_ms", "duration_ms", "share"}, ...]}`` or None when
    no anchor span is present at all. ``complete`` is True only when
    the full create->queue->schedule->bind->startup chain is there —
    the trace_smoke gate's definition of "a complete trace
    reconstructs"."""
    anchors = {name: _first(spans, name) for name in ANCHOR_SPANS}
    present = [n for n in ANCHOR_SPANS if anchors[n] is not None]
    if not present:
        return None
    t0 = min(anchors[n]["start"] for n in present)
    # The trace ends when the pod is ready (startup span end). With no
    # startup span (registry-only harnesses, pod not yet on a node)
    # the LAST ANCHOR's end bounds the timeline and the "start" stage
    # is omitted — a residual tail must not masquerade as node time.
    stages_here: tuple = STAGES
    if anchors["startup"] is not None:
        t_end = anchors["startup"].get("end", t0)
    else:
        stages_here = tuple(s for s in STAGES if s != "start")
        t_end = max(anchors[n].get("end", t0) for n in present)
    # Stage boundary = next anchor's start; the last stage runs to the
    # trace end. Missing anchors collapse their stage to zero at the
    # next known boundary (and mark the timeline incomplete).
    starts: list[tuple[str, float]] = []
    cursor = t0
    boundary_of = {
        "create": anchors["create"],
        "queue": anchors["queue"],
        "schedule": anchors["schedule"],
        "bind": anchors["bind"],
        "start": anchors["bind"],  # start stage begins at bind END
    }
    for stage in stages_here:
        a = boundary_of[stage]
        if stage == "create":
            begin = t0
        elif stage == "start":
            begin = (a.get("end", cursor) if a is not None else cursor)
        else:
            begin = (a.get("start", cursor) if a is not None else cursor)
        begin = max(begin, cursor)
        starts.append((stage, begin))
        cursor = begin
    e2e = max(t_end - t0, 0.0)
    stages = []
    for i, (stage, begin) in enumerate(starts):
        nxt = starts[i + 1][1] if i + 1 < len(starts) else t_end
        dur = max(nxt - begin, 0.0)
        stages.append({
            "stage": stage,
            "start_ms": round((begin - t0) * 1e3, 3),
            "duration_ms": round(dur * 1e3, 3),
            "share": round(dur / e2e, 4) if e2e > 0 else 0.0,
        })
    return {
        "start": t0,
        "end": t_end,
        "e2e_ms": round(e2e * 1e3, 3),
        "complete": all(anchors[n] is not None for n in ANCHOR_SPANS),
        "stages": stages,
    }


def check_nesting(spans: Sequence[dict]) -> list[str]:
    """Structural violations in one trace's spans: a child starting
    before its parent, or a span ending before it starts. Returns
    human-readable problems (empty = clean) — the integration test's
    'monotonic, nested' assertion."""
    by_id = {s.get("span_id"): s for s in spans}
    problems = []
    for s in spans:
        if s.get("end", 0.0) + 1e-9 < s.get("start", 0.0):
            problems.append(f"span {s.get('name')} ends before it starts")
        parent = by_id.get(s.get("parent_id") or "")
        if parent is not None \
                and s.get("start", 0.0) + 1e-9 < parent.get("start", 0.0):
            problems.append(
                f"span {s.get('name')} starts before its parent "
                f"{parent.get('name')}")
    return problems


def stage_breakdown(all_spans: Sequence[dict]) -> dict:
    """Aggregate per-stage breakdown over MANY traces — the perf
    harnesses' span-derived startup decomposition. Groups spans by
    trace id, reconstructs each timeline, and reports per-stage
    raw-sample percentiles (p50/p99 ms, the package's nearest-rank
    definition) plus each stage's share of total attributed time, so a
    future perf PR attacks the measured stage, not a guess. Stages
    with no samples are omitted (registry-only harnesses have no node
    half, hence no ``start`` stage)."""
    from ..perf import pct
    by_trace: dict[str, list] = {}
    for s in all_spans:
        by_trace.setdefault(s.get("trace_id", ""), []).append(s)
    samples: dict[str, list[float]] = {}
    e2e: list[float] = []
    traces = 0
    for spans in by_trace.values():
        tl = pod_timeline(spans)
        if tl is None:
            continue
        traces += 1
        e2e.append(tl["e2e_ms"])
        for st in tl["stages"]:
            if st["duration_ms"] > 0.0:
                samples.setdefault(st["stage"], []).append(
                    st["duration_ms"])
    total = sum(sum(v) for v in samples.values())
    out: dict = {"traces": traces}
    if e2e:
        ordered = sorted(e2e)
        out["e2e_p50_ms"] = round(pct(ordered, 0.5), 3)
        out["e2e_p99_ms"] = round(pct(ordered, 0.99), 3)
    for stage in STAGES:
        vals = samples.get(stage)
        if not vals:
            continue
        ordered = sorted(vals)
        out[stage] = {
            "p50_ms": round(pct(ordered, 0.5), 3),
            "p99_ms": round(pct(ordered, 0.99), 3),
            "share": round(sum(vals) / total, 4) if total > 0 else 0.0,
        }
    return out
