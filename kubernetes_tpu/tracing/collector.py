"""Bounded in-process span collector + JSONL export.

The sink every finished span lands in. Bounded like the watch queues:
a ring of ``max_spans`` (oldest dropped, counted) — tracing must never
grow memory with uptime. Exposed three ways:

- ``GET /debug/v1/traces`` on the apiserver (server.py) serves this
  process's buffer filtered by trace id / pod / component;
- ``POST /debug/v1/traces`` ingests spans pushed by OUT-of-process
  components (multi-host agents; in a LocalCluster every component
  shares this process and no push is needed);
- ``KTPU_TRACE_EXPORT=<path>`` appends every collected span as one
  JSON line at process exit (offline analysis; perf harnesses read
  the buffer directly instead).
"""
from __future__ import annotations

import json
import os
from collections import deque
from typing import Optional

from ..metrics.registry import Counter, Gauge
from ..util.lockdep import make_lock

TRACE_SPANS = Counter(
    "trace_spans_total",
    "Finished spans collected, by component",
    labels=("component",))

TRACE_SPANS_DROPPED = Counter(
    "trace_spans_dropped_total",
    "Spans evicted from the bounded collector ring (oldest-first)")

TRACE_BUFFER_SPANS = Gauge(
    "trace_buffer_spans",
    "Spans currently retained in the in-process collector")

#: Ring size; override via KTPU_TRACE_BUFFER. Sized for a traced
#: LocalCluster run (a pod's lifecycle is ~6-8 spans; 16k spans covers
#: ~2k traced pods) — perf arms sample, so they stay far below it.
_DEFAULT_MAX = 16384


class SpanCollector:
    def __init__(self, max_spans: Optional[int] = None):
        if max_spans is None:
            try:
                max_spans = int(os.environ.get("KTPU_TRACE_BUFFER", "")
                                or _DEFAULT_MAX)
            except ValueError:
                max_spans = _DEFAULT_MAX
        self.max_spans = max(1, max_spans)
        self._spans: deque[dict] = deque(maxlen=self.max_spans)
        #: Shard workers are real threads; the ring must not corrupt.
        self._lock = make_lock("tracing.SpanCollector")
        self.dropped = 0

    def add(self, span: dict) -> None:
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1
                TRACE_SPANS_DROPPED.inc()
            self._spans.append(span)
            TRACE_BUFFER_SPANS.set(float(len(self._spans)))
        TRACE_SPANS.inc(component=span.get("component", ""))

    def ingest(self, spans: list) -> int:
        """Accept externally produced span dicts (the POST surface);
        returns how many were taken. Malformed items are skipped —
        telemetry ingest must never 500 a remote agent into backoff."""
        taken = 0
        for s in spans:
            if isinstance(s, dict) and s.get("trace_id") \
                    and s.get("span_id"):
                self.add(s)
                taken += 1
        return taken

    def snapshot(self, trace_id: str = "", pod: str = "",
                 component: str = "", limit: int = 0) -> list[dict]:
        """Matching spans, oldest first. ``pod`` matches the span's
        ``attrs.pod`` ("ns/name"). ``limit`` keeps the NEWEST N."""
        with self._lock:
            spans = list(self._spans)
        if trace_id:
            spans = [s for s in spans if s.get("trace_id") == trace_id]
        if pod:
            spans = [s for s in spans
                     if (s.get("attrs") or {}).get("pod") == pod]
        if component:
            spans = [s for s in spans if s.get("component") == component]
        if limit > 0 and len(spans) > limit:
            spans = spans[-limit:]
        return spans

    def trace_ids(self) -> set[str]:
        with self._lock:
            return {s.get("trace_id", "") for s in self._spans}

    def __len__(self) -> int:
        return len(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0
            TRACE_BUFFER_SPANS.set(0.0)

    def export_jsonl(self, path: str) -> int:
        """Append every retained span as one JSON line; returns the
        span count written."""
        with self._lock:
            spans = list(self._spans)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "a") as f:
            for s in spans:
                f.write(json.dumps(s, sort_keys=True) + "\n")
        return len(spans)

    def dump_jsonl(self) -> str:
        with self._lock:
            spans = list(self._spans)
        return "".join(json.dumps(s, sort_keys=True) + "\n" for s in spans)


#: Process-global collector (per-component collectors are possible by
#: constructing SpanCollector directly; everything in-tree shares).
COLLECTOR = SpanCollector()

_export_path = os.environ.get("KTPU_TRACE_EXPORT", "")
if _export_path:
    import atexit

    atexit.register(lambda: COLLECTOR.export_jsonl(_export_path))
