"""Graceful-preemption engine — signal → checkpoint → requeue.

The ONE implementation every eviction path shares (ISSUE 7 / ROADMAP
open item 5): scheduler gang preemption (``scheduler/scheduler.py
_preempt_gang``), partial-bind recovery (``_evict_gang_survivors``)
and the QueueController's fair-share reclaim all route gang evictions
here. Behind the ``GracefulPreemption`` feature gate (default off =
the legacy ~1s hard kill, byte-identical); a gang opts in with
``spec.checkpoint`` (grace seconds + signal mode).

Protocol (state durable in ``PodGroup.status.preemption`` — it rides
the MVCC WAL like admission state, so a control-plane crash resumes
the round instead of forgetting a signaled gang):

1. **Signal** — stamp ``phase=Signaled`` with the member set and an
   absolute deadline (now + grace), then annotate each member pod
   with :data:`~kubernetes_tpu.api.types.PREEMPT_ANNOTATION`. The
   node agent sees the annotation and delivers the in-container
   request (``KTPU_PREEMPT_FILE`` appears; SIGTERM per the signal
   mode) — see ``node/agent.py``.
2. **Checkpoint** — the workload saves (Orbax, ``workloads/
   checkpoint.py``) and writes an atomic checkpoint-complete marker
   beside the step dir; the agent reads it and calls
   :func:`record_member_checkpoint`, which appends the member and
   raises ``checkpoint_step`` MONOTONICALLY (the tpusan
   checkpoint-monotonic invariant watches exactly this field).
3. **Requeue** — a finisher task waits until every still-live
   signaled member reported (members that die mid-checkpoint drop
   out of the quorum — a crashed pod must not make the gang pay the
   full deadline) or the deadline passes, then evicts the members
   (the legacy kill) and stamps ``phase=Requeued`` with the outcome.
   The workload's next incarnation resumes from the recorded step
   via ``KTPU_JOB_NAME`` — reclaim costs one checkpoint interval,
   not the job.

A wedged workload can never hold quota hostage: the deadline path IS
the legacy eviction, just delayed by the gang's own grace budget.
The engine is level-triggered and re-entrant — re-invoking it on an
already-signaled gang past its deadline finishes the round, so a
crashed finisher task only costs latency, never convergence.

Chaos: the ``preempt`` injection site ("kill-member") force-deletes
one signaled member between signal and marker — the mid-checkpoint
crash the protocol must converge through without double-booking
chips or resuming from a torn step.
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Iterable, Optional

from .api import errors, types as t
from .api.meta import now as meta_now
from .metrics.registry import Counter, Gauge, Histogram
from .util.tasks import spawn

log = logging.getLogger("preemption")

#: Finisher poll cadence while waiting for checkpoint reports.
POLL_SECONDS = 0.05

#: Checkpoint-complete marker filename, published atomically beside
#: the Orbax step dirs. Canonical here (import-light — the node agent
#: reads markers without pulling jax); ``workloads/checkpoint.py``
#: re-exports it for the workload side.
MARKER_NAME = "ktpu-preempt-complete.json"


def job_checkpoint_dir(job: str, base: str = "") -> str:
    """Mirror of ``workloads.checkpoint.checkpoint_dir`` without the
    jax import: the agent computes the same path the workload uses
    (<base>/<job>, job = the agent-injected ``KTPU_JOB_NAME``)."""
    import os
    base = base or os.environ.get("KTPU_CHECKPOINT_DIR", "/tmp/ktpu-ckpt")
    return os.path.join(base, job)


def marker_path(ckpt_dir: str) -> str:
    import os
    return os.path.join(ckpt_dir, MARKER_NAME)


def read_marker_info(ckpt_dir: str) -> Optional[tuple[int, float]]:
    """(step, write time) of the published checkpoint-complete marker,
    or None when absent/unreadable (a torn tmp file is invisible by
    construction — the writer publishes via rename). Callers use the
    write time to reject a STALE marker left by an earlier round: the
    checkpoint dir is shared per job, and a survivor of an elastic
    shrink never restarts, so nothing clears the old round's marker."""
    import json
    try:
        with open(marker_path(ckpt_dir), encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    step = data.get("step")
    if not isinstance(step, int) or step < 0:
        return None
    ts = data.get("time")
    return step, float(ts) if isinstance(ts, (int, float)) else 0.0


def read_marker(ckpt_dir: str) -> Optional[int]:
    info = read_marker_info(ckpt_dir)
    return info[0] if info is not None else None

CHECKPOINT_WAIT = Histogram(
    "preemption_checkpoint_wait_seconds",
    "Signal to quorum-checkpoint-complete (or deadline) per gang round",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0, 60.0, 300.0),
    # Raw samples: the --reclaim-storm bench reports true p50/p99.
    sample_limit=100_000)

SIGNALED = Counter(
    "preemption_signaled_total",
    "Graceful-preemption rounds signaled, by initiating path",
    labels=("reason",))

ROUNDS = Counter(
    "preemption_rounds_total",
    "Graceful-preemption rounds finished, by outcome "
    "(checkpointed|deadline)",
    labels=("outcome",))

SHRINKS = Counter(
    "preemption_shrinks_total",
    "Elastic gangs shrunk to min_replicas under reclaim (instead of "
    "a full unadmit)")

GOODPUT = Gauge(
    "preemption_goodput_ratio",
    "Fraction of pre-reclaim training steps retained across the last "
    "reclaim storm, per bench mode (evict|graceful)",
    labels=("mode",))


def enabled() -> bool:
    from .util.features import GATES
    return GATES.enabled("GracefulPreemption")


def eligible(group: Optional[t.PodGroup]) -> bool:
    """Does this gang take the graceful path? Gate on AND the gang
    opted in with a positive checkpoint grace budget."""
    if group is None or not enabled():
        return False
    ck = group.spec.checkpoint
    return ck is not None and ck.grace_seconds > 0


def elastic_target(group: t.PodGroup) -> int:
    """Member count the scheduler may bind up to. 0 = not elastic /
    gate off (no cap)."""
    if not enabled() or not group.spec.max_replicas:
        return 0
    return min(group.status.replicas or group.spec.max_replicas,
               group.spec.max_replicas)


def _chaos_kill_member(members: list[t.Pod]) -> Optional[t.Pod]:
    """The ``preempt`` chaos site: a ``kill-member`` fault names one
    signaled member to force-delete mid-checkpoint."""
    from .chaos import core as chaos
    c = chaos.CONTROLLER
    if c is None or not members:
        return None
    fault = c.decide(chaos.SITE_PREEMPT)
    if fault is not None and fault.kind == "kill-member":
        return members[int(fault.param) % len(members)]
    return None


async def _update_group_status(client, ns: str, name: str, mutate,
                               retries: int = 8) -> Optional[t.PodGroup]:
    """rv-guarded read-modify-write of a PodGroup's status; ``mutate``
    returns False to abort (stale round). None when the group is gone
    or the mutation aborted."""
    for _ in range(retries):
        try:
            cur = await client.get("podgroups", ns, name)
        except errors.NotFoundError:
            return None
        if mutate(cur) is False:
            return None
        try:
            await client.update_status(cur)
            return cur
        except errors.ConflictError:
            continue
        except errors.NotFoundError:
            return None
    log.warning("preemption: status write for %s/%s kept conflicting",
                ns, name)
    return None


async def signal_gang(client, group: t.PodGroup, members: list[t.Pod],
                      *, reason: str, recorder=None,
                      wait: bool = False) -> bool:
    """Start (or resume) a graceful round for ``members`` of ``group``.

    Idempotent/level-triggered: an in-flight round for the same (or a
    superset) member set is left alone; a round past its deadline is
    finished here. Returns True when a graceful round is running or
    was just completed — the caller must NOT hard-evict; False means
    the caller should fall back to the legacy kill (not eligible).

    ``wait=True`` runs the finisher inline (harness/controller use);
    the scheduler passes False so placement never blocks on a grace
    budget.
    """
    if not eligible(group):
        return False
    members = [p for p in members if t.is_pod_active(p)]
    if not members:
        return True  # nothing left to signal; round is trivially done
    ns = group.metadata.namespace
    name = group.metadata.name
    grace = group.spec.checkpoint.grace_seconds
    names = sorted(p.metadata.name for p in members)
    deadline = time.time() + grace

    inflight = {"hit": False}
    round_names = {"names": names}

    def mutate(cur: t.PodGroup):
        st = cur.status.preemption
        kept: list[str] = []
        merged = names
        if st is not None and st.phase in (t.PREEMPT_SIGNALED,
                                           t.PREEMPT_CHECKPOINTING):
            if time.time() <= st.deadline:
                if set(names) <= set(st.signaled):
                    inflight["hit"] = True
                    return False  # round covers us: its finisher owns it
                # WIDEN the round: a full reclaim arriving while an
                # elastic-shrink round is mid-flight must cover the
                # survivors too — a no-op here would leave them to the
                # sweep's hard kill with no signal. Union the member
                # sets (keeping reported checkpoints); the old
                # finisher aborts on the signaled-set change and the
                # one spawned below takes over.
                merged = sorted(set(st.signaled) | set(names))
                kept = [m for m in st.checkpointed if m in merged]
            # else: stale round (crashed finisher) — restart the clock.
        cur.status.preemption = t.PreemptionStatus(
            phase=(t.PREEMPT_CHECKPOINTING if kept
                   else t.PREEMPT_SIGNALED),
            signaled=merged, checkpointed=kept,
            checkpoint_step=st.checkpoint_step if st is not None else -1,
            signaled_time=meta_now(), deadline=deadline,
            rounds=st.rounds if st is not None else 0)
        round_names["names"] = merged
        return None

    cur = await _update_group_status(client, ns, name, mutate)
    if cur is None:
        # In-flight round (finisher owns it) or the group vanished
        # (NotFound: the gang is over — nothing to signal; the caller
        # falls back to the legacy kill for any stragglers).
        return inflight["hit"]
    names = round_names["names"]
    SIGNALED.inc(reason=reason)
    if recorder is not None:
        recorder.event(group, "Normal", "PreemptionSignaled",
                       f"{reason}: {len(names)} members have "
                       f"{grace:g}s to checkpoint")
    # Mid-checkpoint crash injection (chaos site "preempt"): the
    # victim dies AFTER the Signaled stamp but BEFORE its signal is
    # delivered (annotated) — it can never publish a marker, exactly
    # the member-crash window the protocol must converge through.
    # Ordered before the annotation loop so a schedule explorer sees
    # one deterministic story: a dead member is never annotated.
    victim = _chaos_kill_member(members)
    if victim is not None:
        log.warning("chaos: killing member %s between signal and marker",
                    victim.key())
        try:
            await client.delete("pods", victim.metadata.namespace,
                                victim.metadata.name,
                                grace_period_seconds=0)
        except errors.StatusError:
            pass
    # Annotate member pods — the node agent's cue to deliver the
    # in-container signal (file + SIGTERM per spec.checkpoint.signal).
    # Value: "<unix deadline>;<signal mode>".
    stamp = f"{deadline!r};{group.spec.checkpoint.signal}"
    for pod in members:
        if victim is not None and pod.key() == victim.key():
            continue
        try:
            fresh = await client.get("pods", pod.metadata.namespace,
                                     pod.metadata.name)
            if fresh.metadata.annotations.get(t.PREEMPT_ANNOTATION) \
                    == stamp:
                continue
            # Overwrite a STALE stamp (restarted round): the agent
            # keys its delivery dedup on the value, so an unchanged
            # old annotation would leave the new round with no marker
            # watcher — every save would go unreported.
            fresh.metadata.annotations[t.PREEMPT_ANNOTATION] = stamp
            await client.update(fresh)
        except errors.StatusError as e:
            # Annotation is best-effort delivery acceleration; the
            # deadline backstop guarantees progress without it.
            log.debug("preempt annotation for %s: %s", pod.key(), e)
    coro = _finish_round(client, ns, name, names, deadline,
                         time.time(), recorder)
    if wait:
        await coro
    else:
        spawn(coro, name=f"preempt-finish-{ns}/{name}")
    return True


async def finish_stale_round(client, group: t.PodGroup) -> bool:
    """Crash backstop (the QueueController sweep calls this): a round
    whose finisher died is completed once its deadline passed — evict
    + stamp Requeued. False while the round is still in flight (or
    there is none); the caller must then leave the gang alone."""
    st = group.status.preemption
    if st is None or st.phase not in (t.PREEMPT_SIGNALED,
                                      t.PREEMPT_CHECKPOINTING):
        return False
    if time.time() <= st.deadline:
        return False
    await _finish_round(client, group.metadata.namespace,
                        group.metadata.name, sorted(st.signaled),
                        st.deadline, signaled_at=None)
    return True


async def _finish_round(client, ns: str, name: str, names: list[str],
                        deadline: float, signaled_at: Optional[float],
                        recorder=None) -> None:
    """Wait for every still-live signaled member to report (or the
    deadline), then evict and stamp Requeued."""
    outcome = "deadline"
    while True:
        try:
            cur = await client.get("podgroups", ns, name)
        except errors.NotFoundError:
            return  # gang deleted mid-round: nothing to requeue
        st = cur.status.preemption
        if st is None or sorted(st.signaled) != names:
            return  # a newer round superseded this finisher
        live = set()
        for pod_name in names:
            try:
                pod = await client.get("pods", ns, pod_name)
            except errors.NotFoundError:
                continue
            if t.is_pod_active(pod):
                live.add(pod_name)
        reported = set(st.checkpointed)
        if live <= reported:
            outcome = "checkpointed" if reported else "deadline"
            break
        if time.time() > deadline:
            break
        await asyncio.sleep(POLL_SECONDS)
    if signaled_at is not None:
        CHECKPOINT_WAIT.observe(max(0.0, time.time() - signaled_at))
    ROUNDS.inc(outcome=outcome)
    # The kill half — exactly the legacy eviction, checkpoint later.
    for pod_name in names:
        try:
            await client.evict(ns, pod_name,
                               t.Eviction(override_budget=True))
        except errors.StatusError:
            pass

    def mutate(cur: t.PodGroup):
        st = cur.status.preemption
        if st is None or sorted(st.signaled) != names \
                or st.phase == t.PREEMPT_REQUEUED:
            return False
        st.phase = t.PREEMPT_REQUEUED
        st.outcome = outcome
        st.requeued_time = meta_now()
        st.rounds += 1
        return None

    cur = await _update_group_status(client, ns, name, mutate)
    if cur is not None and recorder is not None:
        step = cur.status.preemption.checkpoint_step
        recorder.event(cur, "Normal", "PreemptionRequeued",
                       f"gang requeued ({outcome}); resume step "
                       f"{step if step >= 0 else '<none>'}")


async def record_member_checkpoint(client, ns: str, gang: str,
                                   member: str, step: int) -> bool:
    """A member finished its checkpoint (the node agent read the
    atomic marker; harnesses call this directly as the simulated
    workload). ``checkpoint_step`` only ever RISES — a stale or torn
    marker can never rewind the gang's resume point."""

    def mutate(cur: t.PodGroup):
        st = cur.status.preemption
        if st is None:
            # No engine round in flight — a DIRECT graceful delete
            # (someone deleted the pod with grace) still records the
            # resume point; the phase stays idle.
            st = cur.status.preemption = t.PreemptionStatus()
        appended = False
        if member not in st.checkpointed and st.phase in (
                "", t.PREEMPT_SIGNALED, t.PREEMPT_CHECKPOINTING) \
                and (not st.signaled or member in st.signaled):
            st.checkpointed.append(member)
            appended = True
        new_step = max(st.checkpoint_step, int(step))
        if not appended and new_step == st.checkpoint_step:
            return False
        st.checkpoint_step = new_step
        if st.phase == t.PREEMPT_SIGNALED:
            st.phase = t.PREEMPT_CHECKPOINTING
        return None

    return await _update_group_status(client, ns, gang, mutate) is not None


async def preempt_victims(client, victims: Iterable[t.Pod], *,
                          reason: str, recorder=None) -> list[t.Pod]:
    """Shared entry for victim sets that may span gangs (scheduler
    gang preemption). Gracefully signals every eligible gang; returns
    the pods the caller must still hard-evict itself (loose pods and
    members of non-opted-in gangs) — so the gate-off path stays
    byte-identical in the caller's hands."""
    by_gang: dict[str, list[t.Pod]] = {}
    legacy: list[t.Pod] = []
    for pod in victims:
        if pod.spec.gang:
            by_gang.setdefault(
                f"{pod.metadata.namespace}/{pod.spec.gang}", []).append(pod)
        else:
            legacy.append(pod)
    for gk, members in sorted(by_gang.items()):
        ns, gname = gk.split("/", 1)
        try:
            group = await client.get("podgroups", ns, gname)
        except errors.StatusError:
            group = None
        handled = group is not None and await signal_gang(
            client, group, members, reason=reason, recorder=recorder)
        if not handled:
            legacy.extend(members)
    return legacy
