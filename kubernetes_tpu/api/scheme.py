"""Type registry + codec — the L0 'runtime.Scheme' equivalent.

The reference centralises serialization/conversion/defaulting in
``staging/src/k8s.io/apimachinery/pkg/runtime`` (``Scheme``,
codecs). Here the object model is Python dataclasses, so the codec is a
generic structural serde driven by type hints:

- ``to_dict(obj)``   dataclass -> plain JSON-able dict (None / empty
  collections elided, datetimes to RFC3339, enums to value).
- ``from_dict(cls, d)`` dict -> dataclass, recursing through
  Optional/list/dict type hints; unknown fields are *preserved* in
  ``obj.__extra__`` so round-tripping never loses data (the reference
  gets this from protobuf/JSON struct tags).
- ``Scheme``         maps (api_version, kind) <-> class and applies
  per-type defaulting functions, like ``runtime.Scheme`` does.
"""
from __future__ import annotations

import dataclasses
import datetime
import enum
import json
import types as _pytypes
import typing
from typing import Any, Optional, Type, TypeVar, get_args, get_origin, get_type_hints

T = TypeVar("T")

_RFC3339 = "%Y-%m-%dT%H:%M:%S.%fZ"


def _enc_time(dt: datetime.datetime) -> str:
    if dt.tzinfo is not None:
        dt = dt.astimezone(datetime.timezone.utc).replace(tzinfo=None)
    # isoformat is C-accelerated; force the microsecond field so the
    # wire format stays exactly _RFC3339 regardless of dt.microsecond.
    return dt.isoformat(timespec="microseconds") + "Z"


def _dec_time(s: str) -> datetime.datetime:
    # fromisoformat is C-accelerated (~20x strptime), but only 3.11+
    # accepts the trailing 'Z' — strip it up front, or every timestamp
    # decode on 3.10 pays a raised ValueError + strptime (measured as
    # a per-pod hot-path cost: ~6 timestamps per decoded pod).
    try:
        dt = datetime.datetime.fromisoformat(
            s[:-1] if s.endswith("Z") else s)
    except ValueError:
        return datetime.datetime.strptime(s, _RFC3339)
    if dt.tzinfo is not None:
        dt = dt.astimezone(datetime.timezone.utc).replace(tzinfo=None)
    return dt


#: Per-class field names whose default list/dict is NON-empty: an
#: explicitly empty value there is meaningful (e.g. Namespace
#: spec.finalizers=[] means "no finalizers", not "use the default") and
#: must survive the wire instead of decoding back to the default.
_KEEP_EMPTY: dict[type, frozenset] = {}


def _keep_empty_fields(cls: type) -> frozenset:
    cached = _KEEP_EMPTY.get(cls)
    if cached is None:
        keep = set()
        for f in dataclasses.fields(cls):
            if f.default_factory is not dataclasses.MISSING:
                try:
                    if f.default_factory():
                        keep.add(f.name)
                except (TypeError, ValueError):
                    # Exotic factory needing arguments/state: treat the
                    # field as elidable-when-empty, same as MISSING.
                    continue
        cached = _KEEP_EMPTY[cls] = frozenset(keep)
    return cached


_ENC_FIELDS: dict[type, tuple] = {}


def _enc_fields(cls: type) -> tuple:
    """((field name, keep-when-empty), ...) cached per dataclass."""
    cached = _ENC_FIELDS.get(cls)
    if cached is None:
        keep = _keep_empty_fields(cls)
        cached = _ENC_FIELDS[cls] = tuple(
            (f.name, f.name in keep) for f in dataclasses.fields(cls))
    return cached


def to_dict(obj: Any) -> Any:
    """Recursively convert an API object into a JSON-able structure."""
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, datetime.datetime):
        return _enc_time(obj)
    if isinstance(obj, (list, tuple)):
        return [to_dict(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): to_dict(v) for k, v in obj.items()}
    if dataclasses.is_dataclass(obj):
        out: dict[str, Any] = {}
        # Elide empty collections and empty strings ("" means unset
        # throughout the model) to keep wire objects tight, but keep
        # false/0 scalars (they are meaningful, e.g. replicas: 0)
        # and empty collections on fields whose DEFAULT is
        # non-empty (an explicit [] there is a real value).
        # Exact-type fast paths: plain JSON scalars skip the recursive
        # call (encode is on the hot REST path with decode).
        for name, keep in _enc_fields(type(obj)):
            v = getattr(obj, name)
            if v is None:
                continue
            tv = v.__class__
            if tv is str:
                if v:
                    out[name] = v
                continue
            if tv is bool or tv is int or tv is float:
                out[name] = v
                continue
            if (tv is list or tv is dict) and not v:
                if keep:
                    out[name] = v.copy()
                continue
            out[name] = to_dict(v)
        extra = getattr(obj, "__extra__", None)
        if extra:
            for k, v in extra.items():
                out.setdefault(k, v)
        return out
    raise TypeError(f"cannot serialize {type(obj)!r}")


def _resolve_hint(hint: Any) -> Any:
    """Strip Optional[...] to its inner type; return hint otherwise."""
    origin = get_origin(hint)
    if origin is typing.Union or origin is _pytypes.UnionType:
        args = [a for a in get_args(hint) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return hint


def _coerce(hint: Any, value: Any) -> Any:
    if value is None:
        return None
    hint = _resolve_hint(hint)
    origin = get_origin(hint)
    if origin in (list, tuple):
        (inner,) = get_args(hint) or (Any,)
        seq = [_coerce(inner, v) for v in value]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        args = get_args(hint)
        vt = args[1] if len(args) == 2 else Any
        return {k: _coerce(vt, v) for k, v in value.items()}
    if isinstance(hint, type):
        if dataclasses.is_dataclass(hint):
            return from_dict(hint, value)
        if issubclass(hint, enum.Enum):
            return hint(value)
        if issubclass(hint, datetime.datetime):
            return _dec_time(value) if isinstance(value, str) else value
        if hint is float and isinstance(value, int):
            return float(value)
    return value


_HINT_CACHE: dict[type, dict[str, Any]] = {}


def _hints(cls: type) -> dict[str, Any]:
    h = _HINT_CACHE.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _HINT_CACHE[cls] = h
    return h


#: Per-dataclass compiled decoders: field -> specialized coercer
#: callable, or None when the JSON value passes through untouched
#: (str/int/bool/Any — the common case). Decode is the hottest path in
#: the REST stack (every watch event and response body), so the
#: per-call typing introspection of :func:`_coerce` is done once per
#: class here instead of once per field per object.
_DECODER_CACHE: dict[type, dict[str, Any]] = {}


def _make_coercer(hint: Any):
    """Specialized coercer for ``hint`` or None for identity.

    Identity is only for immutable scalars. Containers ALWAYS build a
    fresh object (``list``/``dict`` constructors when elements are
    plain) — decoded objects must never alias the source dict, because
    the registry decodes straight from the store's live values
    (``store.get(copy=False)``) and callers mutate what they get."""
    hint = _resolve_hint(hint)
    origin = get_origin(hint)
    if origin in (list, tuple):
        (inner,) = get_args(hint) or (Any,)
        ic = _make_coercer(inner)
        if origin is tuple:
            if ic is None:
                return tuple
            return lambda v: tuple(ic(x) for x in v)
        if ic is None:
            return list
        return lambda v: [ic(x) for x in v]
    if origin is dict:
        args = get_args(hint)
        vc = _make_coercer(args[1] if len(args) == 2 else Any)
        if vc is None:
            return dict
        return lambda v: {k: vc(x) for k, x in v.items()}
    if isinstance(hint, type):
        if dataclasses.is_dataclass(hint):
            return lambda v: from_dict(hint, v)
        if issubclass(hint, enum.Enum):
            return hint
        if issubclass(hint, datetime.datetime):
            return lambda v: _dec_time(v) if isinstance(v, str) else v
        if hint is float:
            return lambda v: float(v) if isinstance(v, int) else v
        if hint is dict or hint is list:
            return _copy_any  # bare container hints: deep, no alias
    if hint is Any or hint is object:
        # Untyped field: may hold anything, including containers.
        return _copy_any
    return None


def _copy_any(v):
    """Deep-copy plain JSON containers; scalars pass through. Bare
    dict/list/Any fields (e.g. CustomResource.spec) must honor the same
    no-alias invariant as typed ones — nested levels included, since
    the registry decodes from the store's live values."""
    tv = v.__class__
    if tv is dict:
        return {k: _copy_any(x) for k, x in v.items()}
    if tv is list:
        return [_copy_any(x) for x in v]
    return v


def _decoders(cls: type) -> dict[str, Any]:
    d = _DECODER_CACHE.get(cls)
    if d is None:
        hints = _hints(cls)
        d = {f.name: _make_coercer(hints.get(f.name, Any))
             for f in dataclasses.fields(cls)}
        _DECODER_CACHE[cls] = d
    return d


#: Per-dataclass exec-compiled decode functions (the reference gets the
#: same effect from generated codecs). None = class not compilable
#: (frozen/slots/__post_init__/required fields) -> generic path.
_COMPILED_DECODE: dict[type, Any] = {}
_MISS = object()


def _compile_decode(cls: type):
    """Build a specialized ``dict -> cls`` decoder.

    Bypasses ``cls(**kwargs)`` (keyword parsing + a generated __init__
    that re-assigns every field) by writing defaults straight into a
    ``__new__``-made instance's ``__dict__`` and overwriting with
    dispatched coercions. Only for plain dataclasses — anything with
    ``__post_init__``, ``__slots__``, frozen semantics, or required
    (default-less) fields keeps the generic path, whose behavior
    (e.g. TypeError on a missing required field) must not change."""
    if (getattr(cls, "__post_init__", None) is not None
            or any("__slots__" in k.__dict__ for k in cls.__mro__)
            or cls.__dataclass_params__.frozen):  # type: ignore[attr-defined]
        return None
    flds = dataclasses.fields(cls)
    ns: dict[str, Any] = {"__new": object.__new__, "__cls": cls,
                          "__disp": _decoders(cls), "__MISS": _MISS}
    lines = ["def __decode(data):",
             "    obj = __new(__cls)",
             "    d = obj.__dict__"]
    for i, f in enumerate(flds):
        if f.default is not dataclasses.MISSING:
            ns[f"__c{i}"] = f.default
            lines.append(f"    d[{f.name!r}] = __c{i}")
        elif f.default_factory is not dataclasses.MISSING:
            if f.default_factory is list:
                lines.append(f"    d[{f.name!r}] = []")
            elif f.default_factory is dict:
                lines.append(f"    d[{f.name!r}] = {{}}")
            else:
                ns[f"__f{i}"] = f.default_factory
                lines.append(f"    d[{f.name!r}] = __f{i}()")
        else:
            return None  # required field: keep generic error behavior
    lines += [
        "    extra = None",
        "    for k, v in data.items():",
        "        c = __disp.get(k, __MISS)",
        "        if c is __MISS:",
        "            if extra is None:",
        "                extra = {}",
        "            extra[k] = v",
        "        elif c is None or v is None:",
        "            d[k] = v",
        "        else:",
        "            d[k] = c(v)",
        "    if extra is not None:",
        "        d['__extra__'] = extra",
        "    return obj",
    ]
    exec("\n".join(lines), ns)  # noqa: S102 — codegen over our own fields
    return ns["__decode"]


def from_dict(cls: Type[T], data: dict) -> T:
    """Build dataclass ``cls`` from a plain dict, preserving unknown keys."""
    if data is None:
        return None  # type: ignore[return-value]
    try:
        fn = _COMPILED_DECODE[cls]
    except KeyError:
        if not dataclasses.is_dataclass(cls):
            return data  # type: ignore[return-value]
        fn = _COMPILED_DECODE[cls] = _compile_decode(cls)
    if fn is not None:
        return fn(data)
    decoders = _decoders(cls)
    kwargs: dict[str, Any] = {}
    extra: dict[str, Any] = {}
    for k, v in data.items():
        if k in decoders:
            c = decoders[k]
            kwargs[k] = v if c is None or v is None else c(v)
        else:
            extra[k] = v
    obj = cls(**kwargs)  # type: ignore[call-arg]
    if extra:
        object.__setattr__(obj, "__extra__", extra)
    return obj


def deepcopy(obj: T) -> T:
    """Deep-copy via the codec — mirrors generated DeepCopy in the reference."""
    if obj is None:
        return None  # type: ignore[return-value]
    return from_dict(type(obj), to_dict(obj))


def encode(obj: Any) -> bytes:
    return json.dumps(to_dict(obj), separators=(",", ":"), sort_keys=True).encode()


class Scheme:
    """(api_version, kind) <-> class registry with defaulting.

    Reference analog: ``runtime.Scheme`` type registration +
    ``scheme.Default(obj)`` (``pkg/apis/core/v1/defaults.go``).
    """

    def __init__(self) -> None:
        self._by_gvk: dict[tuple[str, str], type] = {}
        self._by_cls: dict[type, tuple[str, str]] = {}
        self._defaulters: dict[type, list] = {}
        #: (api_version, kind) -> (to_hub, from_hub) dict->dict wire
        #: transforms for served-but-not-stored versions (see
        #: api/versioning.py). Scoped to the scheme, like class
        #: registration — two registries must not share CRD versions.
        self._conversions: dict[tuple[str, str], tuple] = {}

    # -- version conversion (api/versioning.py machinery) -----------------

    def register_conversion(self, api_version: str, kind: str,
                            to_hub_fn, from_hub_fn) -> None:
        self._conversions[(api_version, kind)] = (to_hub_fn, from_hub_fn)

    def unregister_conversion(self, api_version: str, kind: str) -> None:
        self._conversions.pop((api_version, kind), None)

    def convertible(self, api_version: str, kind: str) -> bool:
        return (api_version, kind) in self._conversions

    def conversions_for_kind(self, kind: str) -> list[str]:
        """Registered external api_versions for ``kind``."""
        return [av for av, k in self._conversions if k == kind]

    def to_hub(self, api_version: str, kind: str, data: dict) -> dict:
        return self._conversions[(api_version, kind)][0](data)

    def from_hub(self, api_version: str, kind: str, data: dict) -> dict:
        return self._conversions[(api_version, kind)][1](data)

    def register(self, api_version: str, kind: str, cls: type) -> type:
        self._by_gvk[(api_version, kind)] = cls
        self._by_cls[cls] = (api_version, kind)
        return cls

    def unregister(self, api_version: str, kind: str) -> None:
        """Remove a dynamically-registered type (CRD deletion) so dead
        classes do not accumulate in a process-global scheme."""
        cls = self._by_gvk.pop((api_version, kind), None)
        if cls is not None:
            self._by_cls.pop(cls, None)
        self._defaulters.pop(cls, None)

    def add_defaulter(self, cls: type, fn) -> None:
        self._defaulters.setdefault(cls, []).append(fn)

    def default(self, obj: Any) -> Any:
        for fn in self._defaulters.get(type(obj), ()):  # pragma: no branch
            fn(obj)
        return obj

    def gvk_for(self, obj_or_cls: Any) -> tuple[str, str]:
        cls = obj_or_cls if isinstance(obj_or_cls, type) else type(obj_or_cls)
        try:
            return self._by_cls[cls]
        except KeyError:
            raise KeyError(f"type {cls.__name__} not registered in scheme") from None

    def class_for(self, api_version: str, kind: str) -> type:
        try:
            return self._by_gvk[(api_version, kind)]
        except KeyError:
            raise KeyError(f"no type registered for {api_version}/{kind}") from None

    def decode(self, data: bytes | str | dict) -> Any:
        """Decode JSON/dict into the registered type named by its TypeMeta."""
        if isinstance(data, (bytes, str)):
            data = json.loads(data)
        api_version = data.get("api_version") or data.get("apiVersion") or ""
        kind = data.get("kind") or ""
        cls = self.class_for(api_version, kind)
        obj = from_dict(cls, data)
        # Stamp TypeMeta so round-trips are stable.
        if hasattr(obj, "api_version"):
            obj.api_version = api_version
            obj.kind = kind
        return self.default(obj)

    def encode(self, obj: Any) -> bytes:
        d = to_dict(obj)
        gvk = self._by_cls.get(type(obj))
        if gvk:
            d["api_version"], d["kind"] = gvk
        return json.dumps(d, separators=(",", ":"), sort_keys=True).encode()


#: Process-global scheme all builtin types register into (the reference's
#: ``pkg/api.Scheme`` / ``legacyscheme.Scheme`` equivalent).
DEFAULT_SCHEME = Scheme()
