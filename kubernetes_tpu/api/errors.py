"""API error taxonomy.

Mirrors the reference's structured StatusError machinery
(``staging/src/k8s.io/apimachinery/pkg/api/errors``) so every layer —
registry, HTTP server, client — speaks one error language and HTTP
status codes round-trip losslessly through the REST boundary.
"""
from __future__ import annotations

from typing import Any, Optional


class StatusError(Exception):
    """Base error carrying an HTTP code + machine-readable reason."""

    code: int = 500
    reason: str = "InternalError"

    def __init__(self, message: str = "", *, details: Optional[dict] = None):
        super().__init__(message or self.reason)
        self.message = message or self.reason
        self.details = details or {}

    def to_dict(self) -> dict[str, Any]:
        return {
            "kind": "Status",
            "status": "Failure",
            "code": self.code,
            "reason": self.reason,
            "message": self.message,
            "details": self.details,
        }

    @staticmethod
    def from_dict(d: dict) -> "StatusError":
        cls = _BY_REASON.get(d.get("reason", ""), StatusError)
        err = cls(d.get("message", ""), details=d.get("details") or {})
        err.code = d.get("code", cls.code)
        return err


class NotFoundError(StatusError):
    code = 404
    reason = "NotFound"


class AlreadyExistsError(StatusError):
    code = 409
    reason = "AlreadyExists"


class ConflictError(StatusError):
    """Optimistic-concurrency failure (stale resource_version)."""

    code = 409
    reason = "Conflict"


class InvalidError(StatusError):
    code = 422
    reason = "Invalid"


class BadRequestError(StatusError):
    code = 400
    reason = "BadRequest"


class ForbiddenError(StatusError):
    code = 403
    reason = "Forbidden"


class UnauthorizedError(StatusError):
    code = 401
    reason = "Unauthorized"


class TimeoutError_(StatusError):
    code = 504
    reason = "Timeout"


class TooManyRequestsError(StatusError):
    code = 429
    reason = "TooManyRequests"


class GoneError(StatusError):
    """Watch from a compacted revision (etcd3 'required revision has been compacted')."""

    code = 410
    reason = "Expired"


class MethodNotAllowedError(StatusError):
    code = 405
    reason = "MethodNotAllowed"


class UnsupportedMediaTypeError(StatusError):
    """The request body's Content-Type is not one this server decodes
    (reference: 415 from the negotiated-serializer stack) — distinct
    from 400 so a codec MISMATCH (compact body at a JSON-only server)
    is diagnosable apart from a garbled body."""
    code = 415
    reason = "UnsupportedMediaType"


class ServiceUnavailableError(StatusError):
    code = 503
    reason = "ServiceUnavailable"


_BY_REASON: dict[str, type[StatusError]] = {
    c.reason: c
    for c in [
        NotFoundError, AlreadyExistsError, ConflictError, InvalidError,
        BadRequestError, ForbiddenError, UnauthorizedError, TimeoutError_,
        TooManyRequestsError, GoneError, MethodNotAllowedError,
        UnsupportedMediaTypeError, ServiceUnavailableError, StatusError,
    ]
}


def is_not_found(e: Exception) -> bool:
    return isinstance(e, NotFoundError)


def is_conflict(e: Exception) -> bool:
    return isinstance(e, ConflictError)


def is_already_exists(e: Exception) -> bool:
    return isinstance(e, AlreadyExistsError)
