"""Inference serving API — autoscaled model serving for user traffic.

KServe/InferenceService-analog kind (reference: serving.kserve.io
InferenceService fused with autoscaling/v1's min/max-replica contract;
PAPERS.md "Evaluating Kubernetes Performance for GenAI Inference" is
the evaluation template this subsystem is measured by):

- :class:`InferenceService` (namespaced): one served model — the model
  ref, the per-replica chip/slice demand, the replica window the
  autoscaler moves inside, and the latency SLO the loadgen grades
  against. The inference controller (``controllers/inference.py``)
  reconciles it into a headless Service (per-replica DNS + Endpoints
  discovery, ``net/dns.py``) plus a Deployment of model-server pods
  (``workloads/model_server.py``), and an HPA-analog loop scales the
  Deployment on ``ClusterMonitor.latest()`` rollups.

Everything is gated behind ``InferenceAutoscaling`` (alpha, default
off): with the gate off the controller and the admission defaulter are
inert and the tree's behavior is byte-identical.
"""
from __future__ import annotations

import datetime
import math
from dataclasses import dataclass, field
from typing import Optional

from .meta import TypedObject
from .scheme import DEFAULT_SCHEME
from .validation import ErrorList, validate_object_meta

SERVING_V1 = "serving/v1"

#: Pod label joining an InferenceService to its replicas (the selector
#: the Deployment/Service/endpoint router all key on). Also the marker
#: the scheduler's gated topology-aware scoring looks for.
SERVICE_LABEL = "serving.tpu/service"

#: Label on warm-pool image-prepull pods (controller-owned, short-lived;
#: they pull the model image on candidate nodes ahead of the first
#: scale-up so time-to-first-ready excludes the cold pull).
PREPULL_LABEL = "serving.tpu/prepull"

#: Annotation on the Deployment the controller manages, recording the
#: owning InferenceService (belt + suspenders beside the owner ref).
MANAGED_ANNOTATION = "serving.tpu/managed-by"


@dataclass
class InferenceServiceSpec:
    #: Model reference the server loads — a name for the stub server,
    #: an artifact path (``file://...``) in real deployments.
    model: str = ""
    #: Container image for the model-server pods ("" = the built-in
    #: host environment, the process runtime's default). An artifact
    #: ref here is what the warm pool pre-pulls.
    image: str = ""
    #: Replica window the autoscaler moves within.
    min_replicas: int = 0      # defaulted to 1 by admission (gated)
    max_replicas: int = 0      # defaulted to max(min, 1)
    #: Per-replica TPU demand: chip count, or a contiguous slice shape
    #: (shape wins when both are set; chips then defaults to its
    #: volume). 0/empty = a CPU-only server.
    chips_per_replica: int = 0
    slice_shape: list[int] = field(default_factory=list)
    #: Per-replica CPU request (scheduling weight for the server pod).
    cpu_per_replica: float = 0.5
    #: Serving port (defaulted to 8100 by admission).
    port: int = 0
    #: Request-latency SLO the loadgen grades attainment against (ms).
    slo_target_ms: float = 0.0  # defaulted to 2000
    #: Rated per-replica decode throughput (tokens/s). The stub model
    #: server simulates exactly this speed; the autoscaler uses it to
    #: turn observed tokens/s into a utilization signal.
    rated_tokens_per_sec: float = 0.0  # defaulted to 256
    #: Busy-fraction target the autoscaler holds replicas at (0..1,
    #: defaulted to 0.65): scale up above it, down below it.
    target_utilization: float = 0.0
    #: Scale-down stabilization window (seconds): replicas only shrink
    #: to the HIGHEST recommendation seen inside the window (reference:
    #: --horizontal-pod-autoscaler-downscale-stabilization).
    scale_down_stabilization_seconds: float = 30.0
    #: Per-decision replica-step rate limits (0 = defaulted: up 4/down 1).
    scale_up_max_step: int = 0
    scale_down_max_step: int = 0
    #: Warm pool: pre-pull the model image on up to this many candidate
    #: nodes beyond those already serving (0 = min(max-min, 2)).
    warm_pool_nodes: int = 0


@dataclass
class InferenceServiceStatus:
    #: Deployment-side counts mirrored for ``ktl get inferenceservices``.
    replicas: int = 0
    ready_replicas: int = 0
    #: The autoscaler's current target.
    desired_replicas: int = 0
    last_scale_time: Optional[datetime.datetime] = None
    last_scale_reason: str = ""
    #: Observed aggregate decode throughput and mean busy fraction over
    #: the service's replicas, from the last autoscaler pass.
    tokens_per_sec: float = 0.0
    utilization: float = 0.0
    #: Age of the ClusterMonitor snapshot the last decision used
    #: (-1 = no decision yet). A stale feed REFUSES to act — this field
    #: is how operators see that happening.
    snapshot_age_seconds: float = -1.0
    #: Nodes whose image store holds this service's artifact image
    #: (warm pool): recorded when a prepull pod succeeds, BEFORE the
    #: pod is reaped — the durable record rides the WAL, so a reaped
    #: prepull cannot be re-created on an already-warm node after a
    #: controller restart (API-object-as-checkpoint, as ever).
    warm_nodes: list[str] = field(default_factory=list)


@dataclass
class InferenceService(TypedObject):
    spec: InferenceServiceSpec = field(default_factory=InferenceServiceSpec)
    status: InferenceServiceStatus = field(
        default_factory=InferenceServiceStatus)


def replica_chips(spec: InferenceServiceSpec) -> int:
    """Chips one replica claims: the slice shape's volume when shaped,
    else the flat count."""
    if spec.slice_shape:
        return math.prod(int(d) for d in spec.slice_shape)
    return spec.chips_per_replica


#: The documented defaults for spec fields left 0 — ONE definition
#: shared by the admission defaulter (stamps them on gated creates)
#: and :func:`effective_spec` (resolves them at READ time), so an
#: object created while the gate was off — or updated to zero a field
#: — can never drive the controller with a port-0 probe or a zero
#: utilization target.
DEFAULT_PORT = 8100
DEFAULT_SLO_MS = 2000.0
DEFAULT_RATED_TPS = 256.0
DEFAULT_TARGET_UTILIZATION = 0.65


def effective_spec(spec: InferenceServiceSpec) -> InferenceServiceSpec:
    """A copy with the serving defaults applied to unset (0) fields —
    what the controller/autoscaler actually operate on."""
    from dataclasses import replace
    return replace(
        spec,
        min_replicas=spec.min_replicas if spec.min_replicas > 0 else 1,
        max_replicas=(spec.max_replicas if spec.max_replicas > 0
                      else max(spec.min_replicas, 1)),
        chips_per_replica=(replica_chips(spec) if spec.slice_shape
                           else spec.chips_per_replica),
        port=spec.port or DEFAULT_PORT,
        slo_target_ms=spec.slo_target_ms or DEFAULT_SLO_MS,
        rated_tokens_per_sec=(spec.rated_tokens_per_sec
                              or DEFAULT_RATED_TPS),
        target_utilization=(spec.target_utilization
                            or DEFAULT_TARGET_UTILIZATION))


def validate_inferenceservice(svc: InferenceService,
                              is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(svc.metadata, errs)
    s = svc.spec
    if not s.model:
        errs.add("spec.model", "required (the model the server loads)")
    if s.min_replicas < 0:
        errs.add("spec.min_replicas", "must be >= 0")
    if s.max_replicas and s.max_replicas < max(s.min_replicas, 1):
        errs.add("spec.max_replicas",
                 f"must be >= max(min_replicas, 1) (= "
                 f"{max(s.min_replicas, 1)})")
    if s.chips_per_replica < 0:
        errs.add("spec.chips_per_replica", "must be >= 0")
    for d in s.slice_shape:
        if int(d) <= 0:
            errs.add("spec.slice_shape", f"dimension {d!r} must be > 0")
    if s.slice_shape and s.chips_per_replica and \
            replica_chips(s) != s.chips_per_replica:
        errs.add("spec.chips_per_replica",
                 f"contradicts slice_shape volume {replica_chips(s)} "
                 f"(set one; the shape wins when both are given)")
    if s.cpu_per_replica < 0:
        errs.add("spec.cpu_per_replica", "must be >= 0")
    if s.port < 0 or s.port > 65535:
        errs.add("spec.port", "must be a port number")
    for fname, v in (("slo_target_ms", s.slo_target_ms),
                     ("rated_tokens_per_sec", s.rated_tokens_per_sec)):
        if not math.isfinite(v) or v < 0:
            errs.add(f"spec.{fname}", "must be finite and >= 0")
    if not 0.0 <= s.target_utilization <= 1.0 \
            or not math.isfinite(s.target_utilization):
        errs.add("spec.target_utilization", "must be in [0, 1]")
    if not math.isfinite(s.scale_down_stabilization_seconds) \
            or s.scale_down_stabilization_seconds < 0:
        errs.add("spec.scale_down_stabilization_seconds",
                 "must be finite and >= 0")
    if s.scale_up_max_step < 0 or s.scale_down_max_step < 0:
        errs.add("spec.scale_up_max_step", "steps must be >= 0")
    if s.warm_pool_nodes < 0:
        errs.add("spec.warm_pool_nodes", "must be >= 0")
    errs.raise_if_any("InferenceService", svc.metadata.name)


def validate_inferenceservice_update(new: InferenceService,
                                     old: InferenceService) -> None:
    validate_inferenceservice(new, is_create=False)
    if (new.spec.chips_per_replica != old.spec.chips_per_replica
            or new.spec.slice_shape != old.spec.slice_shape):
        # Changing per-replica chip geometry under live replicas would
        # mix incompatible server shapes behind one Service; require a
        # delete/recreate (KServe treats the predictor shape the same
        # way — a new revision, not an in-place mutation).
        from .errors import InvalidError
        raise InvalidError(
            f"InferenceService {new.metadata.name!r}: per-replica chip "
            f"demand (spec.chips_per_replica / spec.slice_shape) is "
            f"immutable (delete and recreate to reshape)")


DEFAULT_SCHEME.register(SERVING_V1, "InferenceService", InferenceService)
