"""RBAC API types — Role/ClusterRole + bindings.

Reference: ``staging/src/k8s.io/api/rbac/v1/types.go`` and the RBAC
authorizer in ``plugin/pkg/auth/authorizer/rbac``. Same shape, reduced
to the fields the authorizer consumes: rules are (verbs, resources,
resource_names); subjects are users/groups (service accounts fold into
users as ``system:serviceaccount:<ns>:<name>``, the reference's own
encoding).
"""
from __future__ import annotations

from dataclasses import dataclass, field

from .meta import TypedObject

#: Wildcard matching anything (verbs, resources, names).
ALL = "*"

#: Implicit group carried by every authenticated request (reference:
#: ``user.AllAuthenticated``).
GROUP_AUTHENTICATED = "system:authenticated"
#: Superuser group — bypasses authorization entirely (reference:
#: ``authorizer.PrivilegedGroup`` / system:masters).
GROUP_MASTERS = "system:masters"


@dataclass
class PolicyRule:
    verbs: list[str] = field(default_factory=list)
    resources: list[str] = field(default_factory=list)
    #: Restrict to specific object names ([] = any).
    resource_names: list[str] = field(default_factory=list)

    def matches(self, verb: str, resource: str, name: str) -> bool:
        if ALL not in self.verbs and verb not in self.verbs:
            return False
        if ALL not in self.resources and resource not in self.resources:
            return False
        if self.resource_names and ALL not in self.resource_names \
                and name not in self.resource_names:
            return False
        return True


@dataclass
class Subject:
    kind: str = "User"  # User | Group
    name: str = ""


@dataclass
class RoleRef:
    kind: str = "Role"  # Role | ClusterRole
    name: str = ""


@dataclass
class Role(TypedObject):
    """Namespaced permission set."""
    rules: list[PolicyRule] = field(default_factory=list)


@dataclass
class ClusterRole(TypedObject):
    """Cluster-wide permission set."""
    rules: list[PolicyRule] = field(default_factory=list)


@dataclass
class RoleBinding(TypedObject):
    """Grants a Role (or ClusterRole) within the binding's namespace."""
    role_ref: RoleRef = field(default_factory=RoleRef)
    subjects: list[Subject] = field(default_factory=list)


@dataclass
class ClusterRoleBinding(TypedObject):
    """Grants a ClusterRole across all namespaces."""
    role_ref: RoleRef = field(default_factory=RoleRef)
    subjects: list[Subject] = field(default_factory=list)


RBAC_V1 = "rbac/v1"

from .scheme import DEFAULT_SCHEME  # noqa: E402  (registration, like workloads.py)

for _kind, _cls in [("Role", Role), ("ClusterRole", ClusterRole),
                    ("RoleBinding", RoleBinding),
                    ("ClusterRoleBinding", ClusterRoleBinding)]:
    DEFAULT_SCHEME.register(RBAC_V1, _kind, _cls)


def subject_matches(subject: Subject, user: str, groups: set[str]) -> bool:
    if subject.kind == "User":
        return subject.name == user or subject.name == ALL
    if subject.kind == "Group":
        return subject.name in groups
    return False
