"""API version evolution — external versions + conversion via the hub.

Reference: ``pkg/apis/`` keeps internal ("hub") types with per-version
external types, conversion functions, and defaulting; the apiserver
decodes any served version to the hub, stores ONE version, and encodes
responses back to the version the client asked for — that is what
makes rolling upgrades and wire-compat evolution possible.

Redesign for the dataclass scheme: conversions are registered at the
WIRE level (dict -> dict), which serves both typed built-ins and
dynamically-installed CRDs through one mechanism, and preserves
unknown fields by construction. The storage version is always the
hub's ``api_version``; serving an older version costs one dict
transform per request on that version only.

Proof instance: ``core/v1beta1 PodGroup`` — the gang API's previous
shape (``members`` count + ``topology`` string) served alongside the
v1 hub (``min_member`` + ``slice_shape`` list), stored as v1.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from .meta import TypedObject
from .scheme import DEFAULT_SCHEME

# Conversion storage lives ON the Scheme (scoped like class
# registration — two registries must not share CRD versions); these
# module-level helpers operate on DEFAULT_SCHEME, where the builtin
# versions below register.

def register_conversion(api_version: str, kind: str,
                        to_hub: Callable[[dict], dict],
                        from_hub: Callable[[dict], dict]) -> None:
    DEFAULT_SCHEME.register_conversion(api_version, kind, to_hub, from_hub)


def unregister_conversion(api_version: str, kind: str) -> None:
    DEFAULT_SCHEME.unregister_conversion(api_version, kind)


def convertible(api_version: str, kind: str) -> bool:
    return DEFAULT_SCHEME.convertible(api_version, kind)


def to_hub(api_version: str, kind: str, data: dict) -> dict:
    return DEFAULT_SCHEME.to_hub(api_version, kind, data)


def from_hub(api_version: str, kind: str, data: dict) -> dict:
    return DEFAULT_SCHEME.from_hub(api_version, kind, data)


def identity_conversion(external_av: str, hub_av: str):
    """(to_hub, from_hub) that only rewrite api_version — the CRD
    multi-version case with conversion strategy None (same schema,
    several served versions)."""

    def up(d: dict) -> dict:
        return {**d, "api_version": hub_av}

    def down(d: dict) -> dict:
        return {**d, "api_version": external_av}

    return up, down


# ---------------------------------------------------------------------------
# core/v1beta1 PodGroup — the served-but-not-stored gang API version.
# ---------------------------------------------------------------------------

CORE_V1BETA1 = "core/v1beta1"


@dataclass
class PodGroupV1Beta1Spec:
    #: v1 renamed this to ``min_member``.
    members: int = 0
    #: v1 structured this into ``slice_shape: list[int]``.
    topology: str = ""
    priority: Optional[int] = None
    schedule_timeout_seconds: int = 0


@dataclass
class PodGroupV1Beta1(TypedObject):
    """The beta gang group: same semantics, older field shapes. Exists
    so old clients keep working against a new server (decode +
    default + convert up) and new objects stay readable by old
    clients (convert down)."""

    spec: PodGroupV1Beta1Spec = field(default_factory=PodGroupV1Beta1Spec)
    #: Status shape did not change across versions.
    status: dict = field(default_factory=dict)


DEFAULT_SCHEME.register(CORE_V1BETA1, "PodGroup", PodGroupV1Beta1)


def _default_podgroup_v1beta1(obj: PodGroupV1Beta1) -> None:
    if obj.spec.members <= 0:
        obj.spec.members = 1


DEFAULT_SCHEME.add_defaulter(PodGroupV1Beta1, _default_podgroup_v1beta1)


def _topology_to_shape(topology: str) -> list[int]:
    if not topology:
        return []
    try:
        return [int(x) for x in topology.lower().split("x")]
    except ValueError:
        from . import errors
        raise errors.InvalidError(
            f"spec.topology: must look like '2x2x2', got {topology!r}"
        ) from None


def _shape_to_topology(shape: list) -> str:
    return "x".join(str(int(d)) for d in shape) if shape else ""


def _podgroup_up(d: dict) -> dict:
    """v1beta1 wire dict -> v1 wire dict (the hub)."""
    out = {**d, "api_version": "core/v1"}
    spec = dict(d.get("spec") or {})
    members = spec.pop("members", 0) or 1  # beta defaulting
    topology = spec.pop("topology", "")
    spec["min_member"] = members
    shape = _topology_to_shape(topology)
    if shape:
        spec["slice_shape"] = shape
    out["spec"] = spec
    return out


def _podgroup_down(d: dict) -> dict:
    """v1 wire dict -> v1beta1 wire dict."""
    out = {**d, "api_version": CORE_V1BETA1}
    spec = dict(d.get("spec") or {})
    spec["members"] = spec.pop("min_member", 1)
    shape = spec.pop("slice_shape", [])
    topology = _shape_to_topology(shape)
    if topology:
        spec["topology"] = topology
    out["spec"] = spec
    return out


register_conversion(CORE_V1BETA1, "PodGroup", _podgroup_up, _podgroup_down)
