"""Multi-tenant job queueing API — fair-share admission for gang jobs.

Kueue-analog kinds (reference: kueue.x-k8s.io ClusterQueue/LocalQueue,
arXiv:2510.01256 section on unified quota scheduling):

- :class:`ClusterQueue` (cluster-scoped): a tenant's resource quota —
  nominal per-resource amounts plus an optional borrowing *cohort*.
  Queues in one cohort lend idle nominal quota to each other; a
  borrower is preempted back under its nominal share when the lender's
  own demand returns (gang-aware reclaim, queueing/fairshare.py).
- :class:`LocalQueue` (namespaced): the namespace-side handle binding
  workloads in that namespace to a ClusterQueue. ``PodGroup.spec.queue``
  names a LocalQueue in the group's namespace.

Admission state lives on the PodGroup (``status.admitted`` — the
API-object-as-checkpoint move): it rides the MVCC WAL, so a restarted
QueueController rebuilds usage from listed groups and can never
double-admit after replay.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .meta import TypedObject
from .scheme import DEFAULT_SCHEME
from .validation import ErrorList, validate_object_meta, validate_quota_map

QUEUEING_V1 = "queueing/v1"

#: PodGroup/LocalQueue annotation: projected gang runtime in seconds,
#: consumed by the backfill pass (EASY-style shadow-time check). The
#: gang Job controller stamps it from ``spec.active_deadline_seconds``.
RUNTIME_ANNOTATION = "queueing.tpu/runtime-seconds"

#: LocalQueue annotation marking it the namespace default: PodGroups
#: created with ``spec.queue == ""`` are admitted into it (apiserver
#: admission plugin, gated on JobQueueing).
DEFAULT_QUEUE_ANNOTATION = "queueing.tpu/default-queue"

#: PodGroupStatus.admission_mode values.
ADMISSION_NOMINAL = "Nominal"      # fit inside the queue's own quota
ADMISSION_BORROWED = "Borrowed"    # lent idle quota from the cohort
ADMISSION_BACKFILL = "Backfill"    # jumped the head-of-line blocker


@dataclass
class ClusterQueueSpec:
    #: Borrowing cohort: queues sharing a cohort name lend each other
    #: idle nominal quota ("" = no cohort, never borrows or lends).
    cohort: str = ""
    #: Nominal per-resource quota, e.g. {"google.com/tpu": 256,
    #: "cpu": 512, "memory": 2e12}. Admission charges gang demand
    #: against these.
    nominal_quota: dict[str, float] = field(default_factory=dict)
    #: Per-resource cap on quota borrowed beyond nominal; a resource
    #: absent here may borrow without limit (cohort headroom still
    #: bounds it). Ignored without a cohort.
    borrowing_limit: dict[str, float] = field(default_factory=dict)


@dataclass
class ClusterQueueStatus:
    #: Gangs waiting for admission / currently admitted via this queue.
    pending: int = 0
    admitted: int = 0
    #: Admitted per-resource usage (sum of admitted gang demand).
    usage: dict[str, float] = field(default_factory=dict)
    #: The part of ``usage`` above nominal (lent from the cohort).
    borrowed: dict[str, float] = field(default_factory=dict)
    #: Per-tenant breakdown: "namespace/localqueue" -> resource usage
    #: (``ktl describe clusterqueue`` renders usage vs quota from this).
    tenant_usage: dict[str, dict[str, float]] = field(default_factory=dict)
    #: Gangs of this queue currently mid-reclaim (graceful preemption
    #: signaled / checkpointing, or swept for eviction) — the ``ktl get
    #: clusterqueues`` RECLAIMING column.
    reclaiming: int = 0


@dataclass
class ClusterQueue(TypedObject):
    spec: ClusterQueueSpec = field(default_factory=ClusterQueueSpec)
    status: ClusterQueueStatus = field(default_factory=ClusterQueueStatus)


@dataclass
class LocalQueueSpec:
    #: Name of the ClusterQueue this namespace queue feeds into.
    cluster_queue: str = ""


@dataclass
class LocalQueueStatus:
    pending: int = 0
    admitted: int = 0


@dataclass
class LocalQueue(TypedObject):
    spec: LocalQueueSpec = field(default_factory=LocalQueueSpec)
    status: LocalQueueStatus = field(default_factory=LocalQueueStatus)


def validate_clusterqueue(cq: ClusterQueue, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(cq.metadata, errs)
    validate_quota_map("spec.nominal_quota", cq.spec.nominal_quota, errs)
    validate_quota_map("spec.borrowing_limit", cq.spec.borrowing_limit, errs)
    if cq.spec.borrowing_limit and not cq.spec.cohort:
        errs.add("spec.borrowing_limit",
                 "requires spec.cohort (borrowing happens within a cohort)")
    errs.raise_if_any("ClusterQueue", cq.metadata.name)


def validate_clusterqueue_update(new: ClusterQueue,
                                 old: ClusterQueue) -> None:
    validate_clusterqueue(new, is_create=False)


def validate_localqueue(lq: LocalQueue, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(lq.metadata, errs)
    if not lq.spec.cluster_queue:
        errs.add("spec.cluster_queue", "required")
    errs.raise_if_any("LocalQueue", lq.metadata.name)


def validate_localqueue_update(new: LocalQueue, old: LocalQueue) -> None:
    validate_localqueue(new, is_create=False)
    if new.spec.cluster_queue != old.spec.cluster_queue:
        # Rebinding a namespace to a different ClusterQueue would
        # silently move already-admitted usage between tenants'
        # accounts (Kueue marks the field immutable for the same
        # reason).
        from .errors import InvalidError
        raise InvalidError(
            f"LocalQueue {new.metadata.name!r}: spec.cluster_queue is "
            f"immutable (delete and recreate to rebind)")


DEFAULT_SCHEME.register(QUEUEING_V1, "ClusterQueue", ClusterQueue)
DEFAULT_SCHEME.register(QUEUEING_V1, "LocalQueue", LocalQueue)
