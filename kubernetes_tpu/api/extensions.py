"""CustomResourceDefinitions — user-defined API types.

Reference: ``staging/src/k8s.io/apiextensions-apiserver`` — a CRD
object registers a new REST resource; custom objects are schemaless
maps validated against optional OpenAPI-ish props. Redesign: the
apiserver's routes are already parameterized (/api/{group}/{version}/
{plural}), so installing a CRD is purely a registry-table operation —
no route surgery, no separate apiextensions server.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .errors import InvalidError
from .meta import TypedObject
from .scheme import DEFAULT_SCHEME, to_dict

EXTENSIONS_V1 = "apiextensions/v1"

SCOPE_NAMESPACED = "Namespaced"
SCOPE_CLUSTER = "Cluster"


@dataclass
class CRDNames:
    plural: str = ""
    singular: str = ""
    kind: str = ""
    short_names: list[str] = field(default_factory=list)


@dataclass
class SchemaProps:
    """Minimal OpenAPI v3 subset (reference: JSONSchemaProps): enough
    for type checks + required fields, recursively."""
    type: str = ""  # object | string | integer | number | boolean | array
    required: list[str] = field(default_factory=list)
    properties: dict[str, "SchemaProps"] = field(default_factory=dict)
    items: Optional["SchemaProps"] = None


@dataclass
class CRDSpec:
    group: str = ""
    #: STORAGE version (also served).
    version: str = "v1"
    #: Additional SERVED versions (conversion strategy None — same
    #: schema, api_version rewritten on the wire; reference:
    #: apiextensions served/storage flags).
    served_versions: list[str] = field(default_factory=list)
    scope: str = SCOPE_NAMESPACED
    names: CRDNames = field(default_factory=CRDNames)
    #: Validation applied to the custom object's top level (commonly a
    #: {"type": "object", "properties": {"spec": {...}}} schema).
    schema: Optional[SchemaProps] = None


@dataclass
class CRDCondition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""


@dataclass
class CRDStatus:
    conditions: list[CRDCondition] = field(default_factory=list)


@dataclass
class CustomResourceDefinition(TypedObject):
    spec: CRDSpec = field(default_factory=CRDSpec)
    status: CRDStatus = field(default_factory=CRDStatus)

    def api_version_str(self) -> str:
        return f"{self.spec.group}/{self.spec.version}"


@dataclass
class CustomResource(TypedObject):
    """Generic custom object: free-form spec/status dicts; any other
    top-level fields ride the scheme's unknown-key (__extra__)
    preservation. Each installed CRD gets its own subclass so the
    scheme's class<->gvk mapping stays one-to-one."""
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)


_TYPE_CHECKS = {
    "string": lambda v: isinstance(v, str),
    "integer": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "number": lambda v: isinstance(v, (int, float)) and not isinstance(v, bool),
    "boolean": lambda v: isinstance(v, bool),
    "object": lambda v: isinstance(v, dict),
    "array": lambda v: isinstance(v, list),
}


def validate_against_schema(value, schema: SchemaProps, path: str,
                            errs: list[str]) -> None:
    if schema.type and not _TYPE_CHECKS.get(schema.type, lambda v: True)(value):
        errs.append(f"{path}: expected {schema.type}, "
                    f"got {type(value).__name__}")
        return
    if isinstance(value, dict):
        for req in schema.required:
            if req not in value:
                errs.append(f"{path}.{req}: required")
        for key, sub in schema.properties.items():
            if key in value:
                validate_against_schema(value[key], sub, f"{path}.{key}", errs)
    if isinstance(value, list) and schema.items is not None:
        for i, item in enumerate(value):
            validate_against_schema(item, schema.items, f"{path}[{i}]", errs)


def make_cr_validator(crd: CustomResourceDefinition):
    """Create-validator closure for one CRD's custom objects."""
    schema = crd.spec.schema

    def validate(obj, is_create: bool = True) -> None:
        if schema is None:
            return
        data = to_dict(obj)
        data.pop("metadata", None)
        data.pop("api_version", None)
        data.pop("kind", None)
        errs: list[str] = []
        validate_against_schema(data, schema, crd.spec.names.kind, errs)
        if errs:
            raise InvalidError("; ".join(errs))

    return validate


def validate_crd(crd: CustomResourceDefinition, is_create: bool = True) -> None:
    errs = []
    names = crd.spec.names
    if not crd.spec.group or "/" in crd.spec.group:
        errs.append("spec.group: required, no slashes")
    if not names.plural or not names.plural.islower():
        errs.append("spec.names.plural: required lowercase")
    if not names.kind:
        errs.append("spec.names.kind: required")
    if crd.spec.scope not in (SCOPE_NAMESPACED, SCOPE_CLUSTER):
        errs.append(f"spec.scope: must be {SCOPE_NAMESPACED} or {SCOPE_CLUSTER}")
    if crd.metadata.name != f"{names.plural}.{crd.spec.group}":
        errs.append(f"metadata.name: must be "
                    f"'{names.plural}.{crd.spec.group}'")
    if errs:
        raise InvalidError("; ".join(errs))


def validate_crd_update(new: CustomResourceDefinition,
                        old: CustomResourceDefinition) -> None:
    """Identity fields are immutable (reference: CRD strategy): only the
    schema may change; the registry re-installs the validator."""
    validate_crd(new, is_create=False)
    frozen = []
    if new.spec.group != old.spec.group:
        frozen.append("spec.group")
    if new.spec.version != old.spec.version:
        frozen.append("spec.version")
    if new.spec.scope != old.spec.scope:
        frozen.append("spec.scope")
    if (new.spec.names.plural, new.spec.names.kind) != \
            (old.spec.names.plural, old.spec.names.kind):
        frozen.append("spec.names")
    if frozen:
        raise InvalidError(f"CRD {new.metadata.name!r}: immutable fields "
                           f"changed: {', '.join(frozen)}")


# ---------------------------------------------------------------------------
# API aggregation (reference: kube-aggregator APIService)
# ---------------------------------------------------------------------------

AGGREGATION_V1 = "apiregistration/v1"


@dataclass
class APIServiceSpec:
    """Delegate one group/version to an external apiserver (reference:
    ``staging/src/k8s.io/kube-aggregator`` APIService). The target is a
    direct URL (dev posture) or an in-cluster Service reference
    resolved through its Endpoints."""
    group: str = ""
    version: str = "v1"
    #: Direct base URL of the extension apiserver (e.g.
    #: "http://127.0.0.1:9443"); takes precedence over service_*.
    url: str = ""
    service_namespace: str = ""
    service_name: str = ""
    service_port: int = 0


@dataclass
class APIServiceCondition:
    type: str = ""       # Available
    status: str = ""
    reason: str = ""
    message: str = ""


@dataclass
class APIServiceStatus:
    conditions: list[APIServiceCondition] = field(default_factory=list)


@dataclass
class APIService(TypedObject):
    spec: APIServiceSpec = field(default_factory=APIServiceSpec)
    status: APIServiceStatus = field(default_factory=APIServiceStatus)


def validate_apiservice(svc: APIService, is_create: bool = True) -> None:
    errs = []
    if not svc.spec.group or "/" in svc.spec.group:
        errs.append("spec.group: required, no slashes")
    if not svc.spec.version:
        errs.append("spec.version: required")
    if not svc.spec.url and not (svc.spec.service_namespace
                                 and svc.spec.service_name
                                 and svc.spec.service_port):
        errs.append("spec: either url or service_{namespace,name,port} "
                    "is required")
    if svc.metadata.name != f"{svc.spec.version}.{svc.spec.group}":
        errs.append(f"metadata.name: must be "
                    f"'{svc.spec.version}.{svc.spec.group}'")
    if errs:
        raise InvalidError("; ".join(errs))


def validate_apiservice_update(new: APIService, old: APIService) -> None:
    """Updates must hold every create-time invariant (group shape,
    name binding, target presence)."""
    validate_apiservice(new, is_create=False)


DEFAULT_SCHEME.register(EXTENSIONS_V1, "CustomResourceDefinition",
                        CustomResourceDefinition)
DEFAULT_SCHEME.register(AGGREGATION_V1, "APIService", APIService)


# ---------------------------------------------------------------------------
# Admission webhooks — out-of-tree policy intercepting API writes.
# Reference: staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook/
# {mutating,validating}/admission.go (Admit at mutating/admission.go:199)
# and the admissionregistration.k8s.io API group. Wire shape preserved:
# the server POSTs an AdmissionReview{request} and the hook answers
# AdmissionReview{response{uid, allowed, patch?, status?}}; mutating
# patches are RFC 6902 JSONPatch (base64 on the wire, like the
# reference's patchType: JSONPatch).
# ---------------------------------------------------------------------------

ADMISSION_V1 = "admissionregistration/v1"

FAILURE_POLICY_FAIL = "Fail"
FAILURE_POLICY_IGNORE = "Ignore"


@dataclass
class WebhookRule:
    """Which (operation, resource) pairs a webhook intercepts.

    Reference: admissionregistration RuleWithOperations. Plural-based
    (the framework's resources are flat plurals); ``"*"`` matches all.
    """

    operations: list[str] = field(default_factory=lambda: ["*"])
    resources: list[str] = field(default_factory=list)


@dataclass
class Webhook:
    name: str = ""
    #: Endpoint URL (reference also supports service refs; here the
    #: dataplane has no in-cluster HTTPS services, so URL only).
    #: https:// is the contract (the reference mandates it — review
    #: bodies carry full objects, Secrets included); http:// is
    #: admitted only for loopback hosts (test/dev), anything else is
    #: rejected at config validation.
    url: str = ""
    #: PEM CA bundle verifying the hook's serving cert (reference
    #: clientConfig.caBundle); empty = system trust store.
    ca_bundle: str = ""
    rules: list[WebhookRule] = field(default_factory=list)
    #: Fail (reject the API request when the hook is unreachable) or
    #: Ignore (admit as if allowed) — admission.go failurePolicy.
    failure_policy: str = FAILURE_POLICY_FAIL
    timeout_seconds: float = 10.0


@dataclass
class MutatingWebhookConfiguration(TypedObject):
    webhooks: list[Webhook] = field(default_factory=list)


@dataclass
class ValidatingWebhookConfiguration(TypedObject):
    webhooks: list[Webhook] = field(default_factory=list)


def validate_webhook_configuration(cfg, is_create: bool = True) -> None:
    """URL policy for admission webhooks: https required (review
    bodies carry whole objects — Secret data included on CREATE), with
    a loopback-only http exception for test/dev hooks, matching the
    spirit of the reference's mandatory caBundle+https clientConfig."""
    from urllib.parse import urlparse
    errs = []
    for i, hook in enumerate(cfg.webhooks):
        if not hook.name:
            errs.append(f"webhooks[{i}].name: required")
        parsed = urlparse(hook.url)
        if parsed.scheme == "https":
            pass
        elif parsed.scheme == "http" and parsed.hostname in (
                "127.0.0.1", "localhost", "::1"):
            pass
        else:
            errs.append(
                f"webhooks[{i}].url: must be https:// "
                f"(http:// only for loopback hosts), got {hook.url!r}")
    if errs:
        raise InvalidError("; ".join(errs))


def validate_webhook_configuration_update(new, old) -> None:
    validate_webhook_configuration(new, is_create=False)


DEFAULT_SCHEME.register(ADMISSION_V1, "MutatingWebhookConfiguration",
                        MutatingWebhookConfiguration)
DEFAULT_SCHEME.register(ADMISSION_V1, "ValidatingWebhookConfiguration",
                        ValidatingWebhookConfiguration)
