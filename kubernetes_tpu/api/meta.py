"""Object metadata — the `metav1` equivalent.

Reference: ``staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/types.go``
(ObjectMeta/TypeMeta/OwnerReference/ListMeta). Every persisted object
embeds :class:`ObjectMeta`; every list carries :class:`ListMeta` with the
store revision so informers can resume watches exactly where the LIST
left off (the resourceVersion contract, SURVEY.md section 7 hard part 2).
"""
from __future__ import annotations

import dataclasses
import datetime
import uuid
from dataclasses import dataclass, field
from typing import Optional


def now() -> datetime.datetime:
    return datetime.datetime.utcnow()


_STAMP_FMT = "%Y-%m-%dT%H:%M:%S.%fZ"


def stamp(dt: datetime.datetime) -> str:
    """RFC3339 string for ad-hoc timestamp maps (e.g. PDB
    disrupted_pods). One format, shared with :func:`parse_stamp` —
    writer and reader must never drift."""
    return dt.strftime(_STAMP_FMT)


def parse_stamp(s: str) -> datetime.datetime:
    """Inverse of :func:`stamp`; raises ValueError on junk."""
    return datetime.datetime.strptime(s, _STAMP_FMT)


def new_uid() -> str:
    return str(uuid.uuid4())


#: Deletion-propagation finalizers (reference: metav1.FinalizerOrphan-
#: Dependents / FinalizerDeleteDependents). Set by the registry when a
#: DELETE carries propagationPolicy Orphan/Foreground; processed by the
#: garbage collector, which then clears them to complete the deletion.
FINALIZER_ORPHAN = "orphan"
FINALIZER_FOREGROUND = "foregroundDeletion"


@dataclass
class OwnerReference:
    """Backpointer used by the garbage collector and controller adoption.

    Reference: ``metav1.OwnerReference`` + controller_ref util.
    """

    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False
    block_owner_deletion: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    #: MVCC mod-revision as a decimal string; "" means unset. Optimistic
    #: concurrency: updates carrying a stale value get 409 Conflict.
    resource_version: str = ""
    #: Monotonic spec generation, bumped by the registry on spec change.
    generation: int = 0
    creation_timestamp: Optional[datetime.datetime] = None
    #: Set (not removed) on delete while finalizers remain — graceful deletion.
    deletion_timestamp: Optional[datetime.datetime] = None
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    owner_references: list[OwnerReference] = field(default_factory=list)
    finalizers: list[str] = field(default_factory=list)
    #: Server-side name generation prefix (``generate_name`` + random suffix).
    generate_name: str = ""


@dataclass
class ListMeta:
    #: Store revision at which the list was read; feed to watch ``from_rev``.
    resource_version: str = ""
    #: Continuation token for chunked LIST (opaque).
    continue_token: str = ""


@dataclass
class TypedObject:
    """Base for all API objects: TypeMeta + ObjectMeta.

    Subclasses are dataclasses adding ``spec``/``status``/etc. Object
    identity key is ``namespace/name`` (or ``name`` for cluster-scoped).
    """

    api_version: str = ""
    kind: str = ""
    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    # -- convenience accessors used throughout the codebase --------------
    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def key(self) -> str:
        """Cache key: 'namespace/name' or 'name' when cluster-scoped."""
        if self.metadata.namespace:
            return f"{self.metadata.namespace}/{self.metadata.name}"
        return self.metadata.name


def controller_ref(owner: TypedObject, api_version: str, kind: str) -> OwnerReference:
    return OwnerReference(
        api_version=api_version,
        kind=kind,
        name=owner.metadata.name,
        uid=owner.metadata.uid,
        controller=True,
        block_owner_deletion=True,
    )


def get_controller_of(obj: TypedObject) -> Optional[OwnerReference]:
    for ref in obj.metadata.owner_references:
        if ref.controller:
            return ref
    return None


def is_controlled_by(obj: TypedObject, owner: TypedObject) -> bool:
    ref = get_controller_of(obj)
    return ref is not None and ref.uid == owner.metadata.uid


def split_key(key: str) -> tuple[str, str]:
    """'namespace/name' -> (namespace, name); 'name' -> ('', name)."""
    if "/" in key:
        ns, _, name = key.partition("/")
        return ns, name
    return "", key


def fresh_meta(name: str = "", namespace: str = "", **kw) -> ObjectMeta:
    return ObjectMeta(name=name, namespace=namespace, **kw)


def stamp_new(meta: ObjectMeta) -> None:
    """Server-side fill-in at create time (uid, timestamps, generated name)."""
    if not meta.uid:
        meta.uid = new_uid()
    if meta.creation_timestamp is None:
        meta.creation_timestamp = now()
    if not meta.name and meta.generate_name:
        meta.name = meta.generate_name + uuid.uuid4().hex[:6]


def is_dataclass_instance(obj) -> bool:
    return dataclasses.is_dataclass(obj) and not isinstance(obj, type)
