"""Core API group — Pod/Node/Service/... with a TPU-first device model.

Reference analog: ``staging/src/k8s.io/api/core/v1/types.go`` (~4.6k
lines) plus the fork's per-device extended-resource delta
(``types.go:4018-4056`` ExtendedResourceMap, ``:4036-4051``
PodExtendedResource, ``:4495`` Binding.Target.ExtendedResources).

TPU-first redesign rather than translation:

- A node advertises a :class:`TpuTopology` — chips with *ICI mesh
  coordinates* and attributes, plus the slice identity/shape the node
  belongs to. The reference's device map is flat (ID -> attributes);
  coords are first-class here because placement is sub-mesh allocation.
- A pod carries :class:`PodTpuRequest` — either a chip *count* or a
  *slice shape* (e.g. ``[2,2,4]``) plus attribute affinity. The
  scheduler writes concrete chip IDs into ``assigned`` via the binding
  subresource in one atomic store update (reference pattern:
  ``pkg/registry/core/pod/storage/storage.go:154``).
- Gang scheduling is first-class via :class:`PodGroup` (reference has
  none — SURVEY.md section 2.4).
"""
from __future__ import annotations

import datetime
from dataclasses import dataclass, field
from typing import Optional

from .meta import ListMeta, ObjectMeta, TypedObject
from .scheme import DEFAULT_SCHEME
from .selectors import LabelSelector, Requirement

# ---------------------------------------------------------------------------
# Resource quantities
# ---------------------------------------------------------------------------

#: Resource name for TPU chips (the ``nvidia.com/gpu`` analog).
RESOURCE_TPU = "google.com/tpu"
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"
#: Producer: TTL controller (controllers/ttl.py); consumer: node agent
#: config-read cache (node/volumes.py ObjectCache).
TTL_ANNOTATION = "node.alpha.kubernetes.io/ttl"

#: ResourceList: resource name -> quantity. cpu in cores, memory in bytes.
ResourceList = dict

_SUFFIXES = {
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
}


def parse_quantity(q) -> float:
    """'100m' -> 0.1, '2Gi' -> 2147483648.0, 4 -> 4.0 (k8s quantity syntax)."""
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q).strip()
    for suf in sorted(_SUFFIXES, key=len, reverse=True):
        if s.endswith(suf):
            return float(s[: -len(suf)]) * _SUFFIXES[suf]
    if s.endswith("m"):
        return float(s[:-1]) / 1000.0
    return float(s)


# ---------------------------------------------------------------------------
# TPU device model (the fork-delta, redesigned)
# ---------------------------------------------------------------------------

TPU_HEALTHY = "Healthy"
TPU_UNHEALTHY = "Unhealthy"


@dataclass
class TpuChip:
    """One chip on a node. Reference analog: ``ExtendedResource``
    (``types.go:4022-4034``) — but coords are structural, not a string attr."""

    id: str = ""
    health: str = TPU_HEALTHY
    #: Global coordinates of this chip in its slice's 3D mesh.
    coords: list[int] = field(default_factory=list)
    #: Free-form attributes matched by affinity (chip_type, hbm_gib, ...).
    attributes: dict[str, str] = field(default_factory=dict)


@dataclass
class TpuTopology:
    """Node's view of its TPU hardware, published in NodeStatus.

    Replaces the reference's ``ExtendedResourceMap``
    (``types.go:4018-4020``). The slice identity makes multi-host
    sub-mesh allocation possible: the scheduler groups nodes by
    ``slice_id`` and packs boxes in the slice's global ``mesh_shape``.
    """

    #: e.g. "v5p", "v5e", "v6e".
    chip_type: str = ""
    #: Identity of the (multi-host) slice this node belongs to.
    slice_id: str = ""
    #: Full mesh shape of the slice, e.g. [4,4,4] for v5p-64 (chips).
    mesh_shape: list[int] = field(default_factory=list)
    #: This host's index within the slice (TPU_WORKER_ID seed).
    worker_index: int = 0
    #: Chips physically attached to this host.
    chips: list[TpuChip] = field(default_factory=list)


@dataclass
class PodTpuRequest:
    """Pod-level TPU claim, referenced from containers by name.

    Reference analog: ``PodExtendedResource`` (``types.go:4036-4051``):
    Name/Resources/Affinity/Annotations/Assigned. Redesign: adds
    ``slice_shape`` so a claim can demand a *contiguous sub-mesh*, the
    unit JAX meshes map onto, instead of only a count.
    """

    name: str = ""
    resource: str = RESOURCE_TPU
    #: Number of chips wanted (used when slice_shape is empty).
    chips: int = 0
    #: Contiguous sub-mesh shape wanted, e.g. [2,2,4]. Overrides chips.
    slice_shape: list[int] = field(default_factory=list)
    #: All requirements must match a chip's attributes (cf.
    #: ``ExtendedResourceAffinity.Required``, ``types.go:2632-2639``).
    affinity: list[Requirement] = field(default_factory=list)
    annotations: dict[str, str] = field(default_factory=dict)
    #: Chip IDs chosen by the scheduler; written via the binding
    #: subresource; the durable record of allocation (the fork's key
    #: trick: the checkpoint is the API object — SURVEY.md section 5.4).
    assigned: list[str] = field(default_factory=list)

    def chip_count(self) -> int:
        if self.slice_shape:
            n = 1
            for d in self.slice_shape:
                n *= d
            return n
        return self.chips


@dataclass
class TpuBinding:
    """Scheduler's device choice for one claim, carried on the Binding.

    Reference analog: ``ExtendedResourceBinding`` (``types.go:4495``).
    """

    name: str = ""
    chip_ids: list[str] = field(default_factory=list)


# ---------------------------------------------------------------------------
# Containers & pods
# ---------------------------------------------------------------------------


@dataclass
class KeySelector:
    """Selects one key of a ConfigMap/Secret in the pod's namespace."""
    name: str = ""
    key: str = ""
    optional: bool = False


@dataclass
class FieldRef:
    """Downward-API field selector (reference: ``ObjectFieldSelector``).
    Supported paths: metadata.name, metadata.namespace, metadata.uid,
    spec.node_name, status.pod_ip, status.host_ip."""
    field_path: str = ""


@dataclass
class EnvVarSource:
    config_map_key_ref: Optional[KeySelector] = None
    secret_key_ref: Optional[KeySelector] = None
    field_ref: Optional[FieldRef] = None


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""
    value_from: Optional[EnvVarSource] = None


@dataclass
class EnvFromSource:
    """Bulk env import (reference: ``EnvFromSource``): every data key of
    the named ConfigMap/Secret becomes ``{prefix}{key}``."""
    prefix: str = ""
    config_map_ref: str = ""
    secret_ref: str = ""
    optional: bool = False


@dataclass
class ContainerPort:
    name: str = ""
    container_port: int = 0
    host_port: int = 0
    protocol: str = "TCP"


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    read_only: bool = False


@dataclass
class HostPathVolume:
    path: str = ""


@dataclass
class EmptyDirVolume:
    medium: str = ""


@dataclass
class ConfigMapVolume:
    name: str = ""


@dataclass
class SecretVolume:
    secret_name: str = ""


@dataclass
class PersistentVolumeClaimVolume:
    claim_name: str = ""
    read_only: bool = False


@dataclass
class Volume:
    name: str = ""
    host_path: Optional[HostPathVolume] = None
    empty_dir: Optional[EmptyDirVolume] = None
    config_map: Optional[ConfigMapVolume] = None
    secret: Optional[SecretVolume] = None
    persistent_volume_claim: Optional[PersistentVolumeClaimVolume] = None


@dataclass
class HTTPGetAction:
    path: str = "/"
    port: int = 0
    host: str = ""
    scheme: str = "HTTP"


@dataclass
class Probe:
    """Liveness/readiness probe (reference: ``pkg/probe/`` + prober)."""

    exec_command: list[str] = field(default_factory=list)
    http_get: Optional[HTTPGetAction] = None
    tcp_port: int = 0
    initial_delay_seconds: int = 0
    period_seconds: int = 10
    timeout_seconds: int = 1
    success_threshold: int = 1
    failure_threshold: int = 3


@dataclass
class ResourceRequirements:
    limits: dict[str, float] = field(default_factory=dict)
    requests: dict[str, float] = field(default_factory=dict)


@dataclass
class LifecycleHandler:
    """Exec-style hook action (reference: ``v1.Handler``; exec is the
    one action the process runtime can honor faithfully — it runs in
    the container's env + sandbox, like ``ktl exec``)."""
    exec_command: list[str] = field(default_factory=list)


@dataclass
class Lifecycle:
    """postStart/preStop hooks (reference: ``v1.Lifecycle``,
    ``pkg/kubelet/lifecycle handlers.go``)."""
    post_start: Optional[LifecycleHandler] = None
    pre_stop: Optional[LifecycleHandler] = None


@dataclass
class Container:
    name: str = ""
    image: str = ""
    command: list[str] = field(default_factory=list)
    args: list[str] = field(default_factory=list)
    working_dir: str = ""
    env: list[EnvVar] = field(default_factory=list)
    env_from: list[EnvFromSource] = field(default_factory=list)
    ports: list[ContainerPort] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    volume_mounts: list[VolumeMount] = field(default_factory=list)
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    lifecycle: Optional[Lifecycle] = None
    #: Names of PodSpec.tpu_resources entries this container uses.
    #: Reference analog: ``Container.ExtendedResourceRequests``
    #: (``types.go:2204``).
    tpu_requests: list[str] = field(default_factory=list)
    security_context: Optional[SecurityContext] = None


RESTART_ALWAYS = "Always"
RESTART_ON_FAILURE = "OnFailure"
RESTART_NEVER = "Never"

TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_NO_EXECUTE = "NoExecute"


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = TAINT_NO_SCHEDULE
    time_added: Optional[datetime.datetime] = None


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclass
class NodeAffinityTerm:
    match_expressions: list[Requirement] = field(default_factory=list)

    def matches(self, labels) -> bool:
        return all(r.matches(labels) for r in self.match_expressions)


@dataclass
class PodAffinityTerm:
    label_selector: Optional[LabelSelector] = None
    topology_key: str = "kubernetes.io/hostname"
    namespaces: list[str] = field(default_factory=list)


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 1
    pod_affinity_term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class Affinity:
    #: Node must match at least one term (OR of ANDs, metav1 semantics).
    node_required: list[NodeAffinityTerm] = field(default_factory=list)
    node_preferred: list[NodeAffinityTerm] = field(default_factory=list)
    pod_affinity: list[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity: list[PodAffinityTerm] = field(default_factory=list)
    pod_affinity_preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity_preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class SecurityContext:
    """Container-level security settings (reference:
    ``staging/src/k8s.io/api/core/v1/types.go SecurityContext``),
    restricted to what a process runtime can truly enforce: uid/gid
    via setuid/setgid at spawn, read-only mounts, and rlimits derived
    from resource limits."""
    run_as_user: Optional[int] = None
    run_as_group: Optional[int] = None
    run_as_non_root: bool = False
    read_only_root_filesystem: bool = False


@dataclass
class PodSecurityContext:
    """Pod-level defaults every container inherits unless it overrides
    (reference: ``PodSecurityContext``). ``fs_group`` is the group
    ownership applied to the pod's writable volume dirs."""
    run_as_user: Optional[int] = None
    run_as_group: Optional[int] = None
    run_as_non_root: bool = False
    fs_group: Optional[int] = None


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    volumes: list[Volume] = field(default_factory=list)
    restart_policy: str = RESTART_ALWAYS
    termination_grace_period_seconds: int = 30
    active_deadline_seconds: Optional[int] = None
    node_selector: dict[str, str] = field(default_factory=dict)
    node_name: str = ""
    host_network: bool = False
    hostname: str = ""
    subdomain: str = ""
    service_account_name: str = ""
    scheduler_name: str = "default-scheduler"
    tolerations: list[Toleration] = field(default_factory=list)
    affinity: Optional[Affinity] = None
    priority: Optional[int] = None
    priority_class_name: str = ""
    #: TPU claims (fork analog: PodSpec.ExtendedResources, ``types.go:2885``).
    tpu_resources: list[PodTpuRequest] = field(default_factory=list)
    #: Name of the PodGroup this pod gangs with ("" = no gang).
    gang: str = ""
    security_context: Optional[PodSecurityContext] = None


POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"
POD_UNKNOWN = "Unknown"

COND_POD_SCHEDULED = "PodScheduled"
COND_POD_INITIALIZED = "Initialized"
COND_POD_READY = "Ready"
COND_CONTAINERS_READY = "ContainersReady"


@dataclass
class PodCondition:
    type: str = ""
    status: str = "False"  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: Optional[datetime.datetime] = None


@dataclass
class ContainerStateWaiting:
    reason: str = ""
    message: str = ""


@dataclass
class ContainerStateRunning:
    started_at: Optional[datetime.datetime] = None


@dataclass
class ContainerStateTerminated:
    exit_code: int = 0
    reason: str = ""
    message: str = ""
    started_at: Optional[datetime.datetime] = None
    finished_at: Optional[datetime.datetime] = None


@dataclass
class ContainerState:
    waiting: Optional[ContainerStateWaiting] = None
    running: Optional[ContainerStateRunning] = None
    terminated: Optional[ContainerStateTerminated] = None


@dataclass
class ContainerStatus:
    name: str = ""
    state: ContainerState = field(default_factory=ContainerState)
    last_state: ContainerState = field(default_factory=ContainerState)
    ready: bool = False
    restart_count: int = 0
    image: str = ""
    container_id: str = ""


@dataclass
class PodStatus:
    phase: str = POD_PENDING
    conditions: list[PodCondition] = field(default_factory=list)
    message: str = ""
    reason: str = ""
    host_ip: str = ""
    pod_ip: str = ""
    start_time: Optional[datetime.datetime] = None
    container_statuses: list[ContainerStatus] = field(default_factory=list)
    init_container_statuses: list[ContainerStatus] = field(default_factory=list)
    #: Node a preemptor is waiting on (reference: status.nominatedNodeName).
    nominated_node_name: str = ""
    #: Guaranteed / Burstable / BestEffort (reference: status.qosClass,
    #: computed by qos.go GetPodQOS; here node/containermanager.py).
    qos_class: str = ""


@dataclass
class Pod(TypedObject):
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class BindingTarget:
    node_name: str = ""
    #: Per-claim chip assignment (fork: Binding.Target.ExtendedResources).
    tpu_bindings: list[TpuBinding] = field(default_factory=list)


@dataclass
class Binding(TypedObject):
    """Posted by the scheduler to ``pods/<name>/binding``; the registry
    writes node_name + assigned chip IDs in ONE GuaranteedUpdate
    (reference: ``pkg/registry/core/pod/storage/storage.go:130-210``)."""

    target: BindingTarget = field(default_factory=BindingTarget)


@dataclass
class Eviction(TypedObject):
    """Posted to ``pods/<name>/eviction`` — the PDB-gated voluntary
    delete (reference: policy Eviction,
    ``pkg/registry/core/pod/storage/eviction.go:57-120``). The server
    refuses with 429 while the budget allows no disruption; on success
    the pod is deleted with ``grace_period_seconds``.

    ``override_budget=True`` is the priority-policy escape hatch
    (scheduler preemption, dead-node escalation): the allowed check is
    skipped but the disruption is still RECORDED in the PDB's
    ``disrupted_pods`` accounting. RBAC-wise it rides the same
    pods/eviction create verb — grant that verb only to components
    trusted to preempt."""

    grace_period_seconds: Optional[int] = None
    override_budget: bool = False


# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------

NODE_READY = "Ready"
NODE_MEMORY_PRESSURE = "MemoryPressure"
NODE_DISK_PRESSURE = "DiskPressure"
NODE_NETWORK_UNAVAILABLE = "NetworkUnavailable"

# Well-known taints applied by the node lifecycle controller
# (reference: ``pkg/controller/node``).
TAINT_NODE_NOT_READY = "node.tpu/not-ready"
TAINT_NODE_UNREACHABLE = "node.tpu/unreachable"
TAINT_NODE_UNSCHEDULABLE = "node.tpu/unschedulable"


@dataclass
class NodeCondition:
    type: str = ""
    status: str = "Unknown"
    reason: str = ""
    message: str = ""
    last_heartbeat_time: Optional[datetime.datetime] = None
    last_transition_time: Optional[datetime.datetime] = None


@dataclass
class NodeAddress:
    type: str = "InternalIP"  # InternalIP | Hostname
    address: str = ""


@dataclass
class NodeSystemInfo:
    machine_id: str = ""
    kernel_version: str = ""
    os_image: str = ""
    container_runtime_version: str = ""
    agent_version: str = ""
    architecture: str = ""


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: list[Taint] = field(default_factory=list)
    provider_id: str = ""
    pod_cidr: str = ""


@dataclass
class NodeStatus:
    capacity: dict[str, float] = field(default_factory=dict)
    allocatable: dict[str, float] = field(default_factory=dict)
    conditions: list[NodeCondition] = field(default_factory=list)
    addresses: list[NodeAddress] = field(default_factory=list)
    node_info: NodeSystemInfo = field(default_factory=NodeSystemInfo)
    #: The TPU device map (fork: node.Status.ExtendedResources via
    #: ``kubelet_node_status.go:552-621``).
    tpu: Optional[TpuTopology] = None
    daemon_endpoints: dict[str, int] = field(default_factory=dict)


@dataclass
class Node(TypedObject):
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)


# ---------------------------------------------------------------------------
# Services / endpoints / namespaces / config
# ---------------------------------------------------------------------------


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0
    target_port: int = 0
    node_port: int = 0
    protocol: str = "TCP"


@dataclass
class ServiceSpec:
    selector: dict[str, str] = field(default_factory=dict)
    ports: list[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""  # "None" => headless
    type: str = "ClusterIP"  # ClusterIP | NodePort | LoadBalancer
    #: "None" | "ClientIP" — ClientIP pins a client to one endpoint
    #: for the timeout (iptables: recent-module lists per SEP chain).
    session_affinity: str = "None"
    session_affinity_timeout_seconds: int = 10800


@dataclass
class ServiceStatus:
    load_balancer_ip: str = ""


@dataclass
class Service(TypedObject):
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    status: ServiceStatus = field(default_factory=ServiceStatus)


@dataclass
class ObjectReference:
    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""
    field_path: str = ""


@dataclass
class EndpointAddress:
    ip: str = ""
    hostname: str = ""
    node_name: str = ""
    target_ref: Optional[ObjectReference] = None


@dataclass
class EndpointPort:
    name: str = ""
    port: int = 0
    protocol: str = "TCP"


@dataclass
class EndpointSubset:
    addresses: list[EndpointAddress] = field(default_factory=list)
    not_ready_addresses: list[EndpointAddress] = field(default_factory=list)
    ports: list[EndpointPort] = field(default_factory=list)


@dataclass
class Endpoints(TypedObject):
    subsets: list[EndpointSubset] = field(default_factory=list)


NS_ACTIVE = "Active"
NS_TERMINATING = "Terminating"


@dataclass
class NamespaceSpec:
    finalizers: list[str] = field(default_factory=lambda: ["kubernetes_tpu"])


@dataclass
class NamespaceStatus:
    phase: str = NS_ACTIVE


@dataclass
class Namespace(TypedObject):
    spec: NamespaceSpec = field(default_factory=NamespaceSpec)
    status: NamespaceStatus = field(default_factory=NamespaceStatus)


@dataclass
class ConfigMap(TypedObject):
    data: dict[str, str] = field(default_factory=dict)


@dataclass
class Secret(TypedObject):
    """``data`` values are base64 (reference wire format, no guessing);
    ``string_data`` is the plaintext write-convenience field, merged
    into ``data`` by the create/update strategy."""
    type: str = "Opaque"
    data: dict[str, str] = field(default_factory=dict)
    string_data: dict[str, str] = field(default_factory=dict)


@dataclass
class EventSource:
    component: str = ""
    host: str = ""


@dataclass
class Event(TypedObject):
    involved_object: ObjectReference = field(default_factory=ObjectReference)
    reason: str = ""
    message: str = ""
    type: str = "Normal"  # Normal | Warning
    count: int = 1
    source: EventSource = field(default_factory=EventSource)
    first_timestamp: Optional[datetime.datetime] = None
    last_timestamp: Optional[datetime.datetime] = None


@dataclass
class ResourceQuotaSpec:
    hard: dict[str, float] = field(default_factory=dict)


@dataclass
class ResourceQuotaStatus:
    hard: dict[str, float] = field(default_factory=dict)
    used: dict[str, float] = field(default_factory=dict)


@dataclass
class ResourceQuota(TypedObject):
    spec: ResourceQuotaSpec = field(default_factory=ResourceQuotaSpec)
    status: ResourceQuotaStatus = field(default_factory=ResourceQuotaStatus)


@dataclass
class LimitRangeItem:
    type: str = "Container"
    default: dict[str, float] = field(default_factory=dict)
    default_request: dict[str, float] = field(default_factory=dict)
    max: dict[str, float] = field(default_factory=dict)
    min: dict[str, float] = field(default_factory=dict)


@dataclass
class LimitRangeSpec:
    limits: list[LimitRangeItem] = field(default_factory=list)


@dataclass
class LimitRange(TypedObject):
    spec: LimitRangeSpec = field(default_factory=LimitRangeSpec)


@dataclass
class PriorityClass(TypedObject):
    value: int = 0
    global_default: bool = False
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    description: str = ""


@dataclass
class UidRange:
    min: int = 0
    max: int = 0


@dataclass
class PodSecurityPolicySpec:
    """PSP-lite (reference: ``pkg/security/podsecuritypolicy/``): the
    subset a process runtime can enforce — who a pod may run as, and
    what of the host it may touch."""
    #: "RunAsAny" | "MustRunAs" (within ranges) | "MustRunAsNonRoot"
    run_as_user_rule: str = "RunAsAny"
    run_as_user_ranges: list[UidRange] = field(default_factory=list)
    #: hostPath volumes allowed at all?
    allow_host_paths: bool = True
    #: every hostPath mount must be read_only in every container
    read_only_host_paths: bool = False


@dataclass
class PodSecurityPolicy(TypedObject):
    spec: PodSecurityPolicySpec = field(
        default_factory=PodSecurityPolicySpec)


@dataclass
class LeaseSpec:
    holder_identity: str = ""
    lease_duration_seconds: float = 15
    acquire_time: Optional[datetime.datetime] = None
    renew_time: Optional[datetime.datetime] = None
    lease_transitions: int = 0


@dataclass
class Lease(TypedObject):
    """Coordination primitive for leader election + node heartbeats."""

    spec: LeaseSpec = field(default_factory=LeaseSpec)


# ---------------------------------------------------------------------------
# Gang scheduling (TPU-first; no reference analog — SURVEY section 2.4)
# ---------------------------------------------------------------------------

PODGROUP_PENDING = "Pending"
PODGROUP_SCHEDULED = "Scheduled"
PODGROUP_RUNNING = "Running"
PODGROUP_FAILED = "Failed"

#: Graceful-preemption protocol phases (status.preemption.phase).
#: "" -> Signaled -> Requeued; Checkpointing is the observable middle
#: state once any member has reported a checkpoint-complete marker.
PREEMPT_SIGNALED = "Signaled"
PREEMPT_CHECKPOINTING = "Checkpointing"
PREEMPT_REQUEUED = "Requeued"

#: How the checkpoint request reaches the workload (spec.checkpoint).
PREEMPT_SIGNAL_FILE = "file"        # KTPU_PREEMPT_FILE appears
PREEMPT_SIGNAL_TERM = "sigterm"     # SIGTERM to container processes
PREEMPT_SIGNAL_BOTH = "both"        # file + SIGTERM (the default)
PREEMPT_SIGNAL_MODES = (PREEMPT_SIGNAL_FILE, PREEMPT_SIGNAL_TERM,
                        PREEMPT_SIGNAL_BOTH)

#: Pod annotation the preemption engine stamps to request a
#: checkpoint; value is the absolute unix deadline (seconds). The node
#: agent delivers the in-container signal when it appears.
PREEMPT_ANNOTATION = "preemption.tpu/checkpoint-by"

#: Live-migration round phases (status.migration.phase). A round is
#: OPEN in Reserved/Moving and CLOSED ("") otherwise; outcome records
#: how the last round ended.
MIGRATE_RESERVED = "Reserved"   # target box reserved, gang not signaled
MIGRATE_MOVING = "Moving"       # checkpoint round in flight / requeued
MIGRATE_PHASES = ("", MIGRATE_RESERVED, MIGRATE_MOVING)

#: Why a migration round was opened (status.migration.reason).
MIGRATE_REASON_DEGRADED = "degraded-node"   # sick-chip taint evacuation
MIGRATE_REASON_DEFRAG = "defrag"            # fragmentation consolidation
MIGRATE_REASONS = (MIGRATE_REASON_DEGRADED, MIGRATE_REASON_DEFRAG)


@dataclass
class CheckpointSpec:
    """Opt-in graceful preemption contract for a gang (spec.checkpoint).

    ``grace_seconds`` bounds how long every eviction path (gang
    preemption, fair-share reclaim, elastic shrink) waits between
    signaling the gang and killing it; 0 disables the protocol for
    this gang even with the GracefulPreemption gate on. On timeout the
    kill proceeds exactly like the legacy path — a wedged workload can
    never hold quota hostage."""

    grace_seconds: float = 0.0
    #: One of PREEMPT_SIGNAL_MODES.
    signal: str = PREEMPT_SIGNAL_BOTH


@dataclass
class PreemptionStatus:
    """Durable graceful-preemption state (status.preemption): rides
    the WAL like admission state, so a restarted control plane resumes
    the protocol instead of forgetting a signaled gang."""

    #: "" | Signaled | Checkpointing | Requeued.
    phase: str = ""
    #: Pod names the current round signaled (elastic shrink signals
    #: only the surplus members).
    signaled: list[str] = field(default_factory=list)
    #: Pod names whose checkpoint-complete marker has been recorded.
    checkpointed: list[str] = field(default_factory=list)
    #: Highest COMPLETED checkpoint step ever recorded for this gang —
    #: monotonic (the tpusan checkpoint-monotonic invariant); -1 =
    #: no checkpoint recorded yet.
    checkpoint_step: int = -1
    #: When the current round was signaled, and its absolute deadline
    #: (signaled_time + spec.checkpoint.grace_seconds).
    signaled_time: Optional[datetime.datetime] = None
    #: Unix seconds; past it the engine degrades to the hard kill.
    deadline: float = 0.0
    #: When the round finished (evict + requeue).
    requeued_time: Optional[datetime.datetime] = None
    #: Why the last round ended: "checkpointed" (quorum reported) or
    #: "deadline" (timed out into the legacy kill).
    outcome: str = ""
    #: Completed graceful rounds — observability + revision stamp.
    rounds: int = 0


@dataclass
class MigrationStatus:
    """Durable live-migration round state (status.migration): rides
    the WAL like preemption state, so a crashed MigrationController
    resumes or aborts an open round instead of stranding the gang
    (tpusan invariant migration-no-strand)."""

    #: "" | Reserved | Moving (MIGRATE_PHASES).
    phase: str = ""
    #: Why this round opened: degraded-node | defrag.
    reason: str = ""
    #: Slice the reserved target box lives on.
    target_slice: str = ""
    #: Mesh coords of the reserved target box, as "x,y,z" strings
    #: (JSON-stable; a crashed controller re-carves the reservation
    #: from these).
    target_cells: list[str] = field(default_factory=list)
    #: Nodes hosting the target box — the chaos target-node-down kind
    #: kills one of these between reserve and bind.
    target_nodes: list[str] = field(default_factory=list)
    #: When the round opened; unix deadline past which the controller
    #: aborts the round (close status, release reservation).
    started_time: Optional[datetime.datetime] = None
    deadline: float = 0.0
    #: When the last round closed — the per-gang cooldown anchor.
    finished_time: Optional[datetime.datetime] = None
    #: Why the last round ended: "moved" | "aborted" | "no-target".
    outcome: str = ""
    #: Completed migration rounds (moved or aborted) — observability.
    rounds: int = 0


@dataclass
class PodGroupSpec:
    #: All-or-nothing: schedule no member until min_member can all fit.
    min_member: int = 1
    #: If set, the whole gang must land on one slice as a contiguous
    #: sub-mesh of this shape (chips across all members).
    slice_shape: list[int] = field(default_factory=list)
    priority: Optional[int] = None
    #: Give up and fail the gang if unschedulable this long (seconds).
    schedule_timeout_seconds: int = 0
    #: LocalQueue (in this namespace) the gang is admitted through.
    #: Empty = unqueued; with the JobQueueing gate off the field is
    #: ignored entirely (api/queueing.py).
    queue: str = ""
    #: Total gang resource demand charged against the queue's quota at
    #: admission time (e.g. {"cpu": 8, "memory": 2**34}). Chips default
    #: from prod(slice_shape) when absent — admission must not depend
    #: on member pods existing yet.
    resources: dict[str, float] = field(default_factory=dict)
    #: Graceful-preemption opt-in (None/grace 0 = legacy hard kill).
    checkpoint: Optional[CheckpointSpec] = None
    #: Elastic sizing (0 = fixed-size gang). A gang may run with any
    #: member count in [min_replicas, max_replicas]; spec.resources /
    #: slice_shape describe the FULL (max_replicas) size and the quota
    #: charge scales linearly with status.replicas. Under fair-share
    #: reclaim an elastic gang shrinks to min_replicas (releasing the
    #: borrowed delta) instead of dying, and regrows when quota allows.
    min_replicas: int = 0
    max_replicas: int = 0


@dataclass
class PodGroupStatus:
    phase: str = PODGROUP_PENDING
    scheduled: int = 0
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    #: Slice the gang landed on + the box origin/shape, for observability.
    slice_id: str = ""
    conditions: list[PodCondition] = field(default_factory=list)
    #: Queue admission (queueing/v1): an unadmitted gang with
    #: ``spec.queue`` set is SUSPENDED — it never enters the scheduling
    #: heap. Persisted in status so WAL replay reconstructs admitted
    #: usage exactly (no double admission after a controller restart).
    admitted: bool = False
    #: How admission happened: Nominal | Borrowed | Backfill ("" while
    #: pending). Borrowed gangs are the reclaim victims when the
    #: lending queue's own demand returns.
    admission_mode: str = ""
    #: When admission happened — the backfill pass projects admitted
    #: gangs' completion (admitted_time + runtime annotation) to compute
    #: the blocker's shadow time.
    admitted_time: Optional[datetime.datetime] = None
    #: ClusterQueue the charge landed in, stamped at admission: usage
    #: accounting must survive the LocalQueue being deleted afterwards
    #: (the namespace binding resolved at admission time is the durable
    #: fact, not the binding's continued existence).
    admission_cluster_queue: str = ""
    #: Graceful-preemption protocol state (None until first signaled).
    preemption: Optional[PreemptionStatus] = None
    #: Live-migration round state (None until first migration).
    migration: Optional[MigrationStatus] = None
    #: Elastic target size (member count the scheduler may bind up
    #: to). 0 on non-elastic gangs; set to max_replicas at admission,
    #: lowered to min_replicas by reclaim shrink, raised again by the
    #: regrow pass. The quota charge follows this number.
    replicas: int = 0


@dataclass
class PodGroup(TypedObject):
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)


# ---------------------------------------------------------------------------
# List envelope
# ---------------------------------------------------------------------------


@dataclass
class ObjectList:
    """Generic list: items carry their own TypeMeta and are decoded
    individually through the scheme."""

    api_version: str = "core/v1"
    kind: str = "List"
    metadata: ListMeta = field(default_factory=ListMeta)
    items: list = field(default_factory=list)


# ---------------------------------------------------------------------------
# Persistent storage (reference: PV/PVC in core/v1/types.go + StorageClass)
# ---------------------------------------------------------------------------

PV_AVAILABLE = "Available"
PV_BOUND = "Bound"
PV_RELEASED = "Released"
PVC_PENDING = "Pending"
PVC_BOUND = "Bound"

RECLAIM_RETAIN = "Retain"
RECLAIM_DELETE = "Delete"

#: The built-in dynamic provisioner (reference analog: the in-tree
#: host-path provisioner used by local-up clusters).
PROVISIONER_HOSTPATH = "kubernetes-tpu/host-path"


@dataclass
class CSIVolumeSource:
    """Out-of-process driver-backed volume (the CSI-analog seam,
    ``volumedriver/api.proto``; reference: core/v1 CSIPersistentVolumeSource
    consumed by ``pkg/volume/csi/csi_plugin.go:40``). ``driver`` names
    the socket under the node's volume-drivers dir; ``volume_handle``
    is the driver's own volume id."""

    driver: str = ""
    volume_handle: str = ""
    read_only: bool = False
    volume_attributes: dict[str, str] = field(default_factory=dict)


@dataclass
class PersistentVolumeSpec:
    #: {"storage": bytes} — same quantity convention as pod resources.
    capacity: dict[str, float] = field(default_factory=dict)
    access_modes: list[str] = field(default_factory=lambda: ["ReadWriteOnce"])
    storage_class_name: str = ""
    host_path: Optional[HostPathVolume] = None
    #: Driver-backed source — exactly one of host_path/csi is set.
    csi: Optional[CSIVolumeSource] = None
    claim_ref: Optional[ObjectReference] = None
    persistent_volume_reclaim_policy: str = RECLAIM_RETAIN


@dataclass
class PersistentVolumeStatus:
    phase: str = PV_AVAILABLE
    message: str = ""


@dataclass
class PersistentVolume(TypedObject):
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)
    status: PersistentVolumeStatus = field(default_factory=PersistentVolumeStatus)


@dataclass
class PersistentVolumeClaimSpec:
    access_modes: list[str] = field(default_factory=lambda: ["ReadWriteOnce"])
    #: {"storage": bytes} requested.
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    storage_class_name: str = ""
    volume_name: str = ""


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = PVC_PENDING
    capacity: dict[str, float] = field(default_factory=dict)


@dataclass
class PersistentVolumeClaim(TypedObject):
    spec: PersistentVolumeClaimSpec = field(
        default_factory=PersistentVolumeClaimSpec)
    status: PersistentVolumeClaimStatus = field(
        default_factory=PersistentVolumeClaimStatus)


#: Secret type carrying a service-account bearer token (reference:
#: ``SecretTypeServiceAccountToken``).
SECRET_TYPE_SA_TOKEN = "kubernetes-tpu/service-account-token"
#: Annotations binding a token Secret to its ServiceAccount (reference:
#: ``ServiceAccountNameKey`` / ``ServiceAccountUIDKey``). Both writer
#: (serviceaccount controller) and reader (apiserver authenticator)
#: use these constants.
SA_NAME_ANNOTATION = "kubernetes-tpu/service-account.name"
SA_UID_ANNOTATION = "kubernetes-tpu/service-account.uid"


@dataclass
class ServiceAccount(TypedObject):
    """Workload identity (reference: core/v1 ServiceAccount). RBAC
    subjects use the encoded user name
    ``system:serviceaccount:<namespace>:<name>``."""
    secrets: list[str] = field(default_factory=list)
    automount_token: bool = True


def service_account_user(namespace: str, name: str) -> str:
    return f"system:serviceaccount:{namespace}:{name}"


@dataclass
class StorageClass(TypedObject):
    provisioner: str = ""
    reclaim_policy: str = RECLAIM_DELETE
    #: Provisioner parameters (host-path: {"base_dir": ...}).
    parameters: dict[str, str] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# Registration + defaulting
# ---------------------------------------------------------------------------

CORE_V1 = "core/v1"

for _kind, _cls in [
    ("Pod", Pod), ("Node", Node), ("Binding", Binding),
    ("Eviction", Eviction), ("Service", Service),
    ("Endpoints", Endpoints), ("Namespace", Namespace), ("ConfigMap", ConfigMap),
    ("Secret", Secret), ("Event", Event), ("ResourceQuota", ResourceQuota),
    ("LimitRange", LimitRange), ("PriorityClass", PriorityClass),
    ("Lease", Lease), ("PodGroup", PodGroup), ("List", ObjectList),
    ("PersistentVolume", PersistentVolume),
    ("PersistentVolumeClaim", PersistentVolumeClaim),
    ("ServiceAccount", ServiceAccount),
]:
    DEFAULT_SCHEME.register(CORE_V1, _kind, _cls)

DEFAULT_SCHEME.register("storage/v1", "StorageClass", StorageClass)
DEFAULT_SCHEME.register("policy/v1", "PodSecurityPolicy", PodSecurityPolicy)


def _default_pod(pod: Pod) -> None:
    if not pod.spec.restart_policy:
        pod.spec.restart_policy = RESTART_ALWAYS
    if not pod.spec.scheduler_name:
        pod.spec.scheduler_name = "default-scheduler"
    for c in pod.spec.containers + pod.spec.init_containers:
        for p in c.ports:
            if not p.protocol:
                p.protocol = "TCP"


DEFAULT_SCHEME.add_defaulter(Pod, _default_pod)


# ---------------------------------------------------------------------------
# Helpers (reference: pkg/apis/core/v1/helper/helpers.go:465-545)
# ---------------------------------------------------------------------------


def pod_tpu_request(pod: Pod, name: str) -> Optional[PodTpuRequest]:
    for r in pod.spec.tpu_resources:
        if r.name == name:
            return r
    return None


def pod_tpu_chip_count(pod: Pod) -> int:
    return sum(r.chip_count() for r in pod.spec.tpu_resources)


def pod_tpu_assigned(pod: Pod) -> list[str]:
    out: list[str] = []
    for r in pod.spec.tpu_resources:
        out.extend(r.assigned)
    return out


def pod_resource_requests(pod: Pod) -> dict[str, float]:
    """Effective requests: max(init containers) elementwise-added to sum(containers),
    mirroring the reference's resource accounting, plus the TPU claim count."""
    total: dict[str, float] = {}
    for c in pod.spec.containers:
        for k, v in c.resources.requests.items():
            total[k] = total.get(k, 0.0) + parse_quantity(v)
    for c in pod.spec.init_containers:
        for k, v in c.resources.requests.items():
            total[k] = max(total.get(k, 0.0), parse_quantity(v))
    tpus = pod_tpu_chip_count(pod)
    if tpus:
        total[RESOURCE_TPU] = total.get(RESOURCE_TPU, 0.0) + tpus
    total[RESOURCE_PODS] = total.get(RESOURCE_PODS, 0.0) + 1
    return total


def is_pod_active(pod: Pod) -> bool:
    return (
        pod.status.phase not in (POD_SUCCEEDED, POD_FAILED)
        and pod.metadata.deletion_timestamp is None
    )


def is_pod_terminal(pod: Pod) -> bool:
    return pod.status.phase in (POD_SUCCEEDED, POD_FAILED)


def is_pod_ready(pod: Pod) -> bool:
    for c in pod.status.conditions:
        if c.type == COND_POD_READY:
            return c.status == "True"
    return False


def get_pod_condition(status: PodStatus, cond_type: str) -> Optional[PodCondition]:
    for c in status.conditions:
        if c.type == cond_type:
            return c
    return None


def update_pod_condition(status: PodStatus, cond: PodCondition) -> bool:
    """Insert/update condition; returns True if anything changed."""
    import datetime as _dt

    cond.last_transition_time = cond.last_transition_time or _dt.datetime.utcnow()
    existing = get_pod_condition(status, cond.type)
    if existing is None:
        status.conditions.append(cond)
        return True
    if (existing.status == cond.status and existing.reason == cond.reason
            and existing.message == cond.message):
        return False
    if existing.status == cond.status:
        cond.last_transition_time = existing.last_transition_time
    status.conditions.remove(existing)
    status.conditions.append(cond)
    return True


def get_node_condition(status: NodeStatus, cond_type: str) -> Optional[NodeCondition]:
    for c in status.conditions:
        if c.type == cond_type:
            return c
    return None


def is_node_ready(node: Node) -> bool:
    c = get_node_condition(node.status, NODE_READY)
    return c is not None and c.status == "True"


def tolerates_taints(pod: Pod, taints: list[Taint], effects=(TAINT_NO_SCHEDULE, TAINT_NO_EXECUTE)) -> bool:
    for t in taints:
        if t.effect not in effects:
            continue
        if not any(tol.tolerates(t) for tol in pod.spec.tolerations):
            return False
    return True


def pod_priority(pod: Pod) -> int:
    return pod.spec.priority if pod.spec.priority is not None else 0
