"""NetworkPolicy — namespace-scoped pod traffic rules.

Reference: ``staging/src/k8s.io/api/networking/v1/types.go``
(NetworkPolicy, NetworkPolicySpec, NetworkPolicyIngressRule/EgressRule,
NetworkPolicyPeer with podSelector/namespaceSelector/ipBlock,
NetworkPolicyPort, PolicyType). Semantics (the reference's contract):

- a pod is *selected* when any policy's ``pod_selector`` matches it in
  the policy's namespace; selected pods default-deny the directions
  listed in ``policy_types`` and allow only what some rule admits;
- unselected pods are unaffected (allow-all);
- rules are additive across policies — there is no deny rule.

Enforcement note: the reference apiserver only STORES these objects —
enforcement belongs to the CNI plugin (Calico etc.). Here the analog
is ``net/netpolicy.py``: an iptables filter-table renderer over pod
IPs, applied when privileged, golden-file tested always — the same
compute-always/apply-when-root posture as the NAT dataplane.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .meta import TypedObject
from .scheme import DEFAULT_SCHEME
from .selectors import LabelSelector

NETWORKING_V1 = "networking/v1"

POLICY_INGRESS = "Ingress"
POLICY_EGRESS = "Egress"


@dataclass
class IPBlock:
    cidr: str = ""
    except_cidrs: list[str] = field(default_factory=list)


@dataclass
class NetworkPolicyPeer:
    """Exactly one of the selectors (or ip_block) per the reference;
    pod+namespace selector together mean 'pods matching X in
    namespaces matching Y'."""
    pod_selector: Optional[LabelSelector] = None
    namespace_selector: Optional[LabelSelector] = None
    ip_block: Optional[IPBlock] = None


@dataclass
class NetworkPolicyPort:
    protocol: str = "TCP"
    port: int = 0  # 0 = all ports


@dataclass
class NetworkPolicyIngressRule:
    #: Empty = from anywhere (but still only what rules admit overall).
    from_peers: list[NetworkPolicyPeer] = field(default_factory=list)
    ports: list[NetworkPolicyPort] = field(default_factory=list)


@dataclass
class NetworkPolicyEgressRule:
    to_peers: list[NetworkPolicyPeer] = field(default_factory=list)
    ports: list[NetworkPolicyPort] = field(default_factory=list)


@dataclass
class NetworkPolicySpec:
    #: Which pods in this namespace the policy governs; empty selector
    #: selects ALL pods in the namespace (reference semantics).
    pod_selector: LabelSelector = field(default_factory=LabelSelector)
    ingress: list[NetworkPolicyIngressRule] = field(default_factory=list)
    egress: list[NetworkPolicyEgressRule] = field(default_factory=list)
    #: Directions this policy participates in. Defaulted at admission:
    #: Ingress always; Egress when egress rules are present.
    policy_types: list[str] = field(default_factory=list)


@dataclass
class NetworkPolicy(TypedObject):
    spec: NetworkPolicySpec = field(default_factory=NetworkPolicySpec)


DEFAULT_SCHEME.register(NETWORKING_V1, "NetworkPolicy", NetworkPolicy)


def _default_network_policy(np: "NetworkPolicy") -> None:
    np.spec.policy_types = default_policy_types(np.spec)


DEFAULT_SCHEME.add_defaulter(NetworkPolicy, _default_network_policy)


def default_policy_types(spec: NetworkPolicySpec) -> list[str]:
    """Reference defaulting: Ingress always; Egress iff egress rules
    exist (or it was explicitly listed)."""
    if spec.policy_types:
        return spec.policy_types
    types = [POLICY_INGRESS]
    if spec.egress:
        types.append(POLICY_EGRESS)
    return types


def validate_network_policy(np: NetworkPolicy, update: bool = False) -> None:
    from .errors import InvalidError
    for i, ptype in enumerate(np.spec.policy_types):
        if ptype not in (POLICY_INGRESS, POLICY_EGRESS):
            raise InvalidError(
                f"spec.policy_types[{i}]: must be Ingress or Egress, "
                f"got {ptype!r}")
    for d, rules in (("ingress", np.spec.ingress),
                     ("egress", np.spec.egress)):
        for i, rule in enumerate(rules):
            peers = (rule.from_peers if d == "ingress" else rule.to_peers)
            for j, peer in enumerate(peers):
                chosen = [x for x in (peer.pod_selector,
                                      peer.namespace_selector,
                                      peer.ip_block) if x is not None]
                if not chosen:
                    raise InvalidError(
                        f"spec.{d}[{i}].peers[{j}]: one of pod_selector,"
                        f" namespace_selector, ip_block required")
                if peer.ip_block is not None and (
                        peer.pod_selector or peer.namespace_selector):
                    raise InvalidError(
                        f"spec.{d}[{i}].peers[{j}]: ip_block is "
                        f"exclusive with the selectors")
                if peer.ip_block is not None and not peer.ip_block.cidr:
                    raise InvalidError(
                        f"spec.{d}[{i}].peers[{j}].ip_block: cidr "
                        f"required")
            for j, port in enumerate(rule.ports):
                if port.protocol not in ("TCP", "UDP"):
                    raise InvalidError(
                        f"spec.{d}[{i}].ports[{j}]: protocol must be "
                        f"TCP or UDP")
                if not (0 <= port.port <= 65535):
                    raise InvalidError(
                        f"spec.{d}[{i}].ports[{j}]: port out of range")
