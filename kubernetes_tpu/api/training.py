"""Training API — multi-host jax.distributed training as a workload.

Kubeflow-TrainJob/JobSet-analog kind (reference: trainer.kubeflow.org
TrainJob fused with the Indexed-Job gang semantics this tree already
has; PAPERS.md "Fine-Tuning and Serving Gemma on Cloud TPU" is the
scenario it exists for):

- :class:`TrainJob` (namespaced): one gang-scheduled multi-host
  training run — the model/workload ref, the worker count, per-worker
  chip demand, the checkpoint contract (shared PV + cadence), and the
  queueing/priority/elastic passthrough into the PodGroup. The train
  controller (``controllers/train.py``) reconciles it into a headless
  Service (rank DNS, ``net/dns.py``) plus a gang-annotated indexed pod
  set running ``workloads/trainer.py``, where every rank discovers the
  rank-0 coordinator through ``workloads/rendezvous.py`` and the
  cluster's own DNS — no external coordinator.

Durable progress (``status``): phase, per-rank states, restart rounds,
resume count, and the last completed checkpoint step all ride the WAL,
so a restarted control plane knows exactly where the gang is — the
API-object-as-checkpoint move, as ever.

Everything is gated behind ``TrainJobController`` (alpha, default
off): with the gate off the controller is inert and the tree's
behavior is byte-identical.
"""
from __future__ import annotations

import datetime
import math
from dataclasses import dataclass, field
from typing import Optional

from .meta import TypedObject
from .scheme import DEFAULT_SCHEME
from .validation import ErrorList, validate_object_meta

TRAINING_V1 = "training/v1"

#: Pod label joining a TrainJob to its worker pods (the selector the
#: headless Service and the controller's bookkeeping key on).
TRAINJOB_LABEL = "training.tpu/trainjob"

#: Pod label carrying the worker's rank (stable across restart rounds;
#: mirrors TPU_WORKER_ID).
RANK_LABEL = "training.tpu/rank"

#: Pod label carrying the WORLD SIZE the pod's rendezvous env was
#: built for. Elastic gangs change their target between rounds; a
#: round's members must all agree on one world, and the controller
#: uses this label to detect a live gang built for a stale target.
WORLD_LABEL = "training.tpu/world"

#: Coordinator port every rank dials (rank 0 binds it inside
#: ``jax.distributed.initialize``); spec.coord_port == 0 means this.
DEFAULT_COORD_PORT = 8476

#: TrainJobStatus.phase values.
TRAIN_PENDING = "Pending"        # workers not all running yet
TRAIN_RUNNING = "Running"        # full gang live
TRAIN_RECOVERING = "Recovering"  # a member died; round restarting
TRAIN_SUCCEEDED = "Succeeded"
TRAIN_FAILED = "Failed"


@dataclass
class TrainCheckpointSpec:
    """The PR-7 checkpoint contract for this job: periodic Orbax saves
    to a shared volume, so a recovered gang resumes instead of
    restarting from scratch."""

    #: PersistentVolumeClaim (this namespace) backing the shared
    #: checkpoint directory. "" = the node-local default base dir —
    #: resume then only survives same-node restarts.
    pvc: str = ""
    #: Save cadence in steps (0 = defaulted to 10 by the controller).
    every_steps: int = 0
    #: Graceful-preemption grace (seconds) carried into the PodGroup's
    #: CheckpointSpec (0 = legacy hard kill on preemption).
    grace_seconds: float = 0.0


@dataclass
class TrainJobSpec:
    #: Workload the trainer runs: "lm" (workloads/lm.py under pjit/mesh
    #: sharding) or "demo" (the exactly-computable counting loop).
    model: str = "lm"
    #: Gang size — one rank per pod, all-or-nothing scheduled.
    num_workers: int = 1
    #: Per-worker TPU demand: chip count, or a contiguous slice shape
    #: (shape wins when both are set). 0/empty = CPU-only workers (the
    #: e2e tier's virtual-device mode).
    chips_per_worker: int = 0
    slice_shape: list[int] = field(default_factory=list)
    #: Whole-gang contiguous sub-mesh shape (PodGroup.spec.slice_shape
    #: passthrough; empty = no contiguity constraint).
    gang_slice_shape: list[int] = field(default_factory=list)
    #: Per-worker CPU request.
    cpu_per_worker: float = 0.5
    #: Container image ("" = the built-in host environment).
    image: str = ""
    #: Training length/shape knobs forwarded to the trainer env.
    total_steps: int = 0       # defaulted to 100
    batch: int = 0             # defaulted by the trainer per model
    seq: int = 0
    #: Extra env forwarded verbatim to the trainer (model-size
    #: overrides, STEP_DELAY for chaos windows, ...). The framework's
    #: rank/rendezvous env wins on collision — an args entry can never
    #: scramble TPU_WORKER_ID or the coordinator contract.
    args: dict[str, str] = field(default_factory=dict)
    checkpoint: TrainCheckpointSpec = field(
        default_factory=TrainCheckpointSpec)
    #: Coordinator port (0 = DEFAULT_COORD_PORT).
    coord_port: int = 0
    #: Gang restart budget: a round restart past this fails the job.
    backoff_limit: int = 6
    #: Queueing/priority/elastic passthrough into the PodGroup.
    queue: str = ""
    priority: Optional[int] = None
    min_workers: int = 0   # elastic min (0 = fixed-size gang)
    max_workers: int = 0   # elastic max


@dataclass
class TrainJobStatus:
    #: One of TRAIN_* above.
    phase: str = TRAIN_PENDING
    #: Live member counts (this round).
    workers: int = 0
    ready_workers: int = 0
    succeeded_workers: int = 0
    #: rank (as string — JSON object keys) -> Pending|Running|
    #: Succeeded|Failed|Missing. The per-rank view ``ktl describe
    #: trainjob`` renders.
    worker_states: dict[str, str] = field(default_factory=dict)
    #: Completed gang restart rounds (member kill -> teardown ->
    #: recreate). Durable: counted exactly once per round, rides the
    #: WAL so a controller crash can never double-count a round.
    restart_rounds: int = 0
    #: Rounds that found a checkpoint to resume from (vs restart from
    #: scratch).
    resumes: int = 0
    #: Highest completed checkpoint step observed (marker or PodGroup
    #: preemption state); -1 = none yet. Monotonic.
    last_checkpoint_step: int = -1
    start_time: Optional[datetime.datetime] = None
    completion_time: Optional[datetime.datetime] = None
    #: Operator-facing note for the last transition (round restarts,
    #: failure reasons).
    message: str = ""


@dataclass
class TrainJob(TypedObject):
    spec: TrainJobSpec = field(default_factory=TrainJobSpec)
    status: TrainJobStatus = field(default_factory=TrainJobStatus)


def worker_chips(spec: TrainJobSpec) -> int:
    """Chips one worker claims: the slice shape's volume when shaped,
    else the flat count."""
    if spec.slice_shape:
        return math.prod(int(d) for d in spec.slice_shape)
    return spec.chips_per_worker


def coord_port(spec: TrainJobSpec) -> int:
    return spec.coord_port or DEFAULT_COORD_PORT


def checkpoint_every(spec: TrainJobSpec) -> int:
    return spec.checkpoint.every_steps or 10


def total_steps(spec: TrainJobSpec) -> int:
    return spec.total_steps or 100


def validate_trainjob(tj: TrainJob, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(tj.metadata, errs)
    s = tj.spec
    # Shape/type guard FIRST: the scheme passes unknown-typed JSON
    # values through untouched, and a string where an int belongs must
    # become a field error here — not a ValueError/TypeError that the
    # server surfaces as a 500.
    for fname, v in (("num_workers", s.num_workers),
                     ("chips_per_worker", s.chips_per_worker),
                     ("total_steps", s.total_steps),
                     ("batch", s.batch), ("seq", s.seq),
                     ("coord_port", s.coord_port),
                     ("backoff_limit", s.backoff_limit),
                     ("min_workers", s.min_workers),
                     ("max_workers", s.max_workers)):
        if not isinstance(v, int) or isinstance(v, bool):
            errs.add(f"spec.{fname}", f"must be an integer (got {v!r})")
    for fname, v in (("cpu_per_worker", s.cpu_per_worker),
                     ("checkpoint.every_steps", s.checkpoint.every_steps),
                     ("checkpoint.grace_seconds",
                      s.checkpoint.grace_seconds)):
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            errs.add(f"spec.{fname}", f"must be a number (got {v!r})")
    if s.priority is not None and (not isinstance(s.priority, int)
                                   or isinstance(s.priority, bool)):
        # Flows verbatim into PodGroup.spec.priority, which the
        # fair-share sort negates — a string here would wedge
        # admission for the whole queue, not just this job.
        errs.add("spec.priority", f"must be an integer or null "
                                  f"(got {s.priority!r})")
    for fname, v in (("model", s.model), ("queue", s.queue),
                     ("image", s.image)):
        if not isinstance(v, str):
            errs.add(f"spec.{fname}", f"must be a string (got {v!r})")
    for fname, shape in (("slice_shape", s.slice_shape),
                         ("gang_slice_shape", s.gang_slice_shape)):
        for d in shape:
            if not isinstance(d, int) or isinstance(d, bool):
                errs.add(f"spec.{fname}",
                         f"dimension {d!r} must be an integer")
    for k, v in s.args.items():
        # args become process env verbatim; a numeric JSON value
        # (args: {"STEP_DELAY": 0.3}) would crash every worker at
        # spawn (subprocess env must be str->str) and burn the whole
        # backoff budget on recovery rounds.
        if not isinstance(k, str) or not isinstance(v, str):
            errs.add("spec.args",
                     f"{k!r}: keys and values must be strings "
                     f"(quote numbers: \"0.3\")")
    errs.raise_if_any("TrainJob", tj.metadata.name)
    if s.model not in ("lm", "demo"):
        # Reject at admission: an unknown model would pass every layer,
        # rendezvous the full gang, crash, and burn the whole backoff
        # budget on recovery rounds before failing.
        errs.add("spec.model",
                 f"must be one of 'lm', 'demo' (got {s.model!r})")
    if s.num_workers < 1:
        errs.add("spec.num_workers", "must be >= 1")
    if s.chips_per_worker < 0:
        errs.add("spec.chips_per_worker", "must be >= 0")
    for fname, shape in (("slice_shape", s.slice_shape),
                         ("gang_slice_shape", s.gang_slice_shape)):
        for d in shape:
            if d <= 0:
                errs.add(f"spec.{fname}", f"dimension {d!r} must be > 0")
    if s.slice_shape and s.chips_per_worker and \
            worker_chips(s) != s.chips_per_worker:
        errs.add("spec.chips_per_worker",
                 f"contradicts slice_shape volume {worker_chips(s)} "
                 f"(set one; the shape wins when both are given)")
    if s.cpu_per_worker < 0 or not math.isfinite(s.cpu_per_worker):
        errs.add("spec.cpu_per_worker", "must be finite and >= 0")
    if s.total_steps < 0:
        errs.add("spec.total_steps", "must be >= 0 (0 = default)")
    if s.batch < 0 or s.seq < 0:
        errs.add("spec.batch", "batch/seq must be >= 0 (0 = default)")
    if s.coord_port < 0 or s.coord_port > 65535:
        errs.add("spec.coord_port", "must be a port number")
    if s.backoff_limit < 0:
        errs.add("spec.backoff_limit", "must be >= 0")
    ck = s.checkpoint
    if ck.every_steps < 0:
        errs.add("spec.checkpoint.every_steps", "must be >= 0 (0 = default)")
    if not math.isfinite(ck.grace_seconds) or ck.grace_seconds < 0:
        errs.add("spec.checkpoint.grace_seconds", "must be finite and >= 0")
    if s.min_workers or s.max_workers:
        if not 1 <= s.min_workers <= s.max_workers:
            errs.add("spec.min_workers",
                     "elastic sizing needs 1 <= min_workers <= max_workers")
        elif s.max_workers != s.num_workers:
            errs.add("spec.max_workers",
                     f"must equal num_workers ({s.num_workers}) — the gang "
                     f"is created at full size and shrinks elastically")
    errs.raise_if_any("TrainJob", tj.metadata.name)


def validate_trainjob_update(new: TrainJob, old: TrainJob) -> None:
    validate_trainjob(new, is_create=False)
    from .errors import InvalidError
    if (new.spec.num_workers != old.spec.num_workers
            or new.spec.slice_shape != old.spec.slice_shape
            or new.spec.chips_per_worker != old.spec.chips_per_worker):
        # Reshaping a live gang would mix ranks with different world
        # sizes behind one rendezvous; require delete/recreate (the
        # Kubeflow operators treat replica counts the same way).
        raise InvalidError(
            f"TrainJob {new.metadata.name!r}: gang geometry "
            f"(spec.num_workers / per-worker chip demand) is immutable "
            f"(delete and recreate to reshape)")
    if (new.spec.gang_slice_shape != old.spec.gang_slice_shape
            or new.spec.queue != old.spec.queue
            or new.spec.priority != old.spec.priority
            or new.spec.min_workers != old.spec.min_workers
            or new.spec.max_workers != old.spec.max_workers
            or new.spec.checkpoint.grace_seconds
            != old.spec.checkpoint.grace_seconds):
        # These pass through into the PodGroup at creation and are
        # never re-reconciled into a live group — accepting an edit
        # here would silently do nothing (honest contract: refuse).
        raise InvalidError(
            f"TrainJob {new.metadata.name!r}: PodGroup passthrough "
            f"fields (gang_slice_shape/queue/priority/min_workers/"
            f"max_workers/checkpoint.grace_seconds) are immutable "
            f"(delete and recreate to change gang placement)")
    if new.spec.checkpoint.pvc != old.spec.checkpoint.pvc:
        # The resolved volume path is frozen into every worker's env
        # (and cached controller-side); repointing a live job would
        # split checkpoints across volumes and break resume.
        raise InvalidError(
            f"TrainJob {new.metadata.name!r}: spec.checkpoint.pvc is "
            f"immutable (delete and recreate to move the checkpoint "
            f"volume)")
    from dataclasses import replace
    if replace(new.spec, backoff_limit=old.spec.backoff_limit) \
            != old.spec:
        # Everything else (model, training knobs, args, coord_port,
        # image, cpu) is frozen into each worker pod's env/spec at
        # creation: a single-rank recreate after an edit would desync
        # the gang (wrong port, wrong step count, wrong model tree).
        # Only the restart budget is a pure controller-side knob.
        raise InvalidError(
            f"TrainJob {new.metadata.name!r}: spec is immutable except "
            f"spec.backoff_limit (worker env is frozen at pod "
            f"creation; delete and recreate to change the workload)")


DEFAULT_SCHEME.register(TRAINING_V1, "TrainJob", TrainJob)
