"""Workload API groups (apps/v1, batch/v1, autoscaling/v1).

Reference: ``staging/src/k8s.io/api/{apps,batch,autoscaling}/v1`` types
backing the controllers in ``pkg/controller/{deployment,replicaset,
statefulset,daemon,job,cronjob,podautoscaler}``.

TPU-first additions: ``JobSpec.gang`` creates a PodGroup so a
distributed training Job is placed all-or-nothing on one contiguous
sub-mesh (no reference analog — SURVEY.md section 2.4).
"""
from __future__ import annotations

import datetime
from dataclasses import dataclass, field
from typing import Optional

from .meta import TypedObject
from .scheme import DEFAULT_SCHEME
from .selectors import LabelSelector
from .types import PersistentVolumeClaim, PodTemplateSpec

APPS_V1 = "apps/v1"
BATCH_V1 = "batch/v1"
AUTOSCALING_V1 = "autoscaling/v1"


# ---------------------------------------------------------------------------
# ReplicaSet / Deployment
# ---------------------------------------------------------------------------


@dataclass
class ReplicaSetSpec:
    replicas: int = 1
    min_ready_seconds: int = 0
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class ReplicaSetStatus:
    replicas: int = 0
    fully_labeled_replicas: int = 0
    ready_replicas: int = 0
    available_replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicaSet(TypedObject):
    spec: ReplicaSetSpec = field(default_factory=ReplicaSetSpec)
    status: ReplicaSetStatus = field(default_factory=ReplicaSetStatus)


ROLLING_UPDATE = "RollingUpdate"
RECREATE = "Recreate"


@dataclass
class RollingUpdateDeployment:
    #: ints (pod counts) or strings like "25%".
    max_unavailable: str = "25%"
    max_surge: str = "25%"


@dataclass
class DeploymentStrategy:
    type: str = ROLLING_UPDATE
    rolling_update: RollingUpdateDeployment = field(default_factory=RollingUpdateDeployment)


@dataclass
class DeploymentSpec:
    replicas: int = 1
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    strategy: DeploymentStrategy = field(default_factory=DeploymentStrategy)
    min_ready_seconds: int = 0
    revision_history_limit: int = 10
    paused: bool = False


@dataclass
class DeploymentCondition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""
    last_transition_time: Optional[datetime.datetime] = None


@dataclass
class DeploymentStatus:
    observed_generation: int = 0
    replicas: int = 0
    updated_replicas: int = 0
    ready_replicas: int = 0
    available_replicas: int = 0
    unavailable_replicas: int = 0
    conditions: list[DeploymentCondition] = field(default_factory=list)


@dataclass
class Deployment(TypedObject):
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)


# ---------------------------------------------------------------------------
# StatefulSet — ranked identity for distributed workers
# ---------------------------------------------------------------------------


@dataclass
class StatefulSetSpec:
    replicas: int = 1
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    #: Headless service giving pods stable DNS names (rank identity).
    service_name: str = ""
    pod_management_policy: str = "OrderedReady"  # or "Parallel"
    update_strategy: str = ROLLING_UPDATE
    #: Per-replica stable storage (reference: volumeClaimTemplates):
    #: each template yields a PVC named <template>-<set>-<ordinal>,
    #: mounted into the pod as a volume of the template's name. Claims
    #: are NOT owner-referenced — they outlive pods AND the set (the
    #: whole point of stable storage; deletion is an operator act).
    volume_claim_templates: list[PersistentVolumeClaim] = field(
        default_factory=list)


@dataclass
class StatefulSetStatus:
    observed_generation: int = 0
    replicas: int = 0
    ready_replicas: int = 0
    current_replicas: int = 0
    updated_replicas: int = 0
    #: Revision bookkeeping (reference: currentRevision/updateRevision):
    #: current is promoted to update once the rollout completes.
    current_revision: str = ""
    update_revision: str = ""


@dataclass
class StatefulSet(TypedObject):
    spec: StatefulSetSpec = field(default_factory=StatefulSetSpec)
    status: StatefulSetStatus = field(default_factory=StatefulSetStatus)


# ---------------------------------------------------------------------------
# DaemonSet — device plugins, metrics exporters run as these
# ---------------------------------------------------------------------------


@dataclass
class DaemonSetSpec:
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    update_strategy: str = ROLLING_UPDATE
    min_ready_seconds: int = 0


@dataclass
class DaemonSetStatus:
    desired_number_scheduled: int = 0
    current_number_scheduled: int = 0
    number_misscheduled: int = 0
    number_ready: int = 0
    number_available: int = 0
    observed_generation: int = 0


@dataclass
class DaemonSet(TypedObject):
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)
    status: DaemonSetStatus = field(default_factory=DaemonSetStatus)


# ---------------------------------------------------------------------------
# Job / CronJob — gang-aware batch
# ---------------------------------------------------------------------------


@dataclass
class GangPolicy:
    """TPU-first: run this Job as a gang on one contiguous sub-mesh."""

    #: Pods that must be co-scheduled; defaults to parallelism.
    min_member: int = 0
    #: Slice shape for the whole gang (chips), e.g. [4,4,4] for v5p-64.
    slice_shape: list[int] = field(default_factory=list)
    schedule_timeout_seconds: int = 0
    #: LocalQueue the Job's PodGroup is admitted through (queueing/v1
    #: fair-share admission; "" = unqueued, or the namespace default
    #: LocalQueue when the JobQueueing gate is on).
    queue: str = ""
    #: Graceful-preemption opt-in for the Job's gang (seconds the
    #: workload gets to checkpoint when preempted/reclaimed; 0 = the
    #: legacy hard kill). Carried into PodGroup.spec.checkpoint.
    checkpoint_grace_seconds: float = 0.0
    #: Elastic sizing carried into PodGroup.spec.min/max_replicas
    #: (0/0 = fixed-size gang).
    min_replicas: int = 0
    max_replicas: int = 0


@dataclass
class JobSpec:
    parallelism: int = 1
    completions: Optional[int] = None
    active_deadline_seconds: Optional[int] = None
    backoff_limit: int = 6
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    #: Completion index env var injected per pod (stable ranks).
    completion_mode: str = "Indexed"  # Indexed | NonIndexed
    gang: Optional[GangPolicy] = None


@dataclass
class JobCondition:
    type: str = ""  # Complete | Failed
    status: str = ""
    reason: str = ""
    message: str = ""
    last_transition_time: Optional[datetime.datetime] = None


@dataclass
class JobStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    start_time: Optional[datetime.datetime] = None
    completion_time: Optional[datetime.datetime] = None
    conditions: list[JobCondition] = field(default_factory=list)
    #: Durable progress accounting: terminal pods are counted exactly once
    #: by UID, so force-deleting their records (pod GC, gang teardown)
    #: cannot rewind succeeded/failed. Kubernetes moved to finalizer-based
    #: tracking for the same reason; persisting in status is the
    #: API-object-as-checkpoint move (SURVEY.md section 5.4).
    counted_succeeded_uids: list[str] = field(default_factory=list)
    counted_failed_uids: list[str] = field(default_factory=list)
    #: Indexed mode: indexes that have completed (stable across pod GC).
    completed_indexes: list[int] = field(default_factory=list)


@dataclass
class Job(TypedObject):
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)


@dataclass
class CronJobSpec:
    schedule: str = ""  # 5-field cron
    suspend: bool = False
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    starting_deadline_seconds: Optional[int] = None
    job_template: JobSpec = field(default_factory=JobSpec)
    successful_jobs_history_limit: int = 3
    failed_jobs_history_limit: int = 1


@dataclass
class CronJobStatus:
    active: list[str] = field(default_factory=list)  # job names
    last_schedule_time: Optional[datetime.datetime] = None


@dataclass
class CronJob(TypedObject):
    spec: CronJobSpec = field(default_factory=CronJobSpec)
    status: CronJobStatus = field(default_factory=CronJobStatus)


# ---------------------------------------------------------------------------
# HorizontalPodAutoscaler (reference: pkg/controller/podautoscaler)
# ---------------------------------------------------------------------------


@dataclass
class CrossVersionObjectReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""


@dataclass
class HorizontalPodAutoscalerSpec:
    scale_target_ref: CrossVersionObjectReference = field(
        default_factory=CrossVersionObjectReference
    )
    min_replicas: int = 1
    max_replicas: int = 1
    target_cpu_utilization_percentage: int = 80


@dataclass
class HorizontalPodAutoscalerStatus:
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization_percentage: Optional[int] = None
    last_scale_time: Optional[datetime.datetime] = None


@dataclass
class HorizontalPodAutoscaler(TypedObject):
    spec: HorizontalPodAutoscalerSpec = field(default_factory=HorizontalPodAutoscalerSpec)
    status: HorizontalPodAutoscalerStatus = field(default_factory=HorizontalPodAutoscalerStatus)


@dataclass
class PodDisruptionBudgetSpec:
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None
    selector: Optional[LabelSelector] = None


@dataclass
class PodDisruptionBudgetStatus:
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0
    #: Controller's view of the generation its numbers were computed
    #: from — the eviction subresource refuses (429) while stale
    #: (reference: eviction.go checkAndDecrement observedGeneration).
    observed_generation: int = 0
    #: pod name -> RFC3339 eviction-approved time. The eviction
    #: handler records approved-but-not-yet-deleted pods here so the
    #: disruption controller excludes them from current_healthy until
    #: they actually go (or the entry times out, ~2min — crashed
    #: deleters must not pin the budget). eviction.go DisruptedPods.
    disrupted_pods: dict[str, str] = field(default_factory=dict)


@dataclass
class PodDisruptionBudget(TypedObject):
    spec: PodDisruptionBudgetSpec = field(default_factory=PodDisruptionBudgetSpec)
    status: PodDisruptionBudgetStatus = field(default_factory=PodDisruptionBudgetStatus)


for _kind, _cls, _gv in [
    ("ReplicaSet", ReplicaSet, APPS_V1),
    ("Deployment", Deployment, APPS_V1),
    ("StatefulSet", StatefulSet, APPS_V1),
    ("DaemonSet", DaemonSet, APPS_V1),
    ("Job", Job, BATCH_V1),
    ("CronJob", CronJob, BATCH_V1),
    ("HorizontalPodAutoscaler", HorizontalPodAutoscaler, AUTOSCALING_V1),
    ("PodDisruptionBudget", PodDisruptionBudget, "policy/v1"),
]:
    DEFAULT_SCHEME.register(_gv, _kind, _cls)
