"""Strategic merge patch — list-aware patching with merge keys.

Reference: ``apimachinery/pkg/util/strategicpatch`` — unlike RFC 7386
JSON merge-patch (which replaces lists wholesale), a strategic patch
merges lists of objects by a per-type **merge key** (containers by
name, taints by key, conditions by type...), so a patch touching one
container does not clobber its siblings. The reference reads merge keys
from struct tags; here they live in :data:`MERGE_KEYS`, keyed by the
dataclass element type, and the patcher walks the typed object model
(``typing`` hints) alongside the raw dicts.

Directives (same wire format as the reference):

- ``{"$patch": "delete", <mergeKey>: v}`` in a list removes the element;
- ``{"$patch": "replace"}`` as a list element replaces the whole list
  with the patch's remaining elements;
- ``null`` values delete map keys (as in merge-patch).
"""
from __future__ import annotations

import dataclasses
import typing
from typing import Any, Optional, get_args, get_origin, get_type_hints

from . import types as t

#: element dataclass -> field acting as the merge key.
MERGE_KEYS: dict[type, str] = {
    t.Container: "name",
    t.EnvVar: "name",
    t.EnvFromSource: "config_map_ref",
    t.ContainerPort: "container_port",
    t.Volume: "name",
    t.VolumeMount: "mount_path",
    t.Taint: "key",
    t.Toleration: "key",
    t.NodeCondition: "type",
    t.PodCondition: "type",
    t.ServicePort: "port",
    t.PodTpuRequest: "name",
    t.NodeAddress: "type",
}

_DIRECTIVE = "$patch"


def _element_type(cls: type, field_name: str) -> Optional[type]:
    """Element dataclass of a ``list[...]`` field, else None."""
    try:
        hints = get_type_hints(cls)
    except Exception:  # noqa: BLE001 — unresolvable hints = atomic
        return None
    hint = hints.get(field_name)
    if hint is None:
        return None
    if get_origin(hint) is list:
        (elem,) = get_args(hint) or (None,)
        return elem if dataclasses.is_dataclass(elem) else None
    return None


def _field_type(cls: type, field_name: str) -> Optional[type]:
    """Nested dataclass type of a field (unwrapping Optional)."""
    try:
        hints = get_type_hints(cls)
    except Exception:  # noqa: BLE001
        return None
    hint = hints.get(field_name)
    if hint is None:
        return None
    if get_origin(hint) is typing.Union:
        args = [a for a in get_args(hint) if a is not type(None)]
        hint = args[0] if len(args) == 1 else None
    return hint if dataclasses.is_dataclass(hint) else None


def strategic_merge(base: Any, patch: Any, cls: Optional[type]) -> Any:
    """Merge ``patch`` into ``base`` (plain dicts/lists/scalars), guided
    by the dataclass ``cls`` describing ``base``'s shape."""
    if isinstance(patch, dict) and isinstance(base, dict):
        out = dict(base)
        for key, pval in patch.items():
            if pval is None:
                out.pop(key, None)
                continue
            bval = out.get(key)
            if isinstance(pval, list) and cls is not None:
                elem = _element_type(cls, key)
                mk = MERGE_KEYS.get(elem) if elem else None
                if mk is not None and isinstance(bval, list):
                    out[key] = _merge_list(bval, pval, elem, mk)
                    continue
            if isinstance(pval, dict):
                sub = _field_type(cls, key) if cls is not None else None
                out[key] = strategic_merge(bval if isinstance(bval, dict)
                                           else {}, pval, sub)
                continue
            out[key] = pval
        return out
    return patch


def _merge_list(base: list, patch: list, elem: type, merge_key: str) -> list:
    out = [dict(item) if isinstance(item, dict) else item for item in base]
    for pitem in patch:
        if not isinstance(pitem, dict):
            return patch  # scalar elements: replace wholesale
        directive = pitem.get(_DIRECTIVE)
        if directive == "replace":
            # Remaining patch elements become the list.
            return [p for p in patch
                    if not (isinstance(p, dict) and p.get(_DIRECTIVE))]
        key_val = pitem.get(merge_key)
        if directive == "delete":
            out = [item for item in out
                   if not (isinstance(item, dict)
                           and item.get(merge_key) == key_val)]
            continue
        if key_val is None:
            out.append({k: v for k, v in pitem.items() if k != _DIRECTIVE})
            continue
        for i, item in enumerate(out):
            if isinstance(item, dict) and item.get(merge_key) == key_val:
                out[i] = strategic_merge(item, pitem, elem)
                break
        else:
            out.append({k: v for k, v in pitem.items() if k != _DIRECTIVE})
    return out


#: Wire content types (reference: types.go PatchType).
MERGE_PATCH = "application/merge-patch+json"
STRATEGIC_MERGE_PATCH = "application/strategic-merge-patch+json"
JSON_PATCH = "application/json-patch+json"  # RFC 6902, body is a list
