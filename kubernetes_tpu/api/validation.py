"""API validation — reject malformed objects before they hit the store.

Reference: ``pkg/apis/core/validation/validation.go`` (~4.8k lines),
incl. the fork's extended-resource validation (``:2457,2883-2888,2950``:
claim names unique, container references resolve, assigned IDs only via
binding). Field errors accumulate into one Invalid error with a path
list, like the reference's ``field.ErrorList``.
"""
from __future__ import annotations

import math
import re
from typing import Optional

from . import rbac as rb
from . import types as t
from . import workloads as w
from .errors import InvalidError

# DNS-1123: what object names must look like.
_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$")
_LABEL_KEY_RE = re.compile(r"^([a-z0-9A-Z][-a-z0-9A-Z_.]*)?[a-z0-9A-Z](/([a-z0-9A-Z][-a-z0-9A-Z_.]*)?[a-z0-9A-Z])?$")
_LABEL_VAL_RE = re.compile(r"^([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9]$|^$")
MAX_NAME_LEN = 253


class ErrorList:
    def __init__(self) -> None:
        self.errors: list[str] = []

    def add(self, path: str, msg: str) -> None:
        self.errors.append(f"{path}: {msg}")

    def raise_if_any(self, kind: str, name: str) -> None:
        if self.errors:
            raise InvalidError(
                f"{kind} {name!r} is invalid: " + "; ".join(self.errors),
                details={"errors": self.errors},
            )


def validate_name(name: str, path: str, errs: ErrorList, required: bool = True) -> None:
    if not name:
        if required:
            errs.add(path, "name is required")
        return
    if len(name) > MAX_NAME_LEN:
        errs.add(path, f"must be <= {MAX_NAME_LEN} chars")
    if not _NAME_RE.match(name):
        errs.add(path, "must be DNS-1123: lowercase alphanumerics, '-', '.'")


def validate_labels(labels: dict, path: str, errs: ErrorList) -> None:
    for k, v in labels.items():
        if not _LABEL_KEY_RE.match(k) or len(k) > 317:
            errs.add(f"{path}.{k}", "invalid label key")
        if not _LABEL_VAL_RE.match(str(v)) or len(str(v)) > 63:
            errs.add(f"{path}.{k}", "invalid label value")


def validate_object_meta(meta, errs: ErrorList, namespaced: bool = True, path: str = "metadata") -> None:
    if not meta.name and not meta.generate_name:
        errs.add(f"{path}.name", "name or generate_name is required")
    if meta.name:
        validate_name(meta.name, f"{path}.name", errs)
    if namespaced and meta.namespace:
        validate_name(meta.namespace, f"{path}.namespace", errs)
    if not namespaced and meta.namespace:
        errs.add(f"{path}.namespace", "cluster-scoped object must not set namespace")
    validate_labels(meta.labels, f"{path}.labels", errs)


# ---------------------------------------------------------------------------
# Pods
# ---------------------------------------------------------------------------


def _validate_container(c: t.Container, claim_names: set, path: str, errs: ErrorList) -> None:
    validate_name(c.name, f"{path}.name", errs)
    if not c.image and not c.command:
        errs.add(f"{path}.image", "image or command is required")
    for i, p in enumerate(c.ports):
        if not (0 < p.container_port < 65536):
            errs.add(f"{path}.ports[{i}]", "container_port must be 1-65535")
    for q in c.tpu_requests:
        # Fork analog: validation.go:2883-2888 — container references
        # must resolve to a declared pod-level claim.
        if q not in claim_names:
            errs.add(f"{path}.tpu_requests", f"no pod tpu_resources entry named {q!r}")
    for k, v in {**c.resources.requests, **c.resources.limits}.items():
        try:
            if t.parse_quantity(v) < 0:
                errs.add(f"{path}.resources.{k}", "must be non-negative")
        except ValueError:
            errs.add(f"{path}.resources.{k}", f"unparseable quantity {v!r}")
    for probe_name in ("liveness_probe", "readiness_probe"):
        probe = getattr(c, probe_name, None)
        if probe is None:
            continue
        http = getattr(probe, "http_get", None)
        if http is not None and not (0 < http.port < 65536):
            errs.add(f"{path}.{probe_name}.http_get.port",
                     "port must be 1-65535")
        if probe.tcp_port and not (0 < probe.tcp_port < 65536):
            errs.add(f"{path}.{probe_name}.tcp_port",
                     "port must be 1-65535")


_PATH_SEGMENT_BAD = set("/%")


def validate_meta_generic(meta, namespaced: bool,
                          path_segment_name: bool = False) -> None:
    """Meta validation applied by the registry to EVERY kind
    (reference: ValidateObjectMeta runs on all object paths, not just
    kinds with bespoke validators). Delegates to
    :func:`validate_object_meta` — one definition of the rules — with
    the name-charset check swapped for path-segment rules when
    ``path_segment_name`` (RBAC-style names like "system:node";
    validation.go ValidatePathSegmentName). Runs AFTER stamp_new, so
    generate_name is already resolved and a missing name is an error.
    """
    errs = ErrorList()
    if path_segment_name:
        name = meta.name
        if not name:
            errs.add("metadata.name", "name is required")
        elif (name in (".", "..")
              or any(c in _PATH_SEGMENT_BAD for c in name)):
            errs.add("metadata.name",
                     "may not be '.', '..' or contain '/' or '%'")
        elif len(name) > MAX_NAME_LEN:
            errs.add("metadata.name", f"must be <= {MAX_NAME_LEN} chars")
        if namespaced and meta.namespace:
            validate_name(meta.namespace, "metadata.namespace", errs)
        if not namespaced and meta.namespace:
            errs.add("metadata.namespace",
                     "cluster-scoped object must not set namespace")
        validate_labels(meta.labels, "metadata.labels", errs)
    else:
        validate_object_meta(meta, errs, namespaced=namespaced)
    for k in meta.annotations:
        if not k or len(k) > 317:
            errs.add(f"metadata.annotations.{k!r}", "invalid annotation key")
    errs.raise_if_any(type(meta).__name__, meta.name)


def validate_pod(pod: t.Pod, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(pod.metadata, errs)
    if not pod.spec.containers:
        errs.add("spec.containers", "at least one container is required")
    claim_names = {r.name for r in pod.spec.tpu_resources}
    if len(claim_names) != len(pod.spec.tpu_resources):
        errs.add("spec.tpu_resources", "claim names must be unique")  # validation.go:2457
    # Volumes: unique names, exactly one source each; every mount must
    # reference a declared volume (validation.go ValidateVolumes +
    # ValidateVolumeMounts — the cross-ref the r3 verdict called thin).
    vol_names = set()
    for i, v in enumerate(pod.spec.volumes):
        validate_name(v.name, f"spec.volumes[{i}].name", errs)
        if v.name in vol_names:
            errs.add(f"spec.volumes[{i}].name",
                     f"duplicate volume name {v.name!r}")
        vol_names.add(v.name)
        sources = [s for s in (v.host_path, v.empty_dir, v.config_map,
                               v.secret, v.persistent_volume_claim)
                   if s is not None]
        if len(sources) > 1:
            errs.add(f"spec.volumes[{i}]",
                     "may not specify more than one volume source")
        elif not sources:
            errs.add(f"spec.volumes[{i}]",
                     "exactly one volume source is required")
    seen = set()
    n_main = len(pod.spec.containers)
    for i, c in enumerate(pod.spec.containers + pod.spec.init_containers):
        cpath = (f"spec.containers[{i}]" if i < n_main
                 else f"spec.init_containers[{i - n_main}]")
        if c.name in seen:
            errs.add(f"{cpath}.name", f"duplicate container name {c.name!r}")
        seen.add(c.name)
        _validate_container(c, claim_names, cpath, errs)
        for j, vm in enumerate(c.volume_mounts):
            if vm.name not in vol_names:
                errs.add(f"{cpath}.volume_mounts[{j}].name",
                         f"no spec.volumes entry named {vm.name!r}")
            if not vm.mount_path:
                errs.add(f"{cpath}.volume_mounts[{j}].mount_path",
                         "mount_path is required")
    if pod.spec.restart_policy not in (t.RESTART_ALWAYS, t.RESTART_ON_FAILURE, t.RESTART_NEVER):
        errs.add("spec.restart_policy", f"unknown policy {pod.spec.restart_policy!r}")
    # Security contexts: uids/gids must be sane; run_as_non_root with
    # an explicit root uid is self-contradictory (validation.go
    # ValidateSecurityContext).
    sec_ctxs = []
    if pod.spec.security_context is not None:
        sec_ctxs.append(("spec.security_context",
                         pod.spec.security_context))
        fsg = pod.spec.security_context.fs_group
        if fsg is not None and fsg < 0:
            errs.add("spec.security_context.fs_group",
                     "must be non-negative")
    for i, c in enumerate(pod.spec.containers + pod.spec.init_containers):
        if c.security_context is not None:
            sec_ctxs.append((f"containers[{c.name}].security_context",
                             c.security_context))
    for path, sc in sec_ctxs:
        for fname in ("run_as_user", "run_as_group"):
            v = getattr(sc, fname)
            if v is not None and v < 0:
                errs.add(f"{path}.{fname}", "must be non-negative")
        if sc.run_as_non_root and sc.run_as_user == 0:
            errs.add(f"{path}", "run_as_non_root with run_as_user=0 "
                                "is contradictory")
    aff = pod.spec.affinity
    if aff is not None:
        # REQUIRED inter-pod terms need a selector and a topology key
        # (validation.go ValidatePodAffinityTerm) — a selector-less
        # required term would match nothing and wedge the pod forever.
        # Preferred (soft) terms without a selector are a harmless
        # zero-score no-op and stay legal, but still need a topology
        # key (the reference validates it for weighted terms too — a
        # keyless soft term silently scores zero everywhere).
        required = ([("spec.affinity.pod_affinity", tm)
                     for tm in aff.pod_affinity]
                    + [("spec.affinity.pod_anti_affinity", tm)
                       for tm in aff.pod_anti_affinity])
        soft = ([("spec.affinity.pod_affinity_preferred", wt.pod_affinity_term)
                 for wt in aff.pod_affinity_preferred]
                + [("spec.affinity.pod_anti_affinity_preferred",
                    wt.pod_affinity_term)
                   for wt in aff.pod_anti_affinity_preferred])
        for path, term in required:
            if term.label_selector is None:
                errs.add(path, "label_selector is required")
        for path, term in required + soft:
            if not term.topology_key:
                errs.add(path, "topology_key is required")
    for i, r in enumerate(pod.spec.tpu_resources):
        if not r.name:
            errs.add(f"spec.tpu_resources[{i}].name", "name is required")
        if r.chips < 0:
            errs.add(f"spec.tpu_resources[{i}].chips", "must be non-negative")
        if r.slice_shape and any(d <= 0 for d in r.slice_shape):
            errs.add(f"spec.tpu_resources[{i}].slice_shape", "dims must be positive")
        if r.slice_shape and len(r.slice_shape) > 3:
            errs.add(f"spec.tpu_resources[{i}].slice_shape", "at most 3 dims")
        if is_create and r.assigned:
            # Fork analog: validation.go:2950 — only the binding
            # subresource may write assignments.
            errs.add(f"spec.tpu_resources[{i}].assigned", "cannot be set on create")
    errs.raise_if_any("Pod", pod.metadata.name)


def validate_pod_update(new: t.Pod, old: t.Pod) -> None:
    errs = ErrorList()
    # Spec is mostly immutable after creation (reference semantics);
    # node_name may only transition empty -> set (via binding).
    if old.spec.node_name and new.spec.node_name != old.spec.node_name:
        errs.add("spec.node_name", "is immutable once set")
    if len(new.spec.containers) != len(old.spec.containers):
        errs.add("spec.containers", "may not add or remove containers")
    # TPU claims are immutable through the normal update path; chip
    # assignments are written only by the binding subresource, which
    # goes straight to storage (fork analog: validation.go:2950 +
    # pkg/registry/core/pod/storage/storage.go:154).
    old_claims = {r.name: r for r in old.spec.tpu_resources}
    new_claims = {r.name: r for r in new.spec.tpu_resources}
    if set(old_claims) != set(new_claims):
        errs.add("spec.tpu_resources", "claims may not be added or removed")
    else:
        for name, nr in new_claims.items():
            o = old_claims[name]
            if nr.assigned != o.assigned:
                errs.add(f"spec.tpu_resources[{name}].assigned",
                         "may only be written via the binding subresource")
            if (nr.chips, nr.slice_shape, nr.resource) != (o.chips, o.slice_shape, o.resource):
                errs.add(f"spec.tpu_resources[{name}]", "claim shape is immutable")
    if new.spec.gang != old.spec.gang:
        errs.add("spec.gang", "is immutable")
    errs.raise_if_any("Pod", new.metadata.name)


# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------


def validate_node(node: t.Node, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(node.metadata, errs, namespaced=False)
    for i, taint in enumerate(node.spec.taints):
        if taint.effect not in (t.TAINT_NO_SCHEDULE, t.TAINT_PREFER_NO_SCHEDULE, t.TAINT_NO_EXECUTE):
            errs.add(f"spec.taints[{i}].effect", f"unknown effect {taint.effect!r}")
        if not taint.key:
            errs.add(f"spec.taints[{i}].key", "key is required")
    topo = node.status.tpu
    if topo is not None:
        ids = [c.id for c in topo.chips]
        if len(set(ids)) != len(ids):
            errs.add("status.tpu.chips", "chip ids must be unique")
        if topo.mesh_shape and any(d <= 0 for d in topo.mesh_shape):
            errs.add("status.tpu.mesh_shape", "dims must be positive")
        for i, chip in enumerate(topo.chips):
            if topo.mesh_shape and len(chip.coords) != len(topo.mesh_shape):
                errs.add(f"status.tpu.chips[{i}].coords", "rank must match mesh_shape")
    errs.raise_if_any("Node", node.metadata.name)


# ---------------------------------------------------------------------------
# Workloads
# ---------------------------------------------------------------------------


def _validate_template_matches(selector, template, errs: ErrorList) -> None:
    if selector is None or selector.empty():
        errs.add("spec.selector", "selector is required and must be non-empty")
        return
    if not selector.matches(template.metadata.labels):
        errs.add("spec.template.metadata.labels", "must match spec.selector")


def validate_replicaset(rs: w.ReplicaSet, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(rs.metadata, errs)
    if rs.spec.replicas < 0:
        errs.add("spec.replicas", "must be non-negative")
    _validate_template_matches(rs.spec.selector, rs.spec.template, errs)
    errs.raise_if_any("ReplicaSet", rs.metadata.name)


def validate_deployment(d: w.Deployment, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(d.metadata, errs)
    if d.spec.replicas < 0:
        errs.add("spec.replicas", "must be non-negative")
    _validate_template_matches(d.spec.selector, d.spec.template, errs)
    if d.spec.strategy.type not in (w.ROLLING_UPDATE, w.RECREATE):
        errs.add("spec.strategy.type", f"unknown strategy {d.spec.strategy.type!r}")
    errs.raise_if_any("Deployment", d.metadata.name)


def validate_statefulset(s: w.StatefulSet, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(s.metadata, errs)
    if s.spec.replicas < 0:
        errs.add("spec.replicas", "must be non-negative")
    _validate_template_matches(s.spec.selector, s.spec.template, errs)
    errs.raise_if_any("StatefulSet", s.metadata.name)


def validate_job(j: w.Job, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(j.metadata, errs)
    if j.spec.parallelism < 0:
        errs.add("spec.parallelism", "must be non-negative")
    if j.spec.completions is not None and j.spec.completions < 0:
        errs.add("spec.completions", "must be non-negative")
    if j.spec.gang is not None:
        g = j.spec.gang
        if g.min_member < 0:
            errs.add("spec.gang.min_member", "must be non-negative")
        if g.slice_shape and any(d <= 0 for d in g.slice_shape):
            errs.add("spec.gang.slice_shape", "dims must be positive")
    errs.raise_if_any("Job", j.metadata.name)


def validate_podgroup(pg: t.PodGroup, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(pg.metadata, errs)
    if pg.spec.min_member < 1:
        errs.add("spec.min_member", "must be >= 1")
    if pg.spec.slice_shape and any(d <= 0 for d in pg.spec.slice_shape):
        errs.add("spec.slice_shape", "dims must be positive")
    if pg.spec.queue:
        validate_name(pg.spec.queue, "spec.queue", errs)
    validate_quota_map("spec.resources", pg.spec.resources, errs)
    ck = pg.spec.checkpoint
    if ck is not None:
        if not math.isfinite(ck.grace_seconds) or ck.grace_seconds < 0:
            errs.add("spec.checkpoint.grace_seconds",
                     "must be a finite number >= 0")
        if ck.signal not in t.PREEMPT_SIGNAL_MODES:
            errs.add("spec.checkpoint.signal",
                     f"must be one of {t.PREEMPT_SIGNAL_MODES}")
    mig = pg.status.migration
    if mig is not None:
        if mig.phase not in t.MIGRATE_PHASES:
            errs.add("status.migration.phase",
                     f"must be one of {t.MIGRATE_PHASES}")
        if mig.reason and mig.reason not in t.MIGRATE_REASONS:
            errs.add("status.migration.reason",
                     f"must be one of {t.MIGRATE_REASONS}")
        if mig.rounds < 0:
            errs.add("status.migration.rounds", "must be >= 0")
        if mig.phase and not mig.target_cells:
            # An open round without a recorded target box is
            # unrecoverable after a controller crash — the resume
            # sweep could neither re-carve nor verify the reservation.
            errs.add("status.migration.target_cells",
                     "required while a round is open")
    mn, mx = pg.spec.min_replicas, pg.spec.max_replicas
    if (mn == 0) != (mx == 0):
        errs.add("spec.min_replicas",
                 "min_replicas and max_replicas must be set together "
                 "(0 = non-elastic)")
    elif mx:
        if mn < 1 or mn > mx:
            errs.add("spec.min_replicas",
                     f"need 1 <= min_replicas <= max_replicas, got "
                     f"{mn}/{mx}")
        if pg.spec.min_member > mn:
            # The scheduler's quorum must be reachable at the shrunken
            # size, or a reclaim shrink would wedge the gang below its
            # own release threshold.
            errs.add("spec.min_member",
                     f"must be <= min_replicas ({mn}) on elastic gangs")
    errs.raise_if_any("PodGroup", pg.metadata.name)


def validate_quota_map(path: str, quotas: dict, errs: ErrorList) -> None:
    """Resource-name -> amount maps (PodGroup.spec.resources,
    ClusterQueue quotas): names non-empty strings, amounts non-negative
    numbers. Shared with api/queueing.py."""
    for res, amt in quotas.items():
        if not res or not isinstance(res, str):
            errs.add(path, f"resource name must be a non-empty string, "
                           f"got {res!r}")
        elif isinstance(amt, bool) or not isinstance(amt, (int, float)):
            errs.add(f"{path}[{res}]", f"must be a number, got {amt!r}")
        elif not math.isfinite(amt):
            # json.loads admits the NaN/Infinity literals; NaN compares
            # False against everything, so it would silently scramble
            # DRF ordering and headroom math instead of erroring.
            errs.add(f"{path}[{res}]", f"must be finite, got {amt!r}")
        elif amt < 0:
            errs.add(f"{path}[{res}]", "must be >= 0")


def validate_podgroup_update(new: t.PodGroup, old: t.PodGroup) -> None:
    """Queue binding and admitted demand are immutable: rewriting
    ``spec.queue`` would move the admission charge to a queue that
    never admitted the gang (bypassing its borrowing limits), and
    resizing ``spec.resources`` while admitted would silently free
    quota the gang still physically holds — the same accounting
    argument behind LocalQueue.spec.cluster_queue immutability.

    Gated on ``JobQueueing`` like the rest of admission: with the gate
    off nothing charges quota, so the immutability has nothing to
    protect — and it must not strand a stale ``spec.queue`` from an
    earlier gated run (gate off = byte-identical update semantics)."""
    validate_podgroup(new, is_create=False)
    from ..util.features import GATES
    if not GATES.enabled("JobQueueing"):
        return
    if new.spec.queue != old.spec.queue:
        raise InvalidError(
            f"PodGroup {new.metadata.name!r}: spec.queue is immutable "
            f"(delete and recreate to move queues)")
    if old.status.admitted and new.spec.resources != old.spec.resources:
        raise InvalidError(
            f"PodGroup {new.metadata.name!r}: spec.resources is immutable "
            f"while admitted (the quota charge would drift from what the "
            f"gang holds)")


_SERVICE_TYPES = ("ClusterIP", "NodePort", "LoadBalancer")
_PROTOCOLS = ("TCP", "UDP", "SCTP")
#: The reference's --service-node-port-range default
#: (``pkg/master/master.go`` DefaultServiceNodePortRange).
NODE_PORT_RANGE = (30000, 32767)


def _valid_ip(s: str) -> bool:
    import ipaddress
    try:
        ipaddress.ip_address(s)
        return True
    except ValueError:
        return False


def validate_service(svc: t.Service, is_create: bool = True) -> None:
    """Reference: ``validation.go ValidateService`` — port ranges and
    uniqueness, NodePort range, protocol/type enums, clusterIP syntax."""
    errs = ErrorList()
    validate_object_meta(svc.metadata, errs)
    if not svc.spec.ports:
        errs.add("spec.ports", "at least one port is required")
    names = set()
    for i, p in enumerate(svc.spec.ports):
        if not (0 < p.port < 65536):
            errs.add(f"spec.ports[{i}].port", "must be 1-65535")
        if p.target_port and not (0 < p.target_port < 65536):
            errs.add(f"spec.ports[{i}].target_port", "must be 1-65535")
        if p.protocol not in _PROTOCOLS:
            errs.add(f"spec.ports[{i}].protocol",
                     f"must be one of {_PROTOCOLS}")
        if len(svc.spec.ports) > 1:
            if not p.name:
                errs.add(f"spec.ports[{i}].name",
                         "required when more than one port is defined")
            elif p.name in names:
                errs.add(f"spec.ports[{i}].name", f"duplicate {p.name!r}")
            names.add(p.name)
        if p.node_port:
            lo, hi = NODE_PORT_RANGE
            if not (lo <= p.node_port <= hi):
                errs.add(f"spec.ports[{i}].node_port",
                         f"must be in the node-port range {lo}-{hi}")
            if svc.spec.type == "ClusterIP":
                errs.add(f"spec.ports[{i}].node_port",
                         "may not be set for type ClusterIP")
    if svc.spec.type not in _SERVICE_TYPES:
        errs.add("spec.type", f"must be one of {_SERVICE_TYPES}")
    if svc.spec.session_affinity not in ("None", "ClientIP"):
        errs.add("spec.session_affinity", "must be None or ClientIP")
    elif (svc.spec.session_affinity == "ClientIP"
          and svc.spec.session_affinity_timeout_seconds <= 0):
        # Only meaningful (and only validated, like the reference)
        # when ClientIP affinity is actually on.
        errs.add("spec.session_affinity_timeout_seconds",
                 "must be positive")
    ip = svc.spec.cluster_ip
    if ip and ip != "None" and not _valid_ip(ip):
        errs.add("spec.cluster_ip", f"must be empty, 'None', or an IP; got {ip!r}")
    validate_labels(svc.spec.selector, "spec.selector", errs)
    errs.raise_if_any("Service", svc.metadata.name)


def validate_service_update(new: t.Service, old: t.Service) -> None:
    validate_service(new, is_create=False)
    errs = ErrorList()
    # Reference: ValidateServiceUpdate — clusterIP is immutable once
    # allocated (flipping it would strand every established flow).
    if old.spec.cluster_ip and new.spec.cluster_ip != old.spec.cluster_ip:
        errs.add("spec.cluster_ip", "is immutable once set")
    errs.raise_if_any("Service", new.metadata.name)


def validate_endpoints(ep: t.Endpoints, is_create: bool = True) -> None:
    """Reference: ``validation.go ValidateEndpoints``."""
    errs = ErrorList()
    validate_object_meta(ep.metadata, errs)
    for i, ss in enumerate(ep.subsets):
        for fname in ("addresses", "not_ready_addresses"):
            for j, a in enumerate(getattr(ss, fname)):
                if not _valid_ip(a.ip):
                    errs.add(f"subsets[{i}].{fname}[{j}].ip",
                             f"invalid IP {a.ip!r}")
        for j, p in enumerate(ss.ports):
            if not (0 < p.port < 65536):
                errs.add(f"subsets[{i}].ports[{j}].port", "must be 1-65535")
            if p.protocol not in _PROTOCOLS:
                errs.add(f"subsets[{i}].ports[{j}].protocol",
                         f"must be one of {_PROTOCOLS}")
    errs.raise_if_any("Endpoints", ep.metadata.name)


_CONFIG_KEY_RE = re.compile(r"^[-._a-zA-Z0-9]+$")
MAX_CONFIG_BYTES = 1024 * 1024  # reference: MaxSecretSize / ConfigMap cap


def validate_configmap(cm: t.ConfigMap, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(cm.metadata, errs)
    total = 0
    for k, v in cm.data.items():
        if not _CONFIG_KEY_RE.match(k):
            errs.add(f"data[{k!r}]",
                     "key must match [-._a-zA-Z0-9]+")
        total += len(k.encode()) + len(str(v).encode())  # bytes, not chars
    if total > MAX_CONFIG_BYTES:
        errs.add("data", f"total size {total} exceeds {MAX_CONFIG_BYTES}")
    errs.raise_if_any("ConfigMap", cm.metadata.name)


def validate_event(ev: t.Event, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(ev.metadata, errs)
    if not ev.involved_object.kind or not ev.involved_object.name:
        errs.add("involved_object", "kind and name are required")
    if ev.type not in ("Normal", "Warning"):
        errs.add("type", "must be Normal or Warning")
    errs.raise_if_any("Event", ev.metadata.name)


def _validate_quantities(d: dict, path: str, errs: ErrorList) -> None:
    for k, v in d.items():
        try:
            if t.parse_quantity(v) < 0:
                errs.add(f"{path}[{k}]", "must be non-negative")
        except ValueError:
            errs.add(f"{path}[{k}]", f"unparseable quantity {v!r}")


def validate_resourcequota(rq: t.ResourceQuota,
                           is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(rq.metadata, errs)
    _validate_quantities(rq.spec.hard, "spec.hard", errs)
    errs.raise_if_any("ResourceQuota", rq.metadata.name)


def validate_limitrange(lr: t.LimitRange, is_create: bool = True) -> None:
    """Reference: ``validation.go ValidateLimitRange`` — per-item
    quantity syntax plus the min <= default_request <= default <= max
    ordering for every resource that appears."""
    errs = ErrorList()
    validate_object_meta(lr.metadata, errs)
    for i, item in enumerate(lr.spec.limits):
        p = f"spec.limits[{i}]"
        if item.type not in ("Container", "Pod"):
            errs.add(f"{p}.type", "must be Container or Pod")
        for fname in ("min", "max", "default", "default_request"):
            _validate_quantities(getattr(item, fname), f"{p}.{fname}", errs)
        ordered = ("min", "default_request", "default", "max")
        resources = set()
        for fname in ordered:
            resources.update(getattr(item, fname))
        for res in sorted(resources):
            chain = []
            for fname in ordered:
                v = getattr(item, fname).get(res)
                if v is None:
                    continue
                try:
                    chain.append((fname, t.parse_quantity(v)))
                except ValueError:
                    break  # already reported above
            for (an, av), (bn, bv) in zip(chain, chain[1:]):
                if av > bv:
                    errs.add(f"{p}", f"{an}[{res}]={av} exceeds {bn}[{res}]={bv}")
    errs.raise_if_any("LimitRange", lr.metadata.name)


#: Reference: ``pkg/apis/scheduling/validation`` — user classes are
#: capped below the system band.
MAX_PRIORITY = 1_000_000_000


def validate_priorityclass(pc: t.PriorityClass,
                           is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(pc.metadata, errs, namespaced=False)
    # Only the two KNOWN system classes escape the user band — a bare
    # "system-" prefix check would let anyone mint "system-mine" and
    # outrank node-critical workloads (reference:
    # scheduling validation's SystemPriorityClasses allowlist).
    if (abs(pc.value) > MAX_PRIORITY
            and pc.metadata.name not in ("system-cluster-critical",
                                         "system-node-critical")):
        errs.add("value", f"must be within ±{MAX_PRIORITY} for user classes")
    if pc.preemption_policy not in ("PreemptLowerPriority", "Never"):
        errs.add("preemption_policy",
                 "must be PreemptLowerPriority or Never")
    errs.raise_if_any("PriorityClass", pc.metadata.name)


def validate_priorityclass_update(new: t.PriorityClass,
                                  old: t.PriorityClass) -> None:
    validate_priorityclass(new, is_create=False)
    errs = ErrorList()
    # Reference: priority value is immutable — running pods captured it.
    if new.value != old.value:
        errs.add("value", "is immutable")
    errs.raise_if_any("PriorityClass", new.metadata.name)


def validate_lease(lease: t.Lease, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(lease.metadata, errs)
    if lease.spec.lease_duration_seconds <= 0:
        errs.add("spec.lease_duration_seconds", "must be positive")
    errs.raise_if_any("Lease", lease.metadata.name)


def validate_serviceaccount(sa: t.ServiceAccount,
                            is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(sa.metadata, errs)
    for i, s in enumerate(sa.secrets):
        validate_name(s, f"secrets[{i}]", errs)
    errs.raise_if_any("ServiceAccount", sa.metadata.name)


# ---------------------------------------------------------------------------
# Storage
# ---------------------------------------------------------------------------

_ACCESS_MODES = ("ReadWriteOnce", "ReadOnlyMany", "ReadWriteMany")


def _validate_access_modes(modes, path: str, errs: ErrorList) -> None:
    if not modes:
        errs.add(path, "at least one access mode is required")
    for m in modes:
        if m not in _ACCESS_MODES:
            errs.add(path, f"unknown access mode {m!r}")


def validate_persistentvolume(pv: t.PersistentVolume,
                              is_create: bool = True) -> None:
    """Reference: ``validation.go ValidatePersistentVolume``."""
    errs = ErrorList()
    validate_object_meta(pv.metadata, errs, namespaced=False)
    storage = pv.spec.capacity.get("storage")
    if storage is None:
        errs.add("spec.capacity.storage", "is required")
    else:
        try:
            if t.parse_quantity(storage) <= 0:
                errs.add("spec.capacity.storage", "must be positive")
        except ValueError:
            errs.add("spec.capacity.storage",
                     f"unparseable quantity {storage!r}")
    _validate_access_modes(pv.spec.access_modes, "spec.access_modes", errs)
    sources = [s for s in (pv.spec.host_path, pv.spec.csi) if s is not None]
    if len(sources) != 1:
        errs.add("spec", "exactly one volume source (host_path or csi) "
                         "is required")
    if pv.spec.persistent_volume_reclaim_policy not in (
            t.RECLAIM_RETAIN, t.RECLAIM_DELETE):
        errs.add("spec.persistent_volume_reclaim_policy",
                 "must be Retain or Delete")
    errs.raise_if_any("PersistentVolume", pv.metadata.name)


def validate_persistentvolume_update(new: t.PersistentVolume,
                                     old: t.PersistentVolume) -> None:
    validate_persistentvolume(new, is_create=False)
    errs = ErrorList()
    # Reference: the backing source is immutable.
    if (new.spec.host_path, new.spec.csi) != (old.spec.host_path,
                                              old.spec.csi):
        errs.add("spec", "volume source is immutable")
    errs.raise_if_any("PersistentVolume", new.metadata.name)


def validate_persistentvolumeclaim(pvc: t.PersistentVolumeClaim,
                                   is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(pvc.metadata, errs)
    _validate_access_modes(pvc.spec.access_modes, "spec.access_modes", errs)
    req = pvc.spec.resources.requests.get("storage")
    if req is None:
        errs.add("spec.resources.requests.storage", "is required")
    else:
        try:
            if t.parse_quantity(req) <= 0:
                errs.add("spec.resources.requests.storage",
                         "must be positive")
        except ValueError:
            errs.add("spec.resources.requests.storage",
                     f"unparseable quantity {req!r}")
    errs.raise_if_any("PersistentVolumeClaim", pvc.metadata.name)


def validate_persistentvolumeclaim_update(new: t.PersistentVolumeClaim,
                                          old: t.PersistentVolumeClaim
                                          ) -> None:
    validate_persistentvolumeclaim(new, is_create=False)
    errs = ErrorList()
    # Reference: PVC spec is immutable after creation except the
    # storage request, which may only GROW (expansion).
    if new.spec.access_modes != old.spec.access_modes:
        errs.add("spec.access_modes", "is immutable")
    if new.spec.storage_class_name != old.spec.storage_class_name:
        errs.add("spec.storage_class_name", "is immutable")
    if old.spec.volume_name and new.spec.volume_name != old.spec.volume_name:
        errs.add("spec.volume_name", "is immutable once bound")
    try:
        n = t.parse_quantity(new.spec.resources.requests.get("storage", 0))
        o = t.parse_quantity(old.spec.resources.requests.get("storage", 0))
        if n < o:
            errs.add("spec.resources.requests.storage",
                     "may not shrink (expansion only)")
    except ValueError:
        pass  # syntax already reported by the create-shape pass
    errs.raise_if_any("PersistentVolumeClaim", new.metadata.name)


def validate_storageclass(sc: t.StorageClass,
                          is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(sc.metadata, errs, namespaced=False)
    if not sc.provisioner:
        errs.add("provisioner", "is required")
    if sc.reclaim_policy not in (t.RECLAIM_RETAIN, t.RECLAIM_DELETE):
        errs.add("reclaim_policy", "must be Retain or Delete")
    errs.raise_if_any("StorageClass", sc.metadata.name)


def validate_storageclass_update(new: t.StorageClass,
                                 old: t.StorageClass) -> None:
    validate_storageclass(new, is_create=False)
    errs = ErrorList()
    if new.provisioner != old.provisioner:
        errs.add("provisioner", "is immutable")
    if new.parameters != old.parameters:
        errs.add("parameters", "is immutable")
    errs.raise_if_any("StorageClass", new.metadata.name)


# ---------------------------------------------------------------------------
# RBAC
# ---------------------------------------------------------------------------


def _validate_rules(rules, errs: ErrorList) -> None:
    for i, rule in enumerate(rules):
        if not rule.verbs:
            errs.add(f"rules[{i}].verbs", "at least one verb is required")
        if not rule.resources:
            errs.add(f"rules[{i}].resources",
                     "at least one resource is required")


def validate_role(role, is_create: bool = True) -> None:
    errs = ErrorList()
    _validate_rules(role.rules, errs)
    errs.raise_if_any(type(role).__name__, role.metadata.name)


def validate_rolebinding(b, is_create: bool = True) -> None:
    errs = ErrorList()
    if not b.role_ref.name:
        errs.add("role_ref.name", "is required")
    if b.role_ref.kind not in ("Role", "ClusterRole"):
        errs.add("role_ref.kind", "must be Role or ClusterRole")
    if isinstance(b, rb.ClusterRoleBinding) and b.role_ref.kind != "ClusterRole":
        errs.add("role_ref.kind",
                 "ClusterRoleBinding may only reference a ClusterRole")
    for i, s in enumerate(b.subjects):
        if not s.name:
            errs.add(f"subjects[{i}].name", "is required")
        if s.kind not in ("User", "Group", "ServiceAccount"):
            errs.add(f"subjects[{i}].kind",
                     "must be User, Group, or ServiceAccount")
    errs.raise_if_any(type(b).__name__, b.metadata.name)


def validate_rolebinding_update(new, old) -> None:
    validate_rolebinding(new, is_create=False)
    errs = ErrorList()
    # Reference: ValidateRoleBindingUpdate — roleRef is immutable
    # (changing it silently re-points every subject's grant).
    if (new.role_ref.kind, new.role_ref.name) != (old.role_ref.kind,
                                                  old.role_ref.name):
        errs.add("role_ref", "is immutable; delete and recreate the binding")
    errs.raise_if_any(type(new).__name__, new.metadata.name)


# ---------------------------------------------------------------------------
# Remaining workloads
# ---------------------------------------------------------------------------


def validate_daemonset(ds: w.DaemonSet, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(ds.metadata, errs)
    _validate_template_matches(ds.spec.selector, ds.spec.template, errs)
    if ds.spec.update_strategy not in (w.ROLLING_UPDATE, "OnDelete"):
        errs.add("spec.update_strategy",
                 f"unknown strategy {ds.spec.update_strategy!r}")
    errs.raise_if_any("DaemonSet", ds.metadata.name)


def _selector_immutable(new_sel, old_sel, errs: ErrorList) -> None:
    """apps/v1 semantics: label selectors are immutable — mutating one
    silently orphans or captures pods (the reference made this a hard
    rule at v1, ValidateDeploymentUpdate et al.). Full structural
    comparison: a changed expression key/op/values is as much a
    mutation as a changed match_label."""
    from .scheme import to_dict
    def key(s):
        return None if s is None else to_dict(s)
    if key(new_sel) != key(old_sel):
        errs.add("spec.selector", "is immutable in apps/v1")


def validate_deployment_update(new: w.Deployment, old: w.Deployment) -> None:
    validate_deployment(new, is_create=False)
    errs = ErrorList()
    _selector_immutable(new.spec.selector, old.spec.selector, errs)
    errs.raise_if_any("Deployment", new.metadata.name)


def validate_replicaset_update(new: w.ReplicaSet, old: w.ReplicaSet) -> None:
    validate_replicaset(new, is_create=False)
    errs = ErrorList()
    _selector_immutable(new.spec.selector, old.spec.selector, errs)
    errs.raise_if_any("ReplicaSet", new.metadata.name)


def validate_statefulset_update(new: w.StatefulSet,
                                old: w.StatefulSet) -> None:
    validate_statefulset(new, is_create=False)
    errs = ErrorList()
    _selector_immutable(new.spec.selector, old.spec.selector, errs)
    if new.spec.service_name != old.spec.service_name:
        errs.add("spec.service_name", "is immutable")
    errs.raise_if_any("StatefulSet", new.metadata.name)


def validate_daemonset_update(new: w.DaemonSet, old: w.DaemonSet) -> None:
    validate_daemonset(new, is_create=False)
    errs = ErrorList()
    _selector_immutable(new.spec.selector, old.spec.selector, errs)
    errs.raise_if_any("DaemonSet", new.metadata.name)


def validate_job_update(new: w.Job, old: w.Job) -> None:
    validate_job(new, is_create=False)
    from .scheme import to_dict
    errs = ErrorList()
    # Reference: ValidateJobUpdate — completions/selector/template/gang
    # frozen; parallelism is the one mutable knob (scale).
    if new.spec.completions != old.spec.completions:
        errs.add("spec.completions", "is immutable")
    if new.spec.completion_mode != old.spec.completion_mode:
        errs.add("spec.completion_mode", "is immutable")
    if to_dict(new.spec.selector) != to_dict(old.spec.selector):
        errs.add("spec.selector", "is immutable")
    if to_dict(new.spec.template) != to_dict(old.spec.template):
        errs.add("spec.template", "is immutable")
    if to_dict(new.spec.gang) != to_dict(old.spec.gang):
        errs.add("spec.gang", "is immutable")
    errs.raise_if_any("Job", new.metadata.name)


def validate_cronjob(cj: w.CronJob, is_create: bool = True) -> None:
    """Reference: ``pkg/apis/batch/validation ValidateCronJob`` — the
    schedule string parses AT ADMISSION with the same parser the
    controller runs, so a typo fails the create instead of wedging the
    controller's sync loop."""
    from ..util.cron import CronSchedule
    errs = ErrorList()
    validate_object_meta(cj.metadata, errs)
    if not cj.spec.schedule:
        errs.add("spec.schedule", "is required")
    else:
        try:
            CronSchedule(cj.spec.schedule)
        except (ValueError, IndexError) as e:
            errs.add("spec.schedule", f"invalid cron expression: {e}")
    if cj.spec.concurrency_policy not in ("Allow", "Forbid", "Replace"):
        errs.add("spec.concurrency_policy",
                 "must be Allow, Forbid, or Replace")
    if (cj.spec.starting_deadline_seconds is not None
            and cj.spec.starting_deadline_seconds < 0):
        errs.add("spec.starting_deadline_seconds", "must be non-negative")
    for fname in ("successful_jobs_history_limit",
                  "failed_jobs_history_limit"):
        if getattr(cj.spec, fname) < 0:
            errs.add(f"spec.{fname}", "must be non-negative")
    if cj.spec.job_template.parallelism < 0:
        errs.add("spec.job_template.parallelism", "must be non-negative")
    errs.raise_if_any("CronJob", cj.metadata.name)


def validate_hpa(hpa: w.HorizontalPodAutoscaler,
                 is_create: bool = True) -> None:
    """Reference: ``pkg/apis/autoscaling/validation``."""
    errs = ErrorList()
    validate_object_meta(hpa.metadata, errs)
    ref = hpa.spec.scale_target_ref
    if not ref.kind or not ref.name:
        errs.add("spec.scale_target_ref", "kind and name are required")
    if hpa.spec.min_replicas < 1:
        errs.add("spec.min_replicas", "must be >= 1")
    if hpa.spec.max_replicas < hpa.spec.min_replicas:
        errs.add("spec.max_replicas", "must be >= spec.min_replicas")
    # >=1 only: targets above 100% are legal and common on multi-core
    # pods (reference: autoscaling validation requires only positive).
    if hpa.spec.target_cpu_utilization_percentage < 1:
        errs.add("spec.target_cpu_utilization_percentage",
                 "must be >= 1")
    errs.raise_if_any("HorizontalPodAutoscaler", hpa.metadata.name)


def validate_pdb(pdb: w.PodDisruptionBudget, is_create: bool = True) -> None:
    """Reference: ``pkg/apis/policy/validation`` — min_available and
    max_unavailable are mutually exclusive, and the selector must be
    well-formed (a malformed one would silently cover nothing,
    defeating the budget)."""
    errs = ErrorList()
    validate_object_meta(pdb.metadata, errs)
    has_min = pdb.spec.min_available is not None
    has_max = pdb.spec.max_unavailable is not None
    if has_min and has_max:
        errs.add("spec", "min_available and max_unavailable "
                         "are mutually exclusive")
    if not has_min and not has_max:
        errs.add("spec", "one of min_available or max_unavailable "
                         "is required")
    if has_min and pdb.spec.min_available < 0:
        errs.add("spec.min_available", "must be non-negative")
    if has_max and pdb.spec.max_unavailable < 0:
        errs.add("spec.max_unavailable", "must be non-negative")
    if pdb.spec.selector is not None:
        validate_labels(pdb.spec.selector.match_labels,
                        "spec.selector.match_labels", errs)
    errs.raise_if_any("PodDisruptionBudget", pdb.metadata.name)


def validate_podsecuritypolicy(psp: t.PodSecurityPolicy,
                               is_create: bool = True) -> None:
    """Reference: ``pkg/apis/policy`` PSP validation (rule enums +
    range sanity)."""
    errs = ErrorList()
    validate_object_meta(psp.metadata, errs, namespaced=False)
    rule = psp.spec.run_as_user_rule
    if rule not in ("RunAsAny", "MustRunAs", "MustRunAsNonRoot"):
        errs.add("spec.run_as_user_rule",
                 "must be RunAsAny, MustRunAs, or MustRunAsNonRoot")
    if rule == "MustRunAs" and not psp.spec.run_as_user_ranges:
        errs.add("spec.run_as_user_ranges",
                 "required when run_as_user_rule is MustRunAs")
    for i, r in enumerate(psp.spec.run_as_user_ranges):
        if r.min < 0 or r.max < r.min:
            errs.add(f"spec.run_as_user_ranges[{i}]",
                     "needs 0 <= min <= max")
    errs.raise_if_any("PodSecurityPolicy", psp.metadata.name)


def validate_secret_update(new: t.Secret, old: t.Secret) -> None:
    validate_secret(new, is_create=False)
    errs = ErrorList()
    if new.type != old.type:
        errs.add("type", "is immutable")
    errs.raise_if_any("Secret", new.metadata.name)


def validate_secret(sec: t.Secret, is_create: bool = True) -> None:
    """``data`` values must be valid base64 (reference:
    ``validation.go ValidateSecret``); plaintext belongs in
    ``string_data``, which the strategy merges before validation."""
    import base64
    import binascii
    errs = ErrorList()
    validate_object_meta(sec.metadata, errs)
    for key, value in sec.data.items():
        try:
            base64.b64decode(value, validate=True)
        except (binascii.Error, ValueError):
            errs.add(f"data[{key}]",
                     "must be base64 (use string_data for plaintext)")
    errs.raise_if_any("Secret", sec.metadata.name)


def validate_namespace(ns: t.Namespace, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(ns.metadata, errs, namespaced=False)
    errs.raise_if_any("Namespace", ns.metadata.name)


#: kind -> (create validator, update validator or None)
VALIDATORS = {
    "Pod": (validate_pod, validate_pod_update),
    "Node": (validate_node, None),
    "ReplicaSet": (validate_replicaset, validate_replicaset_update),
    "Deployment": (validate_deployment, validate_deployment_update),
    "StatefulSet": (validate_statefulset, validate_statefulset_update),
    "DaemonSet": (validate_daemonset, validate_daemonset_update),
    "Job": (validate_job, validate_job_update),
    "CronJob": (validate_cronjob, None),
    "HorizontalPodAutoscaler": (validate_hpa, None),
    "PodDisruptionBudget": (validate_pdb, None),
    "PodSecurityPolicy": (validate_podsecuritypolicy, None),
    "PodGroup": (validate_podgroup, validate_podgroup_update),
    "Service": (validate_service, validate_service_update),
    "Endpoints": (validate_endpoints, None),
    "ConfigMap": (validate_configmap, None),
    "Secret": (validate_secret, validate_secret_update),
    "Event": (validate_event, None),
    "ResourceQuota": (validate_resourcequota, None),
    "LimitRange": (validate_limitrange, None),
    "PriorityClass": (validate_priorityclass, validate_priorityclass_update),
    "Lease": (validate_lease, None),
    "ServiceAccount": (validate_serviceaccount, None),
    "PersistentVolume": (validate_persistentvolume,
                         validate_persistentvolume_update),
    "PersistentVolumeClaim": (validate_persistentvolumeclaim,
                              validate_persistentvolumeclaim_update),
    "StorageClass": (validate_storageclass, validate_storageclass_update),
    "Role": (validate_role, None),
    "ClusterRole": (validate_role, None),
    "RoleBinding": (validate_rolebinding, validate_rolebinding_update),
    "ClusterRoleBinding": (validate_rolebinding, validate_rolebinding_update),
    "Namespace": (validate_namespace, None),
}
