"""API validation — reject malformed objects before they hit the store.

Reference: ``pkg/apis/core/validation/validation.go`` (~4.8k lines),
incl. the fork's extended-resource validation (``:2457,2883-2888,2950``:
claim names unique, container references resolve, assigned IDs only via
binding). Field errors accumulate into one Invalid error with a path
list, like the reference's ``field.ErrorList``.
"""
from __future__ import annotations

import re
from typing import Optional

from . import types as t
from . import workloads as w
from .errors import InvalidError

# DNS-1123: what object names must look like.
_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$")
_LABEL_KEY_RE = re.compile(r"^([a-z0-9A-Z][-a-z0-9A-Z_.]*)?[a-z0-9A-Z](/([a-z0-9A-Z][-a-z0-9A-Z_.]*)?[a-z0-9A-Z])?$")
_LABEL_VAL_RE = re.compile(r"^([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9]$|^$")
MAX_NAME_LEN = 253


class ErrorList:
    def __init__(self) -> None:
        self.errors: list[str] = []

    def add(self, path: str, msg: str) -> None:
        self.errors.append(f"{path}: {msg}")

    def raise_if_any(self, kind: str, name: str) -> None:
        if self.errors:
            raise InvalidError(
                f"{kind} {name!r} is invalid: " + "; ".join(self.errors),
                details={"errors": self.errors},
            )


def validate_name(name: str, path: str, errs: ErrorList, required: bool = True) -> None:
    if not name:
        if required:
            errs.add(path, "name is required")
        return
    if len(name) > MAX_NAME_LEN:
        errs.add(path, f"must be <= {MAX_NAME_LEN} chars")
    if not _NAME_RE.match(name):
        errs.add(path, "must be DNS-1123: lowercase alphanumerics, '-', '.'")


def validate_labels(labels: dict, path: str, errs: ErrorList) -> None:
    for k, v in labels.items():
        if not _LABEL_KEY_RE.match(k) or len(k) > 317:
            errs.add(f"{path}.{k}", "invalid label key")
        if not _LABEL_VAL_RE.match(str(v)) or len(str(v)) > 63:
            errs.add(f"{path}.{k}", "invalid label value")


def validate_object_meta(meta, errs: ErrorList, namespaced: bool = True, path: str = "metadata") -> None:
    if not meta.name and not meta.generate_name:
        errs.add(f"{path}.name", "name or generate_name is required")
    if meta.name:
        validate_name(meta.name, f"{path}.name", errs)
    if namespaced and meta.namespace:
        validate_name(meta.namespace, f"{path}.namespace", errs)
    if not namespaced and meta.namespace:
        errs.add(f"{path}.namespace", "cluster-scoped object must not set namespace")
    validate_labels(meta.labels, f"{path}.labels", errs)


# ---------------------------------------------------------------------------
# Pods
# ---------------------------------------------------------------------------


def _validate_container(c: t.Container, claim_names: set, path: str, errs: ErrorList) -> None:
    validate_name(c.name, f"{path}.name", errs)
    if not c.image and not c.command:
        errs.add(f"{path}.image", "image or command is required")
    for i, p in enumerate(c.ports):
        if not (0 < p.container_port < 65536):
            errs.add(f"{path}.ports[{i}]", "container_port must be 1-65535")
    for q in c.tpu_requests:
        # Fork analog: validation.go:2883-2888 — container references
        # must resolve to a declared pod-level claim.
        if q not in claim_names:
            errs.add(f"{path}.tpu_requests", f"no pod tpu_resources entry named {q!r}")
    for k, v in {**c.resources.requests, **c.resources.limits}.items():
        try:
            if t.parse_quantity(v) < 0:
                errs.add(f"{path}.resources.{k}", "must be non-negative")
        except ValueError:
            errs.add(f"{path}.resources.{k}", f"unparseable quantity {v!r}")
    for probe_name in ("liveness_probe", "readiness_probe"):
        probe = getattr(c, probe_name, None)
        if probe is None:
            continue
        http = getattr(probe, "http_get", None)
        if http is not None and not (0 < http.port < 65536):
            errs.add(f"{path}.{probe_name}.http_get.port",
                     "port must be 1-65535")
        if probe.tcp_port and not (0 < probe.tcp_port < 65536):
            errs.add(f"{path}.{probe_name}.tcp_port",
                     "port must be 1-65535")


_PATH_SEGMENT_BAD = set("/%")


def validate_meta_generic(meta, namespaced: bool,
                          path_segment_name: bool = False) -> None:
    """Meta validation applied by the registry to EVERY kind
    (reference: ValidateObjectMeta runs on all object paths, not just
    kinds with bespoke validators). Delegates to
    :func:`validate_object_meta` — one definition of the rules — with
    the name-charset check swapped for path-segment rules when
    ``path_segment_name`` (RBAC-style names like "system:node";
    validation.go ValidatePathSegmentName). Runs AFTER stamp_new, so
    generate_name is already resolved and a missing name is an error.
    """
    errs = ErrorList()
    if path_segment_name:
        name = meta.name
        if not name:
            errs.add("metadata.name", "name is required")
        elif (name in (".", "..")
              or any(c in _PATH_SEGMENT_BAD for c in name)):
            errs.add("metadata.name",
                     "may not be '.', '..' or contain '/' or '%'")
        elif len(name) > MAX_NAME_LEN:
            errs.add("metadata.name", f"must be <= {MAX_NAME_LEN} chars")
        if namespaced and meta.namespace:
            validate_name(meta.namespace, "metadata.namespace", errs)
        if not namespaced and meta.namespace:
            errs.add("metadata.namespace",
                     "cluster-scoped object must not set namespace")
        validate_labels(meta.labels, "metadata.labels", errs)
    else:
        validate_object_meta(meta, errs, namespaced=namespaced)
    for k in meta.annotations:
        if not k or len(k) > 317:
            errs.add(f"metadata.annotations.{k!r}", "invalid annotation key")
    errs.raise_if_any(type(meta).__name__, meta.name)


def validate_pod(pod: t.Pod, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(pod.metadata, errs)
    if not pod.spec.containers:
        errs.add("spec.containers", "at least one container is required")
    claim_names = {r.name for r in pod.spec.tpu_resources}
    if len(claim_names) != len(pod.spec.tpu_resources):
        errs.add("spec.tpu_resources", "claim names must be unique")  # validation.go:2457
    # Volumes: unique names, exactly one source each; every mount must
    # reference a declared volume (validation.go ValidateVolumes +
    # ValidateVolumeMounts — the cross-ref the r3 verdict called thin).
    vol_names = set()
    for i, v in enumerate(pod.spec.volumes):
        validate_name(v.name, f"spec.volumes[{i}].name", errs)
        if v.name in vol_names:
            errs.add(f"spec.volumes[{i}].name",
                     f"duplicate volume name {v.name!r}")
        vol_names.add(v.name)
        sources = [s for s in (v.host_path, v.empty_dir, v.config_map,
                               v.secret, v.persistent_volume_claim)
                   if s is not None]
        if len(sources) > 1:
            errs.add(f"spec.volumes[{i}]",
                     "may not specify more than one volume source")
        elif not sources:
            errs.add(f"spec.volumes[{i}]",
                     "exactly one volume source is required")
    seen = set()
    n_main = len(pod.spec.containers)
    for i, c in enumerate(pod.spec.containers + pod.spec.init_containers):
        cpath = (f"spec.containers[{i}]" if i < n_main
                 else f"spec.init_containers[{i - n_main}]")
        if c.name in seen:
            errs.add(f"{cpath}.name", f"duplicate container name {c.name!r}")
        seen.add(c.name)
        _validate_container(c, claim_names, cpath, errs)
        for j, vm in enumerate(c.volume_mounts):
            if vm.name not in vol_names:
                errs.add(f"{cpath}.volume_mounts[{j}].name",
                         f"no spec.volumes entry named {vm.name!r}")
            if not vm.mount_path:
                errs.add(f"{cpath}.volume_mounts[{j}].mount_path",
                         "mount_path is required")
    if pod.spec.restart_policy not in (t.RESTART_ALWAYS, t.RESTART_ON_FAILURE, t.RESTART_NEVER):
        errs.add("spec.restart_policy", f"unknown policy {pod.spec.restart_policy!r}")
    aff = pod.spec.affinity
    if aff is not None:
        # REQUIRED inter-pod terms need a selector and a topology key
        # (validation.go ValidatePodAffinityTerm) — a selector-less
        # required term would match nothing and wedge the pod forever.
        # Preferred (soft) terms without a selector are a harmless
        # zero-score no-op and stay legal, but still need a topology
        # key (the reference validates it for weighted terms too — a
        # keyless soft term silently scores zero everywhere).
        required = ([("spec.affinity.pod_affinity", tm)
                     for tm in aff.pod_affinity]
                    + [("spec.affinity.pod_anti_affinity", tm)
                       for tm in aff.pod_anti_affinity])
        soft = ([("spec.affinity.pod_affinity_preferred", wt.pod_affinity_term)
                 for wt in aff.pod_affinity_preferred]
                + [("spec.affinity.pod_anti_affinity_preferred",
                    wt.pod_affinity_term)
                   for wt in aff.pod_anti_affinity_preferred])
        for path, term in required:
            if term.label_selector is None:
                errs.add(path, "label_selector is required")
        for path, term in required + soft:
            if not term.topology_key:
                errs.add(path, "topology_key is required")
    for i, r in enumerate(pod.spec.tpu_resources):
        if not r.name:
            errs.add(f"spec.tpu_resources[{i}].name", "name is required")
        if r.chips < 0:
            errs.add(f"spec.tpu_resources[{i}].chips", "must be non-negative")
        if r.slice_shape and any(d <= 0 for d in r.slice_shape):
            errs.add(f"spec.tpu_resources[{i}].slice_shape", "dims must be positive")
        if r.slice_shape and len(r.slice_shape) > 3:
            errs.add(f"spec.tpu_resources[{i}].slice_shape", "at most 3 dims")
        if is_create and r.assigned:
            # Fork analog: validation.go:2950 — only the binding
            # subresource may write assignments.
            errs.add(f"spec.tpu_resources[{i}].assigned", "cannot be set on create")
    errs.raise_if_any("Pod", pod.metadata.name)


def validate_pod_update(new: t.Pod, old: t.Pod) -> None:
    errs = ErrorList()
    # Spec is mostly immutable after creation (reference semantics);
    # node_name may only transition empty -> set (via binding).
    if old.spec.node_name and new.spec.node_name != old.spec.node_name:
        errs.add("spec.node_name", "is immutable once set")
    if len(new.spec.containers) != len(old.spec.containers):
        errs.add("spec.containers", "may not add or remove containers")
    # TPU claims are immutable through the normal update path; chip
    # assignments are written only by the binding subresource, which
    # goes straight to storage (fork analog: validation.go:2950 +
    # pkg/registry/core/pod/storage/storage.go:154).
    old_claims = {r.name: r for r in old.spec.tpu_resources}
    new_claims = {r.name: r for r in new.spec.tpu_resources}
    if set(old_claims) != set(new_claims):
        errs.add("spec.tpu_resources", "claims may not be added or removed")
    else:
        for name, nr in new_claims.items():
            o = old_claims[name]
            if nr.assigned != o.assigned:
                errs.add(f"spec.tpu_resources[{name}].assigned",
                         "may only be written via the binding subresource")
            if (nr.chips, nr.slice_shape, nr.resource) != (o.chips, o.slice_shape, o.resource):
                errs.add(f"spec.tpu_resources[{name}]", "claim shape is immutable")
    if new.spec.gang != old.spec.gang:
        errs.add("spec.gang", "is immutable")
    errs.raise_if_any("Pod", new.metadata.name)


# ---------------------------------------------------------------------------
# Nodes
# ---------------------------------------------------------------------------


def validate_node(node: t.Node, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(node.metadata, errs, namespaced=False)
    for i, taint in enumerate(node.spec.taints):
        if taint.effect not in (t.TAINT_NO_SCHEDULE, t.TAINT_PREFER_NO_SCHEDULE, t.TAINT_NO_EXECUTE):
            errs.add(f"spec.taints[{i}].effect", f"unknown effect {taint.effect!r}")
        if not taint.key:
            errs.add(f"spec.taints[{i}].key", "key is required")
    topo = node.status.tpu
    if topo is not None:
        ids = [c.id for c in topo.chips]
        if len(set(ids)) != len(ids):
            errs.add("status.tpu.chips", "chip ids must be unique")
        if topo.mesh_shape and any(d <= 0 for d in topo.mesh_shape):
            errs.add("status.tpu.mesh_shape", "dims must be positive")
        for i, chip in enumerate(topo.chips):
            if topo.mesh_shape and len(chip.coords) != len(topo.mesh_shape):
                errs.add(f"status.tpu.chips[{i}].coords", "rank must match mesh_shape")
    errs.raise_if_any("Node", node.metadata.name)


# ---------------------------------------------------------------------------
# Workloads
# ---------------------------------------------------------------------------


def _validate_template_matches(selector, template, errs: ErrorList) -> None:
    if selector is None or selector.empty():
        errs.add("spec.selector", "selector is required and must be non-empty")
        return
    if not selector.matches(template.metadata.labels):
        errs.add("spec.template.metadata.labels", "must match spec.selector")


def validate_replicaset(rs: w.ReplicaSet, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(rs.metadata, errs)
    if rs.spec.replicas < 0:
        errs.add("spec.replicas", "must be non-negative")
    _validate_template_matches(rs.spec.selector, rs.spec.template, errs)
    errs.raise_if_any("ReplicaSet", rs.metadata.name)


def validate_deployment(d: w.Deployment, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(d.metadata, errs)
    if d.spec.replicas < 0:
        errs.add("spec.replicas", "must be non-negative")
    _validate_template_matches(d.spec.selector, d.spec.template, errs)
    if d.spec.strategy.type not in (w.ROLLING_UPDATE, w.RECREATE):
        errs.add("spec.strategy.type", f"unknown strategy {d.spec.strategy.type!r}")
    errs.raise_if_any("Deployment", d.metadata.name)


def validate_statefulset(s: w.StatefulSet, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(s.metadata, errs)
    if s.spec.replicas < 0:
        errs.add("spec.replicas", "must be non-negative")
    _validate_template_matches(s.spec.selector, s.spec.template, errs)
    errs.raise_if_any("StatefulSet", s.metadata.name)


def validate_job(j: w.Job, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(j.metadata, errs)
    if j.spec.parallelism < 0:
        errs.add("spec.parallelism", "must be non-negative")
    if j.spec.completions is not None and j.spec.completions < 0:
        errs.add("spec.completions", "must be non-negative")
    if j.spec.gang is not None:
        g = j.spec.gang
        if g.min_member < 0:
            errs.add("spec.gang.min_member", "must be non-negative")
        if g.slice_shape and any(d <= 0 for d in g.slice_shape):
            errs.add("spec.gang.slice_shape", "dims must be positive")
    errs.raise_if_any("Job", j.metadata.name)


def validate_podgroup(pg: t.PodGroup, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(pg.metadata, errs)
    if pg.spec.min_member < 1:
        errs.add("spec.min_member", "must be >= 1")
    if pg.spec.slice_shape and any(d <= 0 for d in pg.spec.slice_shape):
        errs.add("spec.slice_shape", "dims must be positive")
    errs.raise_if_any("PodGroup", pg.metadata.name)


def validate_service(svc: t.Service, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(svc.metadata, errs)
    for i, p in enumerate(svc.spec.ports):
        if not (0 < p.port < 65536):
            errs.add(f"spec.ports[{i}].port", "must be 1-65535")
    errs.raise_if_any("Service", svc.metadata.name)


def validate_secret(sec: t.Secret, is_create: bool = True) -> None:
    """``data`` values must be valid base64 (reference:
    ``validation.go ValidateSecret``); plaintext belongs in
    ``string_data``, which the strategy merges before validation."""
    import base64
    import binascii
    errs = ErrorList()
    validate_object_meta(sec.metadata, errs)
    for key, value in sec.data.items():
        try:
            base64.b64decode(value, validate=True)
        except (binascii.Error, ValueError):
            errs.add(f"data[{key}]",
                     "must be base64 (use string_data for plaintext)")
    errs.raise_if_any("Secret", sec.metadata.name)


def validate_namespace(ns: t.Namespace, is_create: bool = True) -> None:
    errs = ErrorList()
    validate_object_meta(ns.metadata, errs, namespaced=False)
    errs.raise_if_any("Namespace", ns.metadata.name)


#: kind -> (create validator, update validator or None)
VALIDATORS = {
    "Pod": (validate_pod, validate_pod_update),
    "Node": (validate_node, None),
    "ReplicaSet": (validate_replicaset, None),
    "Deployment": (validate_deployment, None),
    "StatefulSet": (validate_statefulset, None),
    "Job": (validate_job, None),
    "PodGroup": (validate_podgroup, None),
    "Service": (validate_service, None),
    "Namespace": (validate_namespace, None),
}
