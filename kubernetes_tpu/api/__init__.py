from . import meta, scheme, selectors, types, validation  # noqa: F401
