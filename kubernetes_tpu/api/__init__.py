from . import meta, queueing, scheme, selectors, types, validation  # noqa: F401
