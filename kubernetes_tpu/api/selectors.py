"""Label / field selectors and device-attribute selectors.

Reference: ``staging/src/k8s.io/apimachinery/pkg/labels`` (Selector,
Requirement with In/NotIn/Exists/...), and the fork's
``ResourceSelector`` over device attributes
(``staging/src/k8s.io/api/core/v1/types.go:2632-2639``, evaluated at
``plugin/pkg/scheduler/core/extended_resources.go:152 isDeviceAMatch``).

Selectors here serve three consumers: workload controllers matching pods,
the scheduler matching node labels, and the TPU sub-mesh allocator
matching chip attributes (chip type, HBM, topology coords).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Mapping, Optional

# Operators mirror metav1.LabelSelectorOperator + fork's ResourceSelector ops.
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"


@dataclass
class Requirement:
    key: str = ""
    operator: str = OP_IN
    values: list[str] = field(default_factory=list)

    def matches(self, labels: Mapping[str, str]) -> bool:
        present = self.key in labels
        if self.operator == OP_EXISTS:
            return present
        if self.operator == OP_DOES_NOT_EXIST:
            return not present
        if not present:
            return False
        v = str(labels[self.key])
        if self.operator == OP_IN:
            return v in self.values
        if self.operator == OP_NOT_IN:
            return v not in self.values
        if self.operator in (OP_GT, OP_LT):
            try:
                lhs, rhs = float(v), float(self.values[0])
            except (ValueError, IndexError):
                return False
            return lhs > rhs if self.operator == OP_GT else lhs < rhs
        return False


@dataclass
class LabelSelector:
    """match_labels AND match_expressions, all must hold (metav1 semantics).

    An empty selector matches everything; a None selector matches nothing
    (callers encode that distinction, as the reference does).
    """

    match_labels: dict[str, str] = field(default_factory=dict)
    match_expressions: list[Requirement] = field(default_factory=list)

    def matches(self, labels: Mapping[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        return all(r.matches(labels) for r in self.match_expressions)

    def empty(self) -> bool:
        return not self.match_labels and not self.match_expressions


def parse_selector(expr: str) -> LabelSelector:
    """Parse 'a=b,c!=d,e in (x|y),f' (CLI style, cf. labels.Parse)."""
    sel = LabelSelector()
    expr = expr.strip()
    if not expr:
        return sel
    for part in expr.split(","):
        part = part.strip()
        if not part:
            continue
        if " in " in part:
            key, _, vals = part.partition(" in ")
            vs = [v.strip() for v in vals.strip().strip("()").split("|") if v.strip()]
            sel.match_expressions.append(Requirement(key.strip(), OP_IN, vs))
        elif " notin " in part:
            key, _, vals = part.partition(" notin ")
            vs = [v.strip() for v in vals.strip().strip("()").split("|") if v.strip()]
            sel.match_expressions.append(Requirement(key.strip(), OP_NOT_IN, vs))
        elif "!=" in part:
            key, _, v = part.partition("!=")
            sel.match_expressions.append(Requirement(key.strip(), OP_NOT_IN, [v.strip()]))
        elif "==" in part:
            key, _, v = part.partition("==")
            sel.match_labels[key.strip()] = v.strip()
        elif "=" in part:
            key, _, v = part.partition("=")
            sel.match_labels[key.strip()] = v.strip()
        elif part.startswith("!"):
            sel.match_expressions.append(Requirement(part[1:].strip(), OP_DOES_NOT_EXIST))
        else:
            sel.match_expressions.append(Requirement(part, OP_EXISTS))
    return sel


def format_selector(sel: LabelSelector) -> str:
    parts = [f"{k}={v}" for k, v in sorted(sel.match_labels.items())]
    for r in sel.match_expressions:
        if r.operator == OP_EXISTS:
            parts.append(r.key)
        elif r.operator == OP_DOES_NOT_EXIST:
            parts.append(f"!{r.key}")
        elif r.operator == OP_IN:
            parts.append(f"{r.key} in ({'|'.join(r.values)})")
        elif r.operator == OP_NOT_IN:
            parts.append(f"{r.key} notin ({'|'.join(r.values)})")
        else:
            parts.append(f"{r.key} {r.operator} {r.values[0] if r.values else ''}")
    return ",".join(parts)


def match_field_selector(expr: str, fields: Mapping[str, str]) -> bool:
    """Field selectors: 'spec.node_name=worker-1,status.phase!=Failed'."""
    if not expr:
        return True
    for part in expr.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            key, _, v = part.partition("!=")
            if str(fields.get(key.strip(), "")) == v.strip():
                return False
        else:
            key, _, v = part.partition("=")
            if str(fields.get(key.strip(), "")) != v.strip():
                return False
    return True


def matches_any(selectors: Iterable[LabelSelector], labels: Mapping[str, str]) -> bool:
    return any(s.matches(labels) for s in selectors)


def matches_all(selectors: Iterable[LabelSelector], labels: Mapping[str, str]) -> bool:
    return all(s.matches(labels) for s in selectors)
