"""Static device manager + stub topology for hollow nodes.

Reference: ``pkg/kubelet/cm/devicemanager/plugin/stub.go`` — kubemark's
hollow kubelet wires device plugins through a stub rather than real
gRPC sockets, because one process cannot host thousands of gRPC
servers, and the seam under test is the manager's admission/options
surface, not the wire.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from ..api import types as t
from ..node.devicemanager import DeviceManager


class StaticDeviceManager(DeviceManager):
    """Device manager with a fixed topology and local (no-RPC) admit/
    options — the device_plugin_stub.go equivalent for fleets."""

    def __init__(self, topology: t.TpuTopology, resource: str = t.RESOURCE_TPU):
        # Deliberately no super().__init__: no plugin dir, no watcher.
        self._topology = topology
        self._topology_resource = resource
        self.on_topology_changed = None
        self.ready = asyncio.Event()
        self.ready.set()

    async def start(self) -> None:  # no watcher task
        return

    async def stop(self) -> None:
        return

    async def admit_pod(self, pod: t.Pod) -> Optional[str]:
        known = {c.id: c for c in self._topology.chips}
        for cid in t.pod_tpu_assigned(pod):
            chip = known.get(cid)
            if chip is None:
                return f"assigned chip {cid!r} does not exist on this node"
            if chip.health != t.TPU_HEALTHY:
                return f"assigned chip {cid!r} is {chip.health}"
        return None

    async def container_options(self, pod: t.Pod, container: t.Container):
        env: dict[str, str] = {}
        for claim_name in container.tpu_requests:
            claim = t.pod_tpu_request(pod, claim_name)
            if claim is None or not claim.assigned:
                continue
            env["TPU_VISIBLE_CHIPS"] = ",".join(claim.assigned)
            env["TPU_WORKER_ID"] = str(self._topology.worker_index)
            env["TPU_MESH_SHAPE"] = "x".join(
                str(d) for d in self._topology.mesh_shape)
        return env, [], [], {}


def hollow_topology(name: str, chips: int, mesh_shape=None,
                    slice_id: str = "") -> t.TpuTopology:
    """Stub TPU topology for hollow nodes — the single source for both
    agent-backed fleets (:mod:`kubernetes_tpu.hollow.fleet`) and
    API-object-only nodes (:func:`kubernetes_tpu.perf.density.hollow_node`)."""
    shape = list(mesh_shape) if mesh_shape else (
        [2, 2, chips // 4] if chips % 4 == 0 else [chips, 1, 1])
    if shape[0] * shape[1] * shape[2] != chips:
        raise ValueError(f"mesh_shape {shape} != {chips} chips")
    return t.TpuTopology(
        chip_type="v5p", slice_id=slice_id or f"slice-{name}",
        mesh_shape=shape,
        chips=[t.TpuChip(
            id=f"{name}-c{i}", health=t.TPU_HEALTHY,
            coords=[i % shape[0], (i // shape[0]) % shape[1],
                    i // (shape[0] * shape[1])],
            attributes={"chip_type": "v5p"}) for i in range(chips)])
