"""Multi-process hollow fleet: shards of :class:`HollowFleet` spread
over worker processes.

One asyncio loop serializes everything on it; past a few hundred hollow
nodes the shard's own bookkeeping (PLEG ticks, heartbeat posts, watch
decode) competes with itself. Workers give each shard its own loop AND
its own RSS/fd budget line — ``stats()`` reports per-process, so "RSS
per 1k hollow nodes" is a measurement, not an estimate.

Protocol (parent <-> worker, one Pipe each): the worker boots its
shard, waits for its readiness barrier, sends ``("ready", stats)``;
then serves ``"stats"`` / ``"stop"`` commands until told to exit.
Workers use the ``spawn`` start method — forking a parent with a live
event loop and executor threads duplicates locks in undefined states.
"""
from __future__ import annotations

import asyncio
import multiprocessing as mp
import time
from typing import Optional


def _worker_main(conn, base_url: str, cfg: dict) -> None:
    asyncio.run(_worker_async(conn, base_url, cfg))


async def _worker_async(conn, base_url: str, cfg: dict) -> None:
    from .fleet import HollowFleet

    start_concurrency = cfg.pop("start_concurrency", 32)
    ready_timeout = cfg.pop("ready_timeout", 120.0)
    # Big shards poll the barrier less often: each poll LISTs (and
    # decodes) the entire node fleet, and four workers hammering that
    # every second would slow the very boots being waited on.
    ready_poll = cfg.pop(
        "ready_poll", max(1.0, cfg.get("n_nodes", 0) / 500.0))
    fleet = HollowFleet(base_url, **cfg)
    try:
        await fleet.start(start_concurrency=start_concurrency)
        await fleet.wait_ready(timeout=ready_timeout, poll=ready_poll)
        conn.send(("ready", fleet.stats()))
    except Exception as exc:  # noqa: BLE001 — shipped to the parent
        conn.send(("error", repr(exc)))
        try:
            await fleet.stop()
        finally:
            conn.close()
        return
    loop = asyncio.get_running_loop()
    while True:
        cmd = await loop.run_in_executor(None, conn.recv)
        if cmd == "stats":
            conn.send(("stats", fleet.stats()))
        elif cmd == "stop":
            await fleet.stop()
            conn.send(("stopped", {}))
            conn.close()
            return


class ProcFleet:
    """``n_nodes`` hollow nodes sharded over ``n_procs`` workers.

    Node names are ``<prefix>-w<k>-<i>`` so every shard's readiness
    barrier counts only its own nodes. ``node_kw`` passes through to
    each shard's :class:`HollowFleet`."""

    def __init__(self, base_url: str, n_nodes: int, n_procs: int = 2,
                 name_prefix: str = "hollow", **node_kw):
        if n_procs < 1:
            raise ValueError("n_procs must be >= 1")
        self.base_url = base_url
        self.n_nodes = n_nodes
        self.n_procs = n_procs
        self.name_prefix = name_prefix
        self.node_kw = node_kw
        self._procs: list = []
        self._conns: list = []
        self._ready_stats: list[dict] = []

    def _shard_sizes(self) -> list[int]:
        base, rem = divmod(self.n_nodes, self.n_procs)
        return [base + (1 if i < rem else 0) for i in range(self.n_procs)]

    async def start(self, start_concurrency: int = 32,
                    ready_timeout: float = 120.0) -> float:
        """Spawn the workers and block on every shard's readiness
        barrier; return wall seconds until the LAST shard was ready."""
        ctx = mp.get_context("spawn")
        t0 = time.monotonic()
        for idx, count in enumerate(self._shard_sizes()):
            if count == 0:
                continue
            parent, child = ctx.Pipe()
            cfg = dict(self.node_kw,
                       n_nodes=count,
                       name_prefix=f"{self.name_prefix}-w{idx}",
                       start_concurrency=start_concurrency,
                       ready_timeout=ready_timeout)
            proc = ctx.Process(target=_worker_main,
                               args=(child, self.base_url, cfg),
                               daemon=True, name=f"hollow-w{idx}")
            proc.start()
            child.close()
            self._procs.append(proc)
            self._conns.append(parent)
        loop = asyncio.get_running_loop()

        async def wait_ready(conn):
            # spawn re-imports the package per worker; the barrier
            # budget covers boot + import, with slack for the parent's
            # own loop being busy serving the boots.
            kind, payload = await asyncio.wait_for(
                loop.run_in_executor(None, conn.recv),
                timeout=ready_timeout + 60.0)
            if kind != "ready":
                raise RuntimeError(f"hollow worker failed: {payload}")
            return payload

        try:
            self._ready_stats = list(await asyncio.gather(
                *(wait_ready(c) for c in self._conns)))
        except BaseException:
            self.kill()
            raise
        return time.monotonic() - t0

    async def _rpc(self, conn, cmd: str, timeout: float) -> Optional[dict]:
        loop = asyncio.get_running_loop()
        conn.send(cmd)
        kind, payload = await asyncio.wait_for(
            loop.run_in_executor(None, conn.recv), timeout=timeout)
        if kind == "error":
            raise RuntimeError(f"hollow worker failed: {payload}")
        return payload

    async def stats(self, timeout: float = 30.0) -> list[dict]:
        """One budget snapshot per live worker shard."""
        return list(await asyncio.gather(
            *(self._rpc(c, "stats", timeout) for c in self._conns)))

    async def stop(self, timeout: float = 120.0) -> None:
        try:
            await asyncio.gather(
                *(self._rpc(c, "stop", timeout) for c in self._conns),
                return_exceptions=True)
        finally:
            for proc in self._procs:
                proc.join(timeout=10.0)
            self.kill()

    def kill(self) -> None:
        """Hard teardown — also the failure path, so it never raises."""
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass
        for proc in self._procs:
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=5.0)
        self._procs, self._conns = [], []
