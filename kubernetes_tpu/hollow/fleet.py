"""Hollow-node fleet — the kubemark analog, grown into a subsystem.

Reference: ``cmd/kubemark/hollow-node.go`` + ``pkg/kubemark/
hollow_kubelet.go:49`` — a real kubelet wired to a fake docker client
and mock cadvisor, deployed by the hundreds so control-plane scale
runs (``test/e2e/scalability/``) need no real machines.

Here a hollow node is the *real* :class:`NodeAgent` — sync loop, PLEG,
per-pod workers, status posts, heartbeat Lease, and a per-node pod
watch with a ``spec.node_name`` field selector (so apiserver watcher
count equals node count) — over :class:`FakeRuntime` (containers "run"
instantly) and :class:`StaticDeviceManager` (fixed stub topology, no
gRPC socket). What makes thousands of them fit in one process:

- **shared aiohttp session** (one unbounded connector per fleet shard)
  instead of a session + connector pool per node;
- **shared services informer** — one services watch per shard, not one
  per node;
- **slim agents** (``NodeAgent(slim=True)``): no problem detector, no
  container GC, no dynamic config — subsystems that exist for real
  hosts, with zero wire-visible traffic of their own (the parity test
  in ``tests/integration/test_hollow_parity.py`` holds that line);
- **phase jitter**: status/heartbeat loops offset deterministically
  per node so a fleet booted in one burst never renews all its leases
  in the same scheduling bucket (no thundering herd by construction —
  ``fleet_bench`` measures the storm both ways);
- **stretched worker resync** — 100k idle pod workers on a 2 s backstop
  would wake 50k times/s fleet-wide for nothing.

:class:`HollowFleet` is one shard on the current event loop;
:mod:`kubernetes_tpu.hollow.proc` multiplexes shards over worker
processes. Both report RSS / fd / boot-latency budgets through the
``hollow_fleet_*`` metric families.
"""
from __future__ import annotations

import asyncio
import os
import time
from typing import Optional

import aiohttp

from ..api import types as t
from ..client.informer import SharedInformer
from ..client.rest import RESTClient
from ..metrics.registry import REGISTRY as METRICS  # noqa: F401 (re-export)
from ..metrics.registry import Gauge, Histogram
from ..node.agent import NodeAgent
from ..node.runtime import FakeRuntime
from .device import StaticDeviceManager, hollow_topology

FLEET_NODES = Gauge(
    "hollow_fleet_nodes",
    "Hollow nodes in this fleet shard by lifecycle state "
    "(started = agent boot finished; ready = Ready per apiserver).",
    labels=("state",))
FLEET_RSS = Gauge(
    "hollow_fleet_rss_bytes",
    "Resident set size of this fleet shard's process.")
FLEET_FDS = Gauge(
    "hollow_fleet_open_fds",
    "Open file descriptors in this fleet shard's process.")
NODE_START = Histogram(
    "hollow_fleet_node_start_seconds",
    "Per-node agent boot latency (register + informer sync + loops).",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0),
    sample_limit=10_000)

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def rss_bytes() -> int:
    """Resident set size of this process from /proc/self/statm."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, IndexError, ValueError):
        return 0


def open_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


class HollowFleet:
    """N hollow node agents against one apiserver URL, on one loop.

    ``phase_jitter=None`` (default) spreads each periodic loop across
    its full interval; pass ``0.0`` to boot a deliberately phase-locked
    fleet (the thundering-herd control arm). ``share_session=True``
    multiplexes every node's HTTP + watch traffic over one connector;
    per-node watch streams still hold one socket each (the connector is
    unbounded for that reason)."""

    def __init__(self, base_url: str, n_nodes: int, tpu_chips: int = 0,
                 status_interval: float = 10.0,
                 heartbeat_interval: float = 5.0,
                 pleg_interval: float = 2.0,
                 name_prefix: str = "hollow",
                 slim: bool = True,
                 phase_jitter: Optional[float] = None,
                 worker_resync: float = 15.0,
                 share_session: bool = True):
        self.base_url = base_url
        self.n_nodes = n_nodes
        self.tpu_chips = tpu_chips
        self.status_interval = status_interval
        self.heartbeat_interval = heartbeat_interval
        self.pleg_interval = pleg_interval
        self.name_prefix = name_prefix
        self.slim = slim
        self.phase_jitter = (max(status_interval, heartbeat_interval)
                             if phase_jitter is None else phase_jitter)
        self.worker_resync = worker_resync
        self.share_session = share_session
        self.agents: list[NodeAgent] = []
        self._clients: list[RESTClient] = []
        self._session: Optional[aiohttp.ClientSession] = None
        self._fleet_client: Optional[RESTClient] = None
        self._svc_informer: Optional[SharedInformer] = None

    # -- lifecycle --------------------------------------------------------

    def _client(self) -> RESTClient:
        if self._session is not None:
            return RESTClient(self.base_url, session=self._session)
        return RESTClient(self.base_url)

    async def start(self, start_concurrency: int = 32) -> None:
        if self.share_session:
            # One connector for the whole shard. Unbounded: each node's
            # pod watch parks a connection for its lifetime, so any
            # limit below n_nodes deadlocks the boot.
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0, limit_per_host=0))
        self._fleet_client = self._client()
        self._svc_informer = SharedInformer(self._fleet_client, "services")
        self._svc_informer.start()
        await self._svc_informer.wait_for_sync()

        names = [f"{self.name_prefix}-{i:04d}" for i in range(self.n_nodes)]
        it = iter(names)

        async def worker():
            for name in it:
                dm = (StaticDeviceManager(hollow_topology(name, self.tpu_chips))
                      if self.tpu_chips else None)
                client = self._client()
                agent = NodeAgent(
                    client, name, FakeRuntime(), device_manager=dm,
                    status_interval=self.status_interval,
                    heartbeat_interval=self.heartbeat_interval,
                    pleg_interval=self.pleg_interval,
                    server_port=None,  # 5000 HTTP servers would be silly
                    slim=self.slim,
                    phase_jitter=self.phase_jitter,
                    worker_resync=self.worker_resync,
                    services_informer=self._svc_informer)
                t0 = time.monotonic()
                await agent.start()
                NODE_START.observe(time.monotonic() - t0)
                self.agents.append(agent)
                self._clients.append(client)
                FLEET_NODES.set(float(len(self.agents)), state="started")
        await asyncio.gather(*(worker() for _ in range(start_concurrency)))
        self.sample()

    async def wait_ready(self, timeout: float = 120.0,
                         poll: float = 1.0) -> float:
        """Fleet-wide readiness barrier: block until every node of this
        shard is Ready per the apiserver; return elapsed seconds."""
        assert self._fleet_client is not None, "call start() first"
        prefix = f"{self.name_prefix}-"
        t0 = time.monotonic()
        deadline = t0 + timeout
        while True:
            nodes, _ = await self._fleet_client.list("nodes")
            ready = sum(
                1 for n in nodes
                if n.metadata.name.startswith(prefix)
                and (c := t.get_node_condition(n.status, t.NODE_READY))
                is not None and c.status == "True")
            FLEET_NODES.set(float(ready), state="ready")
            if ready >= self.n_nodes:
                return time.monotonic() - t0
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"{ready}/{self.n_nodes} hollow nodes Ready "
                    f"after {timeout:.0f}s")
            await asyncio.sleep(poll)

    # -- accounting -------------------------------------------------------

    def sample(self) -> None:
        """Refresh the process-budget gauges (RSS / fds)."""
        FLEET_RSS.set(float(rss_bytes()))
        FLEET_FDS.set(float(open_fds()))

    def stats(self) -> dict:
        """Picklable budget snapshot — what proc.py ships over the pipe
        and fleet_bench folds into its report."""
        self.sample()
        qs = NODE_START.raw_quantiles((0.5, 0.99)) or [0.0, 0.0]
        return {
            "nodes": len(self.agents),
            "ready": int(FLEET_NODES.value(state="ready")),
            "rss_bytes": rss_bytes(),
            "open_fds": open_fds(),
            "node_start_p50_s": qs[0],
            "node_start_p99_s": qs[1],
            "pid": os.getpid(),
        }

    async def stop(self) -> None:
        async def stop_one(agent: NodeAgent, client: RESTClient):
            try:
                await agent.stop()
            finally:
                await client.close()  # no-op for shared sessions
        await asyncio.gather(
            *(stop_one(a, c) for a, c in zip(self.agents, self._clients)),
            return_exceptions=True)
        self.agents, self._clients = [], []
        if self._svc_informer is not None:
            await self._svc_informer.stop()
            self._svc_informer = None
        if self._fleet_client is not None:
            await self._fleet_client.close()
            self._fleet_client = None
        if self._session is not None:
            await self._session.close()
            self._session = None
