"""Hollow-node fleet subsystem — see :mod:`kubernetes_tpu.hollow.fleet`
for what a hollow node is (and deliberately is not)."""
from .device import StaticDeviceManager, hollow_topology
from .fleet import HollowFleet, open_fds, rss_bytes
from .proc import ProcFleet

__all__ = ["HollowFleet", "ProcFleet", "StaticDeviceManager",
           "hollow_topology", "open_fds", "rss_bytes"]
