"""kloopsan — event-loop occupancy sanitizer (``TPU_LOOPSAN=1``).

The dynamic half of the loop-occupancy discipline (the static half is
the ``hot-path-cost`` tpuvet pass): armed, every asyncio callback the
loop runs is timed at the ``Handle._run`` choke point — the same place
asyncio's own debug-mode ``slow_callback_duration`` hooks — and its
CPU time is charged to a named **seam**: owning component + coroutine
qualname. Think of it as a deterministic, always-on
``slow_callback_duration`` with attribution instead of a log line.

Attribution, per callback:

- A ``Task.__step`` callback is introspected through its coroutine
  await chain (``cr_await``/``gi_yieldfrom``) — the FIRST repo frame
  names the owning component, the DEEPEST repo frame names the stage
  the step resumed in (so an apiserver request parked inside aiohttp
  still charges to ``apiserver:_batch_create``, and a scheduler step
  parked in ``pop_batch`` charges to the queue stage, not just "the
  scheduler").
- A plain function callback charges to its ``__code__`` location
  (``functools.partial`` unwrapped).
- The curated :data:`SEAM_MAP` overrides the derived name for the
  seams the occupancy table is read by: the scheduler loop, apiserver
  handlers, the MVCC write path, informer ``_notify``, the admission
  pass, and the watch fan-out.
- Code outside the repo (aiohttp's HTTP parse/write machinery gets its
  own ``apiserver.http`` seam) falls into the ``other:*`` bucket —
  the *unattributed* share the density gate bounds.

Synchronous hot regions that never appear at a resume point (the
admission pass, the MVCC write, informer ``_notify`` fan-out) carve
their time out of the enclosing callback through :func:`seam` — a
nested-span stack per thread, so a batchCreate handler's charge
decomposes into handler self-time + admission + mvcc.

Callbacks whose TOTAL time exceeds the threshold
(``TPU_LOOPSAN_SLOW_MS``, default 100ms) are recorded as violations
with a source-located stack — ``hack/race.sh`` arms this and asserts
zero.

Seam names derive purely from code objects (file path + qualname), so
they are deterministic across runs and under ``TPU_SAN`` explored
schedules.

Disarmed (the default): :func:`maybe_arm` is a no-op, ``Handle._run``
stays the untouched stdlib attribute (tests assert identity), and
:func:`seam` returns a shared no-op context manager — one dict-free
function call per site.
"""
from __future__ import annotations

import asyncio
import functools
import os
import threading
import time
from typing import Iterable, Optional

from ..metrics.registry import Counter, Gauge

ENV_VAR = "TPU_LOOPSAN"
THRESHOLD_ENV = "TPU_LOOPSAN_SLOW_MS"
DEFAULT_SLOW_MS = 100.0

#: Violation list is bounded: a pathological run must not balloon the
#: sanitizer's own memory (the count keeps climbing in the metric).
MAX_VIOLATIONS = 200

LOOPSAN_BUSY = Gauge(
    "loopsan_seam_busy_seconds",
    "CPU seconds the event loop spent in each attributed seam "
    "(published at snapshot time, armed only)", labels=("seam",))
LOOPSAN_CALLS = Gauge(
    "loopsan_seam_calls",
    "loop callbacks / nested spans charged to each seam",
    labels=("seam",))
LOOPSAN_VIOLATIONS = Counter(
    "loopsan_violations_total",
    "loop callbacks whose total time exceeded TPU_LOOPSAN_SLOW_MS",
    labels=("seam",))

#: Repo package root ( .../kubernetes_tpu ) — frames under it are
#: attributable; everything else is other:* or a curated foreign seam.
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Curated seam map: (path suffix, qualname prefix or "", seam name).
#: First match wins, scanned over every repo frame in the await chain
#: deepest-first — so the fine-grained stage seams (queue, mvcc) beat
#: the generic component fallback. Keep this list short and READABLE:
#: it is the vocabulary of the occupancy table.
SEAM_MAP: tuple[tuple[str, str, str], ...] = (
    ("scheduler/queue.py", "", "scheduler.queue"),
    ("scheduler/scheduler.py", "Scheduler._run", "scheduler.loop"),
    ("storage/mvcc.py", "", "mvcc.write"),
    ("client/informer.py", "SharedInformer._notify", "informer.notify"),
    ("client/informer.py", "", "informer"),
    ("apiserver/admission.py", "", "admission.pass"),
    ("apiserver/fanout.py", "", "apiserver.fanout"),
)

#: Dispatch shims skipped when picking the deepest repo frame — they
#: wrap every request and would otherwise name every handler the same.
_SHIM_QUALNAMES = ("_middleware", "_run_handler")

#: Foreign (non-repo) code granted a named seam instead of other:*.
_FOREIGN_SEAMS: tuple[tuple[str, str], ...] = (
    (os.sep + "aiohttp" + os.sep, "apiserver.http"),
)

_perf = time.perf_counter

# ---------------------------------------------------------------------------
# per-thread accumulation
# ---------------------------------------------------------------------------


class _Frame:
    __slots__ = ("seam", "start", "child")

    def __init__(self, seam: str, start: float):
        self.seam = seam
        self.start = start
        self.child = 0.0


class _ThreadState(threading.local):
    def __init__(self):
        self.stack: list[_Frame] = []
        #: seam -> [calls, busy_s, max_s]
        self.stats: dict[str, list] = {}
        with _states_lock:
            _states.append(self.stats)


_states: list[dict] = []      # every thread's stats dict, for merging
_states_lock = threading.Lock()
_tls = _ThreadState()

_armed = False
_orig_handle_run = None
_threshold_s = DEFAULT_SLOW_MS / 1000.0
_violations: list[dict] = []
_violations_lock = threading.Lock()

#: code object -> (is_repo, relpath, component, curated seam or None)
_code_cache: dict = {}


def _charge(stats: dict, seam: str, elapsed: float) -> None:
    s = stats.get(seam)
    if s is None:
        stats[seam] = [1, elapsed, elapsed]
        return
    s[0] += 1
    s[1] += elapsed
    if elapsed > s[2]:
        s[2] = elapsed


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------


def _code_info(code, qualname: str):
    """(is_repo, relpath, component, curated seam) for one code object,
    cached — the curated scan runs once per distinct code object ever
    seen, not per callback."""
    hit = _code_cache.get(code)
    if hit is not None:
        return hit
    fn = code.co_filename
    if fn.startswith(_PKG_ROOT):
        rel = fn[len(_PKG_ROOT) + 1:].replace(os.sep, "/")
        component = rel.split("/", 1)[0]
        curated = None
        for suffix, qprefix, seam_name in SEAM_MAP:
            if rel.endswith(suffix) and (not qprefix
                                         or qualname.startswith(qprefix)):
                curated = seam_name
                break
        info = (True, rel, component, curated)
    else:
        foreign = None
        for marker, seam_name in _FOREIGN_SEAMS:
            if marker in fn:
                foreign = seam_name
                break
        info = (False, fn, foreign or "", None)
    _code_cache[code] = info
    return info


def _await_chain(coro) -> Iterable[tuple]:
    """(code, qualname, frame) down the suspended await chain; bounded
    depth so a pathological chain cannot stall the wrapper."""
    for _ in range(64):
        if coro is None:
            return
        code = getattr(coro, "cr_code", None)
        frame = None
        if code is not None:
            frame = coro.cr_frame
            nxt = coro.cr_await
        else:
            code = getattr(coro, "gi_code", None)
            if code is not None:
                frame = coro.gi_frame
                nxt = coro.gi_yieldfrom
            else:
                code = getattr(coro, "ag_code", None)
                if code is None:
                    return  # a Future or foreign awaitable: chain ends
                frame = getattr(coro, "ag_frame", None)
                nxt = getattr(coro, "ag_await", None)
        yield code, getattr(coro, "__qualname__", code.co_name), frame
        coro = nxt


def _attribute(callback) -> tuple[str, list]:
    """(seam, stack) for one Handle callback. ``stack`` is the repo
    portion of the await chain as ``file:line qualname`` strings —
    stored only on violations, but computed inline (it is just the
    frames already walked)."""
    cb = callback
    while isinstance(cb, functools.partial):
        cb = cb.func
    owner = getattr(cb, "__self__", None)
    get_coro = getattr(owner, "get_coro", None)
    chain: list[tuple] = []
    if get_coro is not None:          # a Task.__step: walk the coroutine
        try:
            chain = list(_await_chain(get_coro()))
        except Exception:  # noqa: BLE001 — attribution must never raise
            chain = []
    elif getattr(cb, "__code__", None) is not None:
        chain = [(cb.__code__, getattr(cb, "__qualname__",
                                       cb.__code__.co_name), None)]
    if not chain:
        return f"other:{getattr(cb, '__qualname__', repr(cb))}", []

    stack: list[str] = []
    curated = None
    first_component = ""
    deepest_repo = None
    foreign = ""
    for code, qualname, frame in chain:
        is_repo, rel, component, cur = _code_info(code, qualname)
        if is_repo:
            line = frame.f_lineno if frame is not None else code.co_firstlineno
            stack.append(f"{rel}:{line} {qualname}")
            if not first_component:
                first_component = component
            if qualname.rpartition(".")[2] not in _SHIM_QUALNAMES:
                deepest_repo = (component, qualname)
            if cur is not None:
                curated = cur  # deepest curated match wins
        elif component and not foreign:
            foreign = component  # a _FOREIGN_SEAMS name, e.g. apiserver.http
    if curated is not None:
        return curated, stack
    if deepest_repo is not None:
        return f"{deepest_repo[0]}:{deepest_repo[1]}", stack
    if foreign:
        return foreign, stack
    root_q = chain[0][1]
    return f"other:{root_q}", stack


# ---------------------------------------------------------------------------
# the Handle._run wrapper (installed only when armed)
# ---------------------------------------------------------------------------


def _instrumented_run(self):
    seam, vstack = _attribute(self._callback)
    tls = _tls
    frame = _Frame(seam, _perf())
    tls.stack.append(frame)
    try:
        return _orig_handle_run(self)
    finally:
        tls.stack.pop()
        elapsed = _perf() - frame.start
        _charge(tls.stats, seam, elapsed - frame.child)
        if tls.stack:
            tls.stack[-1].child += elapsed
        if elapsed > _threshold_s:
            LOOPSAN_VIOLATIONS.inc(seam=seam)
            with _violations_lock:
                if len(_violations) < MAX_VIOLATIONS:
                    _violations.append({
                        "seam": seam, "ms": round(elapsed * 1000.0, 3),
                        "stack": vstack})


class _NullSeam:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SEAM = _NullSeam()


class _SeamSpan:
    """Nested synchronous span: charges its self-time to ``name`` and
    folds its total into the parent frame's child time. Inert when the
    thread is not inside an instrumented loop callback — off-loop work
    (a durable store's to_thread write) is not loop occupancy."""

    __slots__ = ("name", "_frame")

    def __init__(self, name: str):
        self.name = name
        self._frame = None

    def __enter__(self):
        if _tls.stack:
            self._frame = _Frame(self.name, _perf())
            _tls.stack.append(self._frame)
        return self

    def __exit__(self, *exc):
        frame = self._frame
        if frame is not None:
            tls = _tls
            tls.stack.pop()
            elapsed = _perf() - frame.start
            _charge(tls.stats, frame.seam, elapsed - frame.child)
            if tls.stack:
                tls.stack[-1].child += elapsed
        return False


def seam(name: str):
    """Carve a named synchronous region out of the enclosing loop
    callback's charge (admission pass, MVCC write, informer notify).
    Disarmed this is one shared no-op context manager — no allocation,
    no timing."""
    if not _armed:
        return _NULL_SEAM
    return _SeamSpan(name)


# ---------------------------------------------------------------------------
# arming / reporting
# ---------------------------------------------------------------------------


def loopsan_requested() -> bool:
    return os.environ.get(ENV_VAR, "").lower() in ("1", "true", "yes", "on")


def enabled() -> bool:
    return _armed


def arm(threshold_ms: Optional[float] = None) -> None:
    """Patch ``asyncio.events.Handle._run``. Idempotent. Explicit entry
    for tests; production paths go through :func:`maybe_arm`."""
    global _armed, _orig_handle_run, _threshold_s
    if threshold_ms is None:
        threshold_ms = float(os.environ.get(THRESHOLD_ENV, DEFAULT_SLOW_MS))
    _threshold_s = threshold_ms / 1000.0
    if _armed:
        return
    _orig_handle_run = asyncio.events.Handle._run
    asyncio.events.Handle._run = _instrumented_run
    _armed = True


def disarm() -> None:
    """Restore the stdlib ``Handle._run`` (test isolation)."""
    global _armed
    if not _armed:
        return
    asyncio.events.Handle._run = _orig_handle_run
    _armed = False


def maybe_arm() -> bool:
    """Arm iff ``TPU_LOOPSAN`` is set — called from the apiserver and
    scheduler startup paths; a one-env-check no-op disarmed."""
    if loopsan_requested():
        arm()
        return True
    return _armed


def reset() -> None:
    """Zero all accumulated stats and violations (run isolation)."""
    with _states_lock:
        for stats in _states:
            stats.clear()
    with _violations_lock:
        _violations.clear()


def snapshot(top: int = 0) -> dict:
    """Merge every thread's per-seam stats into the ranked occupancy
    report: total busy, attributed share, per-seam rows, violations.
    ``top`` > 0 truncates the seam table (the full charge still counts
    toward the totals)."""
    merged: dict[str, list] = {}
    with _states_lock:
        snap = [dict(s) for s in _states]
    for stats in snap:
        for seam_name, (calls, busy, mx) in stats.items():
            m = merged.get(seam_name)
            if m is None:
                merged[seam_name] = [calls, busy, mx]
            else:
                m[0] += calls
                m[1] += busy
                if mx > m[2]:
                    m[2] = mx
    total = sum(v[1] for v in merged.values())
    unattributed = sum(v[1] for k, v in merged.items()
                       if k.startswith("other:"))
    rows = [{"seam": k, "calls": v[0],
             "busy_s": round(v[1], 6), "max_ms": round(v[2] * 1000.0, 3),
             "share": round(v[1] / total, 4) if total else 0.0}
            for k, v in sorted(merged.items(),
                               key=lambda kv: -kv[1][1])]
    if top:
        rows = rows[:top]
    with _violations_lock:
        viol = list(_violations)
    return {
        "armed": _armed,
        "threshold_ms": _threshold_s * 1000.0,
        "total_busy_s": round(total, 6),
        "attributed_share": round((total - unattributed) / total, 4)
        if total else 1.0,
        "seams": rows,
        "violations": viol,
    }


def publish_metrics() -> dict:
    """Export the merged per-seam stats as ``loopsan_*`` gauges (the
    /debug/v1/loopprof handler and the perf harnesses call this so the
    metrics page and the JSON report agree) and return the snapshot."""
    snap = snapshot()
    for row in snap["seams"]:
        LOOPSAN_BUSY.set(row["busy_s"], seam=row["seam"])
        LOOPSAN_CALLS.set(float(row["calls"]), seam=row["seam"])
    return snap


def violations() -> list[dict]:
    with _violations_lock:
        return list(_violations)
