"""tpuvet + tpusan — repo-specific static and dynamic analysis.

Reference: the ``hack/verify-*.sh`` family plus ``go vet`` in the
make rules, and client-go's cache mutation detector
(``tools/cache/mutation_detector.go``) for the runtime side.

Static: the framework lives in :mod:`.tpuvet`; the repo-specific
passes in :mod:`.passes`. Dynamic ("tpusan"): :mod:`.interleave` is
the seeded task-interleaving explorer (``TPU_SAN=<seed>``),
:mod:`.invariants` the cluster-invariant sanitizer checked on every
MVCC write — together the deterministic-simulation tier ``hack/race.sh``
gates on. Run the static suite with ``python -m kubernetes_tpu.analysis``
(what ``hack/verify.sh`` does) or programmatically::

    from kubernetes_tpu.analysis import run_tree
    findings = run_tree("kubernetes_tpu")

Adding a pass: subclass :class:`~.tpuvet.Pass`, decorate with
:func:`~.tpuvet.register`, implement ``check_module`` (per-file) and/or
``finalize`` (cross-file), and add a good/bad fixture pair to
``tests/unit/test_tpuvet.py``.

The static framework loads LAZILY (PEP 562): production code imports
this package for the tpusan seams (``analysis.interleave.touch`` in the
store/scheduler hot paths, ``analysis.invariants`` at store
construction), and that import must not drag the whole AST linter onto
the apiserver/scheduler startup path.
"""
_STATIC = ("Finding", "Module", "Pass", "REGISTRY", "register",
           "run_source", "run_tree")

__all__ = list(_STATIC) + ["interleave", "invariants", "loopsan",
                           "passes", "tpuvet"]


def __getattr__(name):
    if name in _STATIC:
        from . import passes  # noqa: F401  (import registers the passes)
        from . import tpuvet
        return getattr(tpuvet, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
