"""tpuvet — repo-specific static analysis (the ``go vet`` analog).

Reference: the ``hack/verify-*.sh`` family plus ``go vet`` in the
make rules, and client-go's cache mutation detector
(``tools/cache/mutation_detector.go``) for the runtime side.

The framework lives in :mod:`.tpuvet`; the repo-specific passes in
:mod:`.passes`. Run the suite with ``python -m kubernetes_tpu.analysis``
(what ``hack/verify.sh`` does) or programmatically::

    from kubernetes_tpu.analysis import run_tree
    findings = run_tree("kubernetes_tpu")

Adding a pass: subclass :class:`~.tpuvet.Pass`, decorate with
:func:`~.tpuvet.register`, implement ``check_module`` (per-file) and/or
``finalize`` (cross-file), and add a good/bad fixture pair to
``tests/unit/test_tpuvet.py``.
"""
from .tpuvet import (Finding, Module, Pass, REGISTRY, register,  # noqa: F401
                     run_source, run_tree)
from . import passes  # noqa: F401  (imports register the passes)
