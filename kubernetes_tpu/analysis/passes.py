"""The repo-specific tpuvet passes.

Each pass encodes a correctness discipline the reference enforces
mechanically (``go vet``, ``hack/verify-*.sh``, the client-go mutation
detector) that plain Python gives us no compiler help with.
"""
from __future__ import annotations

import ast
import re
from typing import Iterable

from .tpuvet import Context, Finding, Module, Pass, register

# ---------------------------------------------------------------------------
# swallowed-exception
# ---------------------------------------------------------------------------

_BLANKET = {"Exception", "BaseException"}


def _is_blanket(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BLANKET
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BLANKET
                   for e in t.elts)
    return False


def _pure_swallow(body: list[ast.stmt]) -> bool:
    """True when the handler does nothing observable: only pass /
    continue / bare constants (docstrings, ``...``)."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue
        return False
    return True


@register
class SwallowedExceptionPass(Pass):
    name = "swallowed-exception"
    description = ("bare/blanket `except` whose body silently discards the "
                   "error (no logging, no re-raise, no handling)")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.ExceptHandler) and _is_blanket(node)
                    and _pure_swallow(node.body)):
                yield Finding(
                    mod.path, node.lineno, node.col_offset, self.name,
                    "blanket except swallows the error silently — log at "
                    "warning level with context, or narrow the exception "
                    "type")


# ---------------------------------------------------------------------------
# async-blocking
# ---------------------------------------------------------------------------

#: module.attr calls that block the event loop.
_BLOCKING_ATTR = {
    ("time", "sleep"),
    ("socket", "create_connection"),
    ("socket", "getaddrinfo"),
    ("socket", "gethostbyname"),
    ("subprocess", "run"),
    ("subprocess", "call"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("os", "system"),
    ("os", "popen"),
    ("urllib", "urlopen"),
    ("requests", "get"),
    ("requests", "post"),
    ("requests", "request"),
}


def _blocking_call_name(call: ast.Call) -> str:
    f = call.func
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and (f.value.id, f.attr) in _BLOCKING_ATTR):
        return f"{f.value.id}.{f.attr}"
    return ""


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Walks one ``async def`` body without descending into nested
    function definitions (a nested sync def / lambda is typically a
    thunk handed to ``run_in_executor`` / ``to_thread`` — off-loop)."""

    def __init__(self) -> None:
        self.hits: list[ast.Call] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return  # separate scope

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return  # visited on its own by the pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return

    def visit_Call(self, node: ast.Call) -> None:
        if _blocking_call_name(node):
            self.hits.append(node)
        self.generic_visit(node)


@register
class AsyncBlockingPass(Pass):
    name = "async-blocking"
    description = ("blocking call (time.sleep / sync subprocess / sync "
                   "socket or HTTP I/O) inside an `async def` body")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            v = _AsyncBodyVisitor()
            for stmt in node.body:
                v.visit(stmt)
            for call in v.hits:
                yield Finding(
                    mod.path, call.lineno, call.col_offset, self.name,
                    f"{_blocking_call_name(call)}() blocks the event loop "
                    f"inside async def {node.name}() — use the asyncio "
                    f"equivalent or run_in_executor")


# ---------------------------------------------------------------------------
# feature-gate
# ---------------------------------------------------------------------------

def _known_gates() -> set[str]:
    from ..util.features import KNOWN_FEATURES
    return set(KNOWN_FEATURES)


_GATE_RECEIVER_RE = re.compile(r"gate", re.IGNORECASE)


@register
class FeatureGatePass(Pass):
    name = "feature-gate"
    description = ("feature-gate string literal not registered in "
                   "util/features.py KNOWN_FEATURES")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        if mod.path.endswith("util/features.py"):
            return
        known = _known_gates()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in ("enabled", "set", "parse")):
                continue
            try:
                receiver = ast.unparse(f.value)
            except (ValueError, RecursionError):  # pragma: no cover
                continue
            if not _GATE_RECEIVER_RE.search(receiver):
                continue
            if not node.args or not isinstance(node.args[0], ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue
            lit = node.args[0].value
            names = ([p.partition("=")[0].strip()
                      for p in lit.split(",") if p.strip()]
                     if f.attr == "parse" else [lit])
            for gate in names:
                if gate and gate not in known:
                    yield Finding(
                        mod.path, node.lineno, node.col_offset, self.name,
                        f"unknown feature gate {gate!r} — register it in "
                        f"util/features.py KNOWN_FEATURES")


# ---------------------------------------------------------------------------
# metric-name
# ---------------------------------------------------------------------------

_METRIC_CTORS = {"Counter", "Gauge", "Histogram"}
_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _metric_ctor(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name) and f.id in _METRIC_CTORS:
        return f.id
    if isinstance(f, ast.Attribute) and f.attr in _METRIC_CTORS:
        return f.attr
    return ""


@register
class MetricNamePass(Pass):
    name = "metric-name"
    description = ("Prometheus metric name invalid, or registered from two "
                   "different sites (the registry is first-wins: the second "
                   "construction is silently inert)")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        if mod.path.endswith("metrics/registry.py"):
            return  # the primitives themselves, not a registration site
        sites = ctx.scratch(self.name).setdefault("sites", {})
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not _metric_ctor(node):
                continue
            arg = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "name":
                    arg = kw.value
            if not isinstance(arg, ast.Constant) or not isinstance(arg.value, str):
                continue
            mname = arg.value
            if not _METRIC_NAME_RE.match(mname):
                yield Finding(
                    mod.path, node.lineno, node.col_offset, self.name,
                    f"invalid Prometheus metric name {mname!r}")
            sites.setdefault(mname, []).append(
                (mod.path, node.lineno, node.col_offset))

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        sites = ctx.scratch(self.name).get("sites", {})
        for mname, where in sorted(sites.items()):
            if len(where) <= 1:
                continue
            first = f"{where[0][0]}:{where[0][1]}"
            for path, line, col in where[1:]:
                yield Finding(
                    path, line, col, self.name,
                    f"metric {mname!r} already registered at {first}; the "
                    f"registry is first-wins so this instance records "
                    f"nothing")


# ---------------------------------------------------------------------------
# cache-mutation
# ---------------------------------------------------------------------------

#: Methods whose result is a shared cached object (or list of them).
_CACHE_GETTERS = {"get", "list", "by_index", "bound_copy"}
#: Receiver must look like a cache for the getter to taint.
_CACHE_RECEIVER_RE = re.compile(
    r"(informer|lister|\.store\b|^store$|snapshot|\bcache\b)",
    re.IGNORECASE)
#: Container-mutators: flagged when invoked on (an attribute of) a
#: cached object, e.g. ``pod.metadata.labels.update(...)``.
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "remove", "discard", "clear", "sort"}


def _cache_getter_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if not isinstance(f, ast.Attribute) or f.attr not in _CACHE_GETTERS:
        return False
    try:
        receiver = ast.unparse(f.value)
    except (ValueError, RecursionError):  # pragma: no cover
        return False
    return bool(_CACHE_RECEIVER_RE.search(receiver))


def _root_name(node: ast.AST):
    """Name node at the base of an Attribute/Subscript chain, or None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _FunctionTaint(ast.NodeVisitor):
    """Track names bound from cache getters inside one function and flag
    in-place mutation through them. Conservatively heuristic: rebinding
    a name (``pod = deepcopy(pod)``) clears its taint."""

    def __init__(self, mod: Module, findings: list[Finding]):
        self.mod = mod
        self.findings = findings
        self.tainted: set[str] = set()       # names holding a cached object
        self.tainted_lists: set[str] = set() # names holding a cached list

    # -- taint sources ----------------------------------------------------

    def _bind(self, target: ast.expr, value: ast.expr) -> None:
        if not isinstance(target, ast.Name):
            return
        if _cache_getter_call(value):
            attr = value.func.attr  # type: ignore[union-attr]
            (self.tainted_lists if attr in ("list", "by_index")
             else self.tainted).add(target.id)
            return
        # Iterating / indexing a cached list yields cached objects.
        if (isinstance(value, ast.Subscript)
                and isinstance(value.value, ast.Name)
                and value.value.id in self.tainted_lists):
            self.tainted.add(target.id)
            return
        # Any other rebind launders the name (deepcopy, fresh object...).
        self.tainted.discard(target.id)
        self.tainted_lists.discard(target.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        # Flag mutations first (the value may read a tainted name).
        for target in node.targets:
            self._flag_store(target)
        for target in node.targets:
            if isinstance(target, ast.Tuple):
                for elt in target.elts:
                    self._bind(elt, node.value)
            else:
                self._bind(target, node.value)
        # visit (not generic_visit): a mutator call can BE the value
        # expression (x = pod.metadata.labels.pop("stale")).
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._flag_store(node.target)
            self._bind(node.target, node.value)
            self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._flag_store(node.target)
        self.visit(node.value)

    def visit_For(self, node: ast.For) -> None:
        it = node.iter
        if isinstance(node.target, ast.Name):
            if _cache_getter_call(it) and it.func.attr in ("list", "by_index"):  # type: ignore[union-attr]
                self.tainted.add(node.target.id)
            elif isinstance(it, ast.Name) and it.id in self.tainted_lists:
                self.tainted.add(node.target.id)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    # -- taint sinks ------------------------------------------------------

    def _flag_store(self, target: ast.expr) -> None:
        if isinstance(target, ast.Tuple):
            for elt in target.elts:
                self._flag_store(elt)
            return
        if not isinstance(target, (ast.Attribute, ast.Subscript)):
            return
        root = _root_name(target)
        if root in self.tainted:
            self.findings.append(Finding(
                self.mod.path, target.lineno, target.col_offset,
                CacheMutationPass.name,
                f"in-place mutation of cached object {root!r} obtained "
                f"from an informer/scheduler cache — deepcopy before "
                f"modifying (shared-cache corruption)"))

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS
                and isinstance(f.value, (ast.Attribute, ast.Subscript))):
            root = _root_name(f.value)
            if root in self.tainted:
                self.findings.append(Finding(
                    self.mod.path, node.lineno, node.col_offset,
                    CacheMutationPass.name,
                    f"{f.attr}() mutates cached object {root!r} obtained "
                    f"from an informer/scheduler cache in place — deepcopy "
                    f"before modifying"))
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._flag_store(target)

    # Nested defs get their own fresh scope via the pass driver.
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return


@register
class CacheMutationPass(Pass):
    name = "cache-mutation"
    description = ("in-place mutation of an object obtained from an "
                   "informer / scheduler cache (shared-cache corruption: "
                   "every other consumer sees the edit)")

    #: The cache layers themselves own their objects; consumers don't.
    _SELF_PATHS = ("client/informer.py", "scheduler/cache.py",
                   "analysis/")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        if any(p in mod.path for p in self._SELF_PATHS):
            return ()
        findings: list[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                v = _FunctionTaint(mod, findings)
                for stmt in node.body:
                    v.visit(stmt)
        return findings


# ---------------------------------------------------------------------------
# task-leak
# ---------------------------------------------------------------------------

_SPAWNERS = {"create_task", "ensure_future"}


def _is_spawn_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return ((isinstance(f, ast.Attribute) and f.attr in _SPAWNERS)
            or (isinstance(f, ast.Name) and f.id in _SPAWNERS))


@register
class TaskLeakPass(Pass):
    name = "task-leak"
    description = ("fire-and-forget asyncio.create_task/ensure_future "
                   "whose Task is discarded: the loop holds tasks only "
                   "weakly (the task can be GC'd mid-flight) and a crash "
                   "inside it is swallowed — retain it and handle the "
                   "exception (util/tasks.py spawn())")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        if mod.path.endswith("util/tasks.py"):
            return  # the remediation helper itself
        for node in ast.walk(mod.tree):
            # Bare statement: the Task is dropped on the floor.
            if isinstance(node, ast.Expr) and _is_spawn_call(node.value):
                yield Finding(
                    mod.path, node.lineno, node.col_offset, self.name,
                    "create_task() result discarded — the task may be "
                    "GC'd mid-flight and its exception is swallowed; "
                    "use util.tasks.spawn() or retain + add_done_callback")
            # A lambda returning the task hands it to a caller that
            # discards it (call_later(cb) ignores cb's return value).
            elif isinstance(node, ast.Lambda) and _is_spawn_call(node.body):
                yield Finding(
                    mod.path, node.lineno, node.col_offset, self.name,
                    "lambda spawns a task whose handle the caller "
                    "discards — same leak as a bare create_task(); use "
                    "util.tasks.spawn() inside the lambda")


# ---------------------------------------------------------------------------
# informer-mutation (interprocedural)
# ---------------------------------------------------------------------------


class _ParamMutation(ast.NodeVisitor):
    """Which of one function's parameters does its body mutate in
    place? (Attribute/Subscript stores, container mutators, del —
    through the parameter name, unless the name is rebound first.)
    Also records parameter pass-through call edges for the transitive
    fixpoint."""

    def __init__(self, params: list[str]):
        self.live = set(params)       # params not yet rebound
        self.order = list(params)
        self.mutated: set[str] = set()
        #: (callee simple name, callee arg index, own param name)
        self.passes: list[tuple[str, int, str]] = []

    def _root(self, node):
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    def _flag_store(self, target) -> None:
        if isinstance(target, ast.Tuple):
            for elt in target.elts:
                self._flag_store(elt)
            return
        if isinstance(target, (ast.Attribute, ast.Subscript)):
            root = self._root(target)
            if root in self.live:
                self.mutated.add(root)

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._flag_store(target)
        self.visit(node.value)
        for target in node.targets:
            for elt in (target.elts if isinstance(target, ast.Tuple)
                        else [target]):
                if isinstance(elt, ast.Name):
                    self.live.discard(elt.id)  # rebound: laundered

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._flag_store(node.target)
            self.visit(node.value)
            if isinstance(node.target, ast.Name):
                self.live.discard(node.target.id)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._flag_store(node.target)
        self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._flag_store(target)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _MUTATORS:
                root = self._root(f.value)
                if root in self.live:
                    self.mutated.add(root)
            # Method pass-through: self.helper(param) — arg i maps to
            # the callee's param i+1 (past self).
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.Name) and arg.id in self.live:
                    self.passes.append((f.attr, i + 1, arg.id))
        elif isinstance(f, ast.Name):
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.Name) and arg.id in self.live:
                    self.passes.append((f.id, i, arg.id))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # separate scope
        return

    def visit_AsyncFunctionDef(self, node):
        return

    def visit_Lambda(self, node):
        return


def _fn_params(fn) -> list[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


class _CacheArgSites(ast.NodeVisitor):
    """Taint names bound from informer/cache getters (the
    cache-mutation source model) and record every call that passes a
    tainted name as a positional argument."""

    def __init__(self, mod: Module):
        self.mod = mod
        self.tainted: set[str] = set()
        self.tainted_lists: set[str] = set()
        #: (line, col, callee simple name, is_method, arg index, name)
        self.sites: list[tuple] = []

    def _bind(self, target, value) -> None:
        if not isinstance(target, ast.Name):
            return
        if _cache_getter_call(value):
            attr = value.func.attr  # type: ignore[union-attr]
            (self.tainted_lists if attr in ("list", "by_index")
             else self.tainted).add(target.id)
            return
        if (isinstance(value, ast.Subscript)
                and isinstance(value.value, ast.Name)
                and value.value.id in self.tainted_lists):
            self.tainted.add(target.id)
            return
        self.tainted.discard(target.id)
        self.tainted_lists.discard(target.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        for target in node.targets:
            if isinstance(target, ast.Tuple):
                for elt in target.elts:
                    self._bind(elt, node.value)
            else:
                self._bind(target, node.value)

    def visit_For(self, node: ast.For) -> None:
        it = node.iter
        if isinstance(node.target, ast.Name):
            if _cache_getter_call(it) and it.func.attr in ("list", "by_index"):  # type: ignore[union-attr]
                self.tainted.add(node.target.id)
            elif isinstance(it, ast.Name) and it.id in self.tainted_lists:
                self.tainted.add(node.target.id)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        callee = is_method = None
        if isinstance(f, ast.Attribute):
            callee, is_method = f.attr, True
        elif isinstance(f, ast.Name):
            callee, is_method = f.id, False
        if callee:
            for i, arg in enumerate(node.args):
                name = None
                if isinstance(arg, ast.Name) and arg.id in self.tainted:
                    name = arg.id
                if name is not None:
                    self.sites.append((node.lineno, node.col_offset,
                                       callee, is_method, i, name))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        return

    def visit_AsyncFunctionDef(self, node):
        return

    def visit_Lambda(self, node):
        return


@register
class InformerMutationPass(Pass):
    name = "informer-mutation"
    description = ("cached object handed to a function that mutates its "
                   "parameter in place (interprocedural cache-mutation: "
                   "the write happens one call away, past what the "
                   "per-function taint pass can see)")

    _SELF_PATHS = CacheMutationPass._SELF_PATHS

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        scratch = ctx.scratch(self.name)
        summaries = scratch.setdefault("summaries", {})
        sites = scratch.setdefault("sites", [])
        # Phase A: mutation summaries for every function/method.
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = _fn_params(node)
            v = _ParamMutation(params)
            for stmt in node.body:
                v.visit(stmt)
            is_method = bool(params) and params[0] in ("self", "cls")
            summaries.setdefault(node.name, []).append({
                "path": mod.path, "params": params,
                "mutated": v.mutated, "passes": v.passes,
                "is_method": is_method})
        # Phase B inputs: tainted-arg call sites (consumers only).
        if any(p in mod.path for p in self._SELF_PATHS):
            return ()
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                v = _CacheArgSites(mod)
                for stmt in node.body:
                    v.visit(stmt)
                for line, col, callee, is_method, i, name in v.sites:
                    sites.append((mod.path, line, col, callee,
                                  is_method, i, name))
        return ()

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        scratch = ctx.scratch(self.name)
        summaries = scratch.get("summaries", {})
        # Transitive closure: f passes param p to g at position j and g
        # mutates its j-th param => f mutates p.
        changed = True
        while changed:
            changed = False
            for cands in summaries.values():
                for s in cands:
                    for callee, j, pname in s["passes"]:
                        if pname in s["mutated"]:
                            continue
                        if self._position_mutated(summaries, s["path"],
                                                  callee, j):
                            s["mutated"].add(pname)
                            changed = True

        def param_index(is_method_call: bool, arg_i: int) -> int:
            return arg_i + 1 if is_method_call else arg_i

        for path, line, col, callee, is_method, i, name in \
                scratch.get("sites", []):
            j = param_index(is_method, i)
            if self._position_mutated(summaries, path, callee, j,
                                      method=is_method):
                yield Finding(
                    path, line, col, self.name,
                    f"cached object {name!r} passed to {callee}(), which "
                    f"mutates that parameter in place — hand it a "
                    f"deepcopy/dataclasses.replace copy instead "
                    f"(shared-cache corruption one call away)")

    @staticmethod
    def _position_mutated(summaries, caller_path: str, callee: str,
                          j: int, method: bool = None) -> bool:
        """Does (any plausible resolution of) ``callee`` mutate its
        j-th parameter? Same-module definitions win; cross-module
        matches count only when the name is unique tree-wide —
        ambiguous common names (update, get...) are skipped rather
        than guessed."""
        cands = summaries.get(callee, [])
        if method is not None:
            cands = [s for s in cands if s["is_method"] == method]
        if not cands:
            return False
        local = [s for s in cands if s["path"] == caller_path]
        pick = local if local else (cands if len(cands) == 1 else [])
        for s in pick:
            if j < len(s["params"]) and s["params"][j] in s["mutated"]:
                return True
        return False


# ---------------------------------------------------------------------------
# status-write (interprocedural)
# ---------------------------------------------------------------------------

#: Exception names that make a surrounding try a conflict guard.
_CONFLICT_GUARDS = {"ConflictError", "StatusError", "Exception",
                    "BaseException"}


def _is_status_write(node: ast.Call) -> bool:
    f = node.func
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr == "update_status":
        return True
    if f.attr == "update":
        for kw in node.keywords:
            if (kw.arg == "subresource"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value == "status"):
                return True
    return False


def _handler_names(handler: ast.ExceptHandler) -> set[str]:
    t = handler.type
    if t is None:
        return {"BaseException"}
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = set()
    for e in elts:
        if isinstance(e, ast.Name):
            out.add(e.id)
        elif isinstance(e, ast.Attribute):
            out.add(e.attr)
    return out


class _StatusWriteSites(ast.NodeVisitor):
    """Status-write call sites in one function body, with whether a
    lexically-enclosing try guards against write conflicts."""

    def __init__(self):
        self.sites: list[tuple[int, int, bool]] = []
        self._guard_depth = 0

    def visit_Try(self, node: ast.Try) -> None:
        guards = any(_handler_names(h) & _CONFLICT_GUARDS
                     for h in node.handlers)
        self._guard_depth += 1 if guards else 0
        for stmt in node.body:
            self.visit(stmt)
        self._guard_depth -= 1 if guards else 0
        # Handlers/else/finally are NOT under this try's guard.
        for h in node.handlers:
            for stmt in h.body:
                self.visit(stmt)
        for stmt in node.orelse + node.finalbody:
            self.visit(stmt)

    def visit_Call(self, node: ast.Call) -> None:
        if _is_status_write(node):
            self.sites.append((node.lineno, node.col_offset,
                               self._guard_depth > 0))
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        return

    def visit_AsyncFunctionDef(self, node):
        return


@register
class StatusWritePass(Pass):
    name = "status-write"
    description = ("status update without an rv-conflict guard: not "
                   "reachable from a controller sync() (whose worker "
                   "retries ConflictError) and not wrapped in a "
                   "try/except that handles the conflict — a stale "
                   "write either raises through an unprepared path or "
                   "silently loses")

    #: Method names whose callers retry on error even outside the
    #: Controller worker (reconcile-style loops that catch per cycle).
    _RETRY_ROOT = "sync"

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        if mod.path.endswith("client/interface.py"):
            return ()  # defines the write primitive; not a consumer
        scratch = ctx.scratch(self.name)
        per_class = scratch.setdefault("classes", [])
        loose = scratch.setdefault("functions", [])
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                base_names = set()
                for b in node.bases:
                    if isinstance(b, ast.Name):
                        base_names.add(b.id)
                    elif isinstance(b, ast.Attribute):
                        base_names.add(b.attr)
                methods = {}
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        methods[item.name] = self._analyze(item)
                per_class.append({"path": mod.path, "name": node.name,
                                  "bases": base_names, "methods": methods})
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                loose.append((mod.path, node.name, self._analyze(node)))
        return ()

    @staticmethod
    def _analyze(fn) -> dict:
        v = _StatusWriteSites()
        calls: set[str] = set()
        for stmt in fn.body:
            v.visit(stmt)
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                calls.add(node.func.attr)
        return {"sites": v.sites, "calls": calls}

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        scratch = ctx.scratch(self.name)
        #: Class names whose sync() is framework-retried: Controller
        #: subclasses (by base name — the worker catches ConflictError
        #: and requeues) plus the base itself.
        controllerish = {"Controller"}
        classes = scratch.get("classes", [])
        grew = True
        while grew:  # transitive subclasses, cross-module by name
            grew = False
            for c in classes:
                if c["name"] not in controllerish \
                        and c["bases"] & controllerish:
                    controllerish.add(c["name"])
                    grew = True
        for c in classes:
            retried = set()
            if c["name"] in controllerish and self._RETRY_ROOT in c["methods"]:
                frontier = [self._RETRY_ROOT]
                while frontier:
                    m = frontier.pop()
                    if m in retried or m not in c["methods"]:
                        continue
                    retried.add(m)
                    frontier.extend(c["methods"][m]["calls"])
            for mname, info in c["methods"].items():
                reachable = mname in retried
                for line, col, guarded in info["sites"]:
                    if guarded or reachable:
                        continue
                    yield Finding(
                        c["path"], line, col, self.name,
                        f"status write in {c['name']}.{mname}() has no "
                        f"conflict guard: not reachable from a "
                        f"controller sync() and not inside a try that "
                        f"handles ConflictError/StatusError — retry or "
                        f"route it through the reconcile loop")
        for path, fname, info in scratch.get("functions", []):
            for line, col, guarded in info["sites"]:
                if not guarded:
                    yield Finding(
                        path, line, col, self.name,
                        f"status write in {fname}() has no conflict "
                        f"guard — wrap in try/except ConflictError (or "
                        f"StatusError) with a retry")


# ---------------------------------------------------------------------------
# hot-path-cost (interprocedural)
# ---------------------------------------------------------------------------

#: Per-object control-plane hot paths: (path suffix, function name).
#: Anything costly reachable from these via the self-call-graph runs
#: once per pod/write/event at density scale — exactly the CPU the
#: loopsan occupancy table attributes at saturation (ROADMAP item 1).
_HOT_ROOTS = (
    ("scheduler/scheduler.py", "_schedule_one"),
    ("scheduler/scheduler.py", "_schedule_gang_inner"),
    ("scheduler/queue.py", "add_pod_sync"),
    ("scheduler/queue.py", "pop_batch"),
    ("apiserver/registry.py", "create"),
    ("apiserver/registry.py", "update"),
    ("apiserver/registry.py", "delete"),
    ("apiserver/registry.py", "create_batch"),
    ("apiserver/admission.py", "admit"),
    ("storage/mvcc.py", "_create"),
    ("storage/mvcc.py", "_update"),
    ("storage/mvcc.py", "_delete"),
    ("client/informer.py", "_notify_inner"),
    ("apiserver/fanout.py", "_run"),
)

#: module.attr calls that are per-call expensive on the loop.
_COSTLY_ATTR = {
    ("copy", "deepcopy"): "copy.deepcopy",
    ("json", "dumps"): "json.dumps",
    ("json", "loads"): "json.loads",
    ("pickle", "dumps"): "pickle.dumps",
    ("pickle", "loads"): "pickle.loads",
    ("time", "sleep"): "time.sleep",
    ("os", "fsync"): "os.fsync",
    ("compactcodec", "encode_wire"): "compactcodec.encode_wire",
    ("compactcodec", "encode_obj"): "compactcodec.encode_obj",
}

#: bare-name calls that are per-call expensive (sync file I/O, copy).
_COSTLY_NAME = {"deepcopy": "deepcopy", "open": "open"}


def _costly_op(call: ast.Call) -> str:
    f = call.func
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)):
        return _COSTLY_ATTR.get((f.value.id, f.attr), "")
    if isinstance(f, ast.Name):
        return _COSTLY_NAME.get(f.id, "")
    return ""


class _HotPathBody(ast.NodeVisitor):
    """Costly-op sites and outgoing call names for one function body.
    Nested defs/lambdas are skipped: the repo idiom hands expensive
    thunks to ``to_thread``/``run_in_executor``, which is off-loop."""

    def __init__(self) -> None:
        self.costly: list[tuple[int, int, str]] = []
        self.calls: set[str] = set()

    def visit_Call(self, node: ast.Call) -> None:
        op = _costly_op(node)
        if op:
            self.costly.append((node.lineno, node.col_offset, op))
        f = node.func
        if isinstance(f, ast.Attribute):
            self.calls.add(f.attr)
        elif isinstance(f, ast.Name):
            self.calls.add(f.id)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        return

    def visit_AsyncFunctionDef(self, node):
        return

    def visit_Lambda(self, node):
        return


@register
class HotPathCostPass(Pass):
    name = "hot-path-cost"
    description = ("deepcopy / json round-trip / full codec encode / "
                   "sleep / sync file-I/O reachable from a curated "
                   "per-object hot-path root (create, MVCC write, "
                   "admission, informer notify, scheduler loop, watch "
                   "fan-out): per-pod CPU on the event loop — batch "
                   "it, cache it, or move it off-loop")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        scratch = ctx.scratch(self.name)
        summaries = scratch.setdefault("summaries", {})
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            v = _HotPathBody()
            for stmt in node.body:
                v.visit(stmt)
            summaries.setdefault(node.name, []).append({
                "path": mod.path, "costly": v.costly, "calls": v.calls})
        return ()

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        scratch = ctx.scratch(self.name)
        summaries = scratch.get("summaries", {})
        #: (path, fn-name) -> summary, reached via resolvable edges.
        reached: dict = {}
        frontier: list[tuple[str, dict]] = []
        for suffix, root in _HOT_ROOTS:
            for s in summaries.get(root, []):
                if s["path"].endswith(suffix) \
                        and (s["path"], root) not in reached:
                    reached[(s["path"], root)] = f"{suffix}:{root}"
                    frontier.append((f"{suffix}:{root}", s))
        while frontier:
            via, s = frontier.pop()
            for callee in s["calls"]:
                for c in self._resolve(summaries, s["path"], callee):
                    key = (c["path"], callee)
                    if key not in reached:
                        reached[key] = via
                        frontier.append((via, c))
        emitted = set()
        for (path, fname), via in sorted(reached.items()):
            for s in summaries.get(fname, []):
                if s["path"] != path:
                    continue
                for line, col, op in s["costly"]:
                    if (path, line, col) in emitted:
                        continue
                    emitted.add((path, line, col))
                    yield Finding(
                        path, line, col, self.name,
                        f"{op}() in {fname}() is reachable from "
                        f"hot-path root {via}: per-object cost on the "
                        f"event loop — batch per chunk, cache the "
                        f"result, or move it off-loop (to_thread)")

    @staticmethod
    def _resolve(summaries, caller_path: str, callee: str) -> list:
        """Plausible definitions of ``callee``: same-module wins;
        cross-module only when the name is unique tree-wide (the
        informer-mutation resolution rule — ambiguous names like
        ``update`` are skipped rather than guessed)."""
        cands = summaries.get(callee, [])
        local = [s for s in cands if s["path"] == caller_path]
        return local if local else (cands if len(cands) == 1 else [])


# ---------------------------------------------------------------------------
# held-lock-await
# ---------------------------------------------------------------------------

_LOCK_NAME_RE = re.compile(r"(^|_)(lock|mu|mutex|rlock)\d*$", re.IGNORECASE)

#: Constructors whose result is a sync (thread) lock.
_LOCK_CTORS = {"Lock", "RLock", "DepLock", "make_lock", "allocate_lock"}


def _lock_ctor_call(value) -> bool:
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    return name in _LOCK_CTORS


def _lock_like(expr, lock_vars: set[str]) -> str:
    """Lock-ish receiver name for a sync ``with`` item, or ''."""
    if isinstance(expr, ast.Name):
        if expr.id in lock_vars or _LOCK_NAME_RE.search(expr.id):
            return expr.id
    elif isinstance(expr, ast.Attribute):
        if _LOCK_NAME_RE.search(expr.attr):
            return expr.attr
    elif _lock_ctor_call(expr):
        return ast.unparse(expr.func)  # e.g. ``with make_lock():``
    return ""


def _first_await(stmts: list[ast.stmt]):
    """First suspension point lexically inside ``stmts``, skipping
    nested function scopes (their awaits run on their own frames)."""
    todo = list(stmts)
    while todo:
        node = todo.pop(0)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return node
        todo.extend(ast.iter_child_nodes(node))
    return None


class _HeldLockVisitor(ast.NodeVisitor):
    """Sync locks held across a suspension point in one async body."""

    def __init__(self) -> None:
        self.lock_vars: set[str] = set()
        self.hits: list[tuple[int, int, str]] = []

    def visit_Assign(self, node: ast.Assign) -> None:
        if _lock_ctor_call(node.value):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    self.lock_vars.add(target.id)
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            name = _lock_like(item.context_expr, self.lock_vars)
            if name and _first_await(node.body) is not None:
                self.hits.append((node.lineno, node.col_offset, name))
                break
        for stmt in node.body:
            self.visit(stmt)

    def visit_FunctionDef(self, node):
        return

    def visit_AsyncFunctionDef(self, node):
        return

    def visit_Lambda(self, node):
        return


def _acquire_release_scan(body: list[ast.stmt],
                          hits: list[tuple[int, int, str]]) -> None:
    """Linear same-block scan: ``x.acquire()`` … await … before
    ``x.release()`` (the explicit-call form ``with`` can't see)."""
    held: dict[str, int] = {}
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("acquire", "release"):
                recv = node.func.value
                name = recv.id if isinstance(recv, ast.Name) else (
                    recv.attr if isinstance(recv, ast.Attribute) else "")
                if not name:
                    continue
                if node.func.attr == "acquire":
                    held[name] = node.lineno
                else:
                    held.pop(name, None)
        if held and _first_await([stmt]) is not None:
            for name in list(held):
                hits.append((stmt.lineno, stmt.col_offset, name))
                del held[name]  # one finding per lock per block
    # Recurse into nested statement blocks (try/if/for bodies).
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if sub:
                _acquire_release_scan(sub, hits)
        for h in getattr(stmt, "handlers", []):
            _acquire_release_scan(h.body, hits)


@register
class HeldLockAwaitPass(Pass):
    name = "held-lock-await"
    description = ("sync (thread) lock held across an await: the loop "
                   "interleaves arbitrary callbacks at the suspension "
                   "point while the lock is held — the static twin of "
                   "lockdep's held-across-await probe (TPU_LOCKDEP)")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        if mod.path.endswith("util/lockdep.py"):
            return ()  # defines the probe; its fixtures hold on purpose
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            v = _HeldLockVisitor()
            for stmt in node.body:
                v.visit(stmt)
            _acquire_release_scan(node.body, v.hits)
            for line, col, name in v.hits:
                yield Finding(
                    mod.path, line, col, self.name,
                    f"sync lock {name!r} held across await in "
                    f"{node.name}() — release before suspending, or "
                    f"use asyncio.Lock (lockdep would flag this at "
                    f"runtime as held-across-await)")
