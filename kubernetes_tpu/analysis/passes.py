"""The repo-specific tpuvet passes.

Each pass encodes a correctness discipline the reference enforces
mechanically (``go vet``, ``hack/verify-*.sh``, the client-go mutation
detector) that plain Python gives us no compiler help with.
"""
from __future__ import annotations

import ast
import re
from typing import Iterable

from .tpuvet import Context, Finding, Module, Pass, register

# ---------------------------------------------------------------------------
# swallowed-exception
# ---------------------------------------------------------------------------

_BLANKET = {"Exception", "BaseException"}


def _is_blanket(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BLANKET
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BLANKET
                   for e in t.elts)
    return False


def _pure_swallow(body: list[ast.stmt]) -> bool:
    """True when the handler does nothing observable: only pass /
    continue / bare constants (docstrings, ``...``)."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue
        return False
    return True


@register
class SwallowedExceptionPass(Pass):
    name = "swallowed-exception"
    description = ("bare/blanket `except` whose body silently discards the "
                   "error (no logging, no re-raise, no handling)")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if (isinstance(node, ast.ExceptHandler) and _is_blanket(node)
                    and _pure_swallow(node.body)):
                yield Finding(
                    mod.path, node.lineno, node.col_offset, self.name,
                    "blanket except swallows the error silently — log at "
                    "warning level with context, or narrow the exception "
                    "type")


# ---------------------------------------------------------------------------
# async-blocking
# ---------------------------------------------------------------------------

#: module.attr calls that block the event loop.
_BLOCKING_ATTR = {
    ("time", "sleep"),
    ("socket", "create_connection"),
    ("socket", "getaddrinfo"),
    ("socket", "gethostbyname"),
    ("subprocess", "run"),
    ("subprocess", "call"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("os", "system"),
    ("os", "popen"),
    ("urllib", "urlopen"),
    ("requests", "get"),
    ("requests", "post"),
    ("requests", "request"),
}


def _blocking_call_name(call: ast.Call) -> str:
    f = call.func
    if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
            and (f.value.id, f.attr) in _BLOCKING_ATTR):
        return f"{f.value.id}.{f.attr}"
    return ""


class _AsyncBodyVisitor(ast.NodeVisitor):
    """Walks one ``async def`` body without descending into nested
    function definitions (a nested sync def / lambda is typically a
    thunk handed to ``run_in_executor`` / ``to_thread`` — off-loop)."""

    def __init__(self) -> None:
        self.hits: list[ast.Call] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return  # separate scope

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return  # visited on its own by the pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return

    def visit_Call(self, node: ast.Call) -> None:
        if _blocking_call_name(node):
            self.hits.append(node)
        self.generic_visit(node)


@register
class AsyncBlockingPass(Pass):
    name = "async-blocking"
    description = ("blocking call (time.sleep / sync subprocess / sync "
                   "socket or HTTP I/O) inside an `async def` body")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            v = _AsyncBodyVisitor()
            for stmt in node.body:
                v.visit(stmt)
            for call in v.hits:
                yield Finding(
                    mod.path, call.lineno, call.col_offset, self.name,
                    f"{_blocking_call_name(call)}() blocks the event loop "
                    f"inside async def {node.name}() — use the asyncio "
                    f"equivalent or run_in_executor")


# ---------------------------------------------------------------------------
# feature-gate
# ---------------------------------------------------------------------------

def _known_gates() -> set[str]:
    from ..util.features import KNOWN_FEATURES
    return set(KNOWN_FEATURES)


_GATE_RECEIVER_RE = re.compile(r"gate", re.IGNORECASE)


@register
class FeatureGatePass(Pass):
    name = "feature-gate"
    description = ("feature-gate string literal not registered in "
                   "util/features.py KNOWN_FEATURES")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        if mod.path.endswith("util/features.py"):
            return
        known = _known_gates()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in ("enabled", "set", "parse")):
                continue
            try:
                receiver = ast.unparse(f.value)
            except (ValueError, RecursionError):  # pragma: no cover
                continue
            if not _GATE_RECEIVER_RE.search(receiver):
                continue
            if not node.args or not isinstance(node.args[0], ast.Constant) \
                    or not isinstance(node.args[0].value, str):
                continue
            lit = node.args[0].value
            names = ([p.partition("=")[0].strip()
                      for p in lit.split(",") if p.strip()]
                     if f.attr == "parse" else [lit])
            for gate in names:
                if gate and gate not in known:
                    yield Finding(
                        mod.path, node.lineno, node.col_offset, self.name,
                        f"unknown feature gate {gate!r} — register it in "
                        f"util/features.py KNOWN_FEATURES")


# ---------------------------------------------------------------------------
# metric-name
# ---------------------------------------------------------------------------

_METRIC_CTORS = {"Counter", "Gauge", "Histogram"}
_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _metric_ctor(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name) and f.id in _METRIC_CTORS:
        return f.id
    if isinstance(f, ast.Attribute) and f.attr in _METRIC_CTORS:
        return f.attr
    return ""


@register
class MetricNamePass(Pass):
    name = "metric-name"
    description = ("Prometheus metric name invalid, or registered from two "
                   "different sites (the registry is first-wins: the second "
                   "construction is silently inert)")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        if mod.path.endswith("metrics/registry.py"):
            return  # the primitives themselves, not a registration site
        sites = ctx.scratch(self.name).setdefault("sites", {})
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not _metric_ctor(node):
                continue
            arg = node.args[0] if node.args else None
            for kw in node.keywords:
                if kw.arg == "name":
                    arg = kw.value
            if not isinstance(arg, ast.Constant) or not isinstance(arg.value, str):
                continue
            mname = arg.value
            if not _METRIC_NAME_RE.match(mname):
                yield Finding(
                    mod.path, node.lineno, node.col_offset, self.name,
                    f"invalid Prometheus metric name {mname!r}")
            sites.setdefault(mname, []).append(
                (mod.path, node.lineno, node.col_offset))

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        sites = ctx.scratch(self.name).get("sites", {})
        for mname, where in sorted(sites.items()):
            if len(where) <= 1:
                continue
            first = f"{where[0][0]}:{where[0][1]}"
            for path, line, col in where[1:]:
                yield Finding(
                    path, line, col, self.name,
                    f"metric {mname!r} already registered at {first}; the "
                    f"registry is first-wins so this instance records "
                    f"nothing")


# ---------------------------------------------------------------------------
# cache-mutation
# ---------------------------------------------------------------------------

#: Methods whose result is a shared cached object (or list of them).
_CACHE_GETTERS = {"get", "list", "by_index", "bound_copy"}
#: Receiver must look like a cache for the getter to taint.
_CACHE_RECEIVER_RE = re.compile(
    r"(informer|lister|\.store\b|^store$|snapshot|\bcache\b)",
    re.IGNORECASE)
#: Container-mutators: flagged when invoked on (an attribute of) a
#: cached object, e.g. ``pod.metadata.labels.update(...)``.
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "remove", "discard", "clear", "sort"}


def _cache_getter_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if not isinstance(f, ast.Attribute) or f.attr not in _CACHE_GETTERS:
        return False
    try:
        receiver = ast.unparse(f.value)
    except (ValueError, RecursionError):  # pragma: no cover
        return False
    return bool(_CACHE_RECEIVER_RE.search(receiver))


def _root_name(node: ast.AST):
    """Name node at the base of an Attribute/Subscript chain, or None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _FunctionTaint(ast.NodeVisitor):
    """Track names bound from cache getters inside one function and flag
    in-place mutation through them. Conservatively heuristic: rebinding
    a name (``pod = deepcopy(pod)``) clears its taint."""

    def __init__(self, mod: Module, findings: list[Finding]):
        self.mod = mod
        self.findings = findings
        self.tainted: set[str] = set()       # names holding a cached object
        self.tainted_lists: set[str] = set() # names holding a cached list

    # -- taint sources ----------------------------------------------------

    def _bind(self, target: ast.expr, value: ast.expr) -> None:
        if not isinstance(target, ast.Name):
            return
        if _cache_getter_call(value):
            attr = value.func.attr  # type: ignore[union-attr]
            (self.tainted_lists if attr in ("list", "by_index")
             else self.tainted).add(target.id)
            return
        # Iterating / indexing a cached list yields cached objects.
        if (isinstance(value, ast.Subscript)
                and isinstance(value.value, ast.Name)
                and value.value.id in self.tainted_lists):
            self.tainted.add(target.id)
            return
        # Any other rebind launders the name (deepcopy, fresh object...).
        self.tainted.discard(target.id)
        self.tainted_lists.discard(target.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        # Flag mutations first (the value may read a tainted name).
        for target in node.targets:
            self._flag_store(target)
        for target in node.targets:
            if isinstance(target, ast.Tuple):
                for elt in target.elts:
                    self._bind(elt, node.value)
            else:
                self._bind(target, node.value)
        # visit (not generic_visit): a mutator call can BE the value
        # expression (x = pod.metadata.labels.pop("stale")).
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._flag_store(node.target)
            self._bind(node.target, node.value)
            self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._flag_store(node.target)
        self.visit(node.value)

    def visit_For(self, node: ast.For) -> None:
        it = node.iter
        if isinstance(node.target, ast.Name):
            if _cache_getter_call(it) and it.func.attr in ("list", "by_index"):  # type: ignore[union-attr]
                self.tainted.add(node.target.id)
            elif isinstance(it, ast.Name) and it.id in self.tainted_lists:
                self.tainted.add(node.target.id)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    # -- taint sinks ------------------------------------------------------

    def _flag_store(self, target: ast.expr) -> None:
        if isinstance(target, ast.Tuple):
            for elt in target.elts:
                self._flag_store(elt)
            return
        if not isinstance(target, (ast.Attribute, ast.Subscript)):
            return
        root = _root_name(target)
        if root in self.tainted:
            self.findings.append(Finding(
                self.mod.path, target.lineno, target.col_offset,
                CacheMutationPass.name,
                f"in-place mutation of cached object {root!r} obtained "
                f"from an informer/scheduler cache — deepcopy before "
                f"modifying (shared-cache corruption)"))

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _MUTATORS
                and isinstance(f.value, (ast.Attribute, ast.Subscript))):
            root = _root_name(f.value)
            if root in self.tainted:
                self.findings.append(Finding(
                    self.mod.path, node.lineno, node.col_offset,
                    CacheMutationPass.name,
                    f"{f.attr}() mutates cached object {root!r} obtained "
                    f"from an informer/scheduler cache in place — deepcopy "
                    f"before modifying"))
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._flag_store(target)

    # Nested defs get their own fresh scope via the pass driver.
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return


@register
class CacheMutationPass(Pass):
    name = "cache-mutation"
    description = ("in-place mutation of an object obtained from an "
                   "informer / scheduler cache (shared-cache corruption: "
                   "every other consumer sees the edit)")

    #: The cache layers themselves own their objects; consumers don't.
    _SELF_PATHS = ("client/informer.py", "scheduler/cache.py",
                   "analysis/")

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        if any(p in mod.path for p in self._SELF_PATHS):
            return ()
        findings: list[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                v = _FunctionTaint(mod, findings)
                for stmt in node.body:
                    v.visit(stmt)
        return findings
