"""tpusan — deterministic interleaving explorer for the asyncio plane.

The control plane's worst bugs are wakeup-order races (the gang-release
wakeup race, the reclaim bind-vs-eviction window, the `_unadmit_overlay`
double-charge — all found late, by chaos or by review). This module
makes that order a *seeded input* instead of an accident of the event
loop, FoundationDB-simulation style: same seed ⇒ same schedule, so a
failing interleaving replays under a debugger instead of recurring once
a month in CI.

Mechanism: asyncio's ready queue (``BaseEventLoop._ready``) is replaced
with a seeded permuting deque. Only **task steps** (handles whose
callback is bound to an :class:`asyncio.Task` — creations and wakeups)
are permuted; infrastructure callbacks (selector/transport plumbing,
which DOES rely on FIFO delivery order) keep their relative order, so
real sockets keep working while coroutine interleaving is fuzzed.

Two modes (``TPU_SAN_MODE``):

- ``random`` — uniform seeded choice among runnable task steps.
- ``dpor`` — DPOR-lite: task steps whose tasks have *touched the same
  shared object* as the most recently scheduled step are preferentially
  permuted (true dynamic partial-order reduction explores only
  conflicting reorderings; this is the bounded, heuristic cut of that
  idea). Shared-object touches come from :func:`touch` calls wired
  into the seams: MVCC writes, the scheduling queue's gang paths, the
  admission pass.

Arming (opt-in, in the style of TPU_CHAOS / TPU_LOCKDEP)::

    TPU_SAN=<seed>            # fuzz every asyncio test / harness loop
    TPU_SAN_MODE=dpor         # optional; default random
    TPU_SAN_SCHEDULES=8       # schedules per seed for explore()-based gates

Replay contract: the schedule **fingerprint** (a rolling hash over
every (candidate-count, chosen-rank) decision) is a pure function of
(seed, the sequence of ready-queue states). For scenarios without
wall-clock timers or real I/O the ready states are themselves
deterministic, so one seed ⇒ one fingerprint ⇒ one interleaving —
asserted by tests/unit/test_tpusan.py. Sibling: :mod:`.invariants`
(what must hold on every explored schedule).
"""
from __future__ import annotations

import asyncio
import hashlib
import os
import random
from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Optional
from weakref import WeakKeyDictionary

ENV_VAR = "TPU_SAN"
ENV_MODE = "TPU_SAN_MODE"
ENV_SCHEDULES = "TPU_SAN_SCHEDULES"

MODES = ("random", "dpor")

#: Probability that a dpor-mode decision restricts itself to the
#: conflicting candidates (1.0 would never explore benign reorderings).
DPOR_BIAS = 0.75

#: Per-task cap on remembered touched keys (DPOR hint state only).
MAX_TOUCHED = 256

#: True once any loop in this process has been installed — the fast
#: bail for :func:`touch` so disarmed production pays one module-global
#: check, nothing else.
ARMED = False


def _is_task_step(handle) -> bool:
    """A ready handle that advances a Task (creation or wakeup): its
    callback is bound to the Task (``TaskStepMethWrapper`` /
    ``task_wakeup`` in the C implementation, ``Task.__step`` in pure
    Python)."""
    cb = getattr(handle, "_callback", None)
    return isinstance(getattr(cb, "__self__", None), asyncio.Task)


class Interleaver:
    """One seeded schedule: the decision source + fingerprint."""

    def __init__(self, seed, mode: str = "random"):
        if mode not in MODES:
            raise ValueError(f"tpusan mode must be one of {MODES}, got {mode!r}")
        self.seed = seed
        self.mode = mode
        self.rng = random.Random(f"tpusan:{seed}")
        self.decisions = 0
        self._h = hashlib.sha256()
        #: task -> set of shared-object keys it touched (DPOR hints).
        self._touched: WeakKeyDictionary = WeakKeyDictionary()
        #: keys touched by the most recently scheduled task step.
        self._last_keys: frozenset = frozenset()

    # -- scheduling decisions ---------------------------------------------

    def choose(self, buf: list, idxs: list[int]) -> int:
        """Pick which ready task step runs next; returns its index in
        ``buf``. Called by :class:`_FuzzReady` with >= 1 candidates."""
        if self.mode == "dpor" and len(idxs) > 1 and self._last_keys:
            conflicting = [i for i in idxs
                           if self._task_keys(buf[i]) & self._last_keys]
            if conflicting and self.rng.random() < DPOR_BIAS:
                idxs = conflicting
        rank = self.rng.randrange(len(idxs)) if len(idxs) > 1 else 0
        j = idxs[rank]
        self.decisions += 1
        self._h.update(b"%d:%d;" % (len(idxs), rank))
        self._last_keys = frozenset(self._task_keys(buf[j]))
        return j

    def _task_keys(self, handle) -> set:
        task = getattr(getattr(handle, "_callback", None), "__self__", None)
        got = self._touched.get(task) if task is not None else None
        return got if got is not None else set()

    def note_touch(self, key: str) -> None:
        task = asyncio.current_task()
        if task is None:
            return
        touched = self._touched.get(task)
        if touched is None:
            touched = self._touched[task] = set()
        if len(touched) < MAX_TOUCHED:
            touched.add(key)

    # -- artifacts --------------------------------------------------------

    def fingerprint(self) -> str:
        """``<decisions>:<digest16>`` — the replay-by-seed artifact. Two
        runs of one seed over a timer-free scenario produce the same
        string; two seeds over a contended scenario (almost) never do."""
        return f"{self.decisions}:{self._h.hexdigest()[:16]}"


class _FuzzReady(list):
    """Drop-in for ``BaseEventLoop._ready``. The loop only uses
    append/popleft/len/bool/clear (collections.deque), so a list
    subclass with a permuting :meth:`popleft` suffices.

    Policy: only the **contiguous front run of task steps** is
    permuted. Infrastructure callbacks (selector/transport plumbing)
    keep FIFO both among themselves AND relative to task steps queued
    after them — a task resuming from ``await sock_connect`` must not
    overtake the ``_sock_write_done`` bookkeeping scheduled just before
    its wakeup (observed: the transport claims the fd, then the late
    remove_writer raises). Task wakeup order — the surface application
    races live on — is still fully explored within each run."""

    def __init__(self, san: Interleaver):
        super().__init__()
        self.san = san

    def popleft(self):
        if len(self) <= 1 or not _is_task_step(self[0]):
            return self.pop(0)
        n = 1
        while n < len(self) and _is_task_step(self[n]):
            n += 1
        if n == 1:
            return self.pop(0)
        return self.pop(self.san.choose(self, list(range(n))))


def install(loop: asyncio.AbstractEventLoop, seed,
            mode: str = "random") -> Interleaver:
    """Put ``loop`` under a seeded schedule; returns the interleaver
    (its :meth:`~Interleaver.fingerprint` is the run artifact)."""
    global ARMED
    san = Interleaver(seed, mode)
    ready = _FuzzReady(san)
    ready.extend(loop._ready)  # normally empty on a fresh loop
    loop._ready = ready
    loop._tpusan = san
    ARMED = True
    return san


def current() -> Optional[Interleaver]:
    """The interleaver driving the running loop, or None."""
    if not ARMED:
        return None
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        return None
    return getattr(loop, "_tpusan", None)


def touch(key: str) -> None:
    """Record that the current task touched shared object ``key`` — the
    DPOR-lite conflict hint. Wired into the seams (MVCC writes, gang
    release/admission paths); free when tpusan is disarmed."""
    if not ARMED:
        return
    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        return
    san = getattr(loop, "_tpusan", None)
    if san is not None:
        san.note_touch(key)


# -- drivers ----------------------------------------------------------------


@dataclass
class ScheduleResult:
    """One explored schedule's verdict."""
    schedule: int
    seed: str
    fingerprint: str
    decisions: int
    value: Any = None


def run(coro: Awaitable, seed, mode: str = "random",
        san: Optional[Interleaver] = None) -> tuple[Any, Interleaver]:
    """``asyncio.run`` under a seeded schedule; returns (result,
    interleaver). The loop is private and closed afterwards, like
    asyncio.run's."""
    loop = asyncio.new_event_loop()
    installed = san or Interleaver(seed, mode)
    ready = _FuzzReady(installed)
    loop._ready = ready
    loop._tpusan = installed
    global ARMED
    ARMED = True
    asyncio.set_event_loop(loop)
    try:
        return loop.run_until_complete(coro), installed
    finally:
        # asyncio.run()'s shutdown contract, which this replaces — and
        # like asyncio.run it must hold on the FAILURE path too (a
        # failing schedule's plane servers/background tasks must not
        # leak into the next schedule of the same process): cancel
        # whatever is still pending so finally-blocks run, drain async
        # generators, and collect while the loop is still alive
        # (dropped aiohttp transports finalize through it; after close
        # they raise "Event loop is closed").
        try:
            pending = asyncio.all_tasks(loop)
            if pending:
                for task in pending:
                    task.cancel()
                loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True))
            loop.run_until_complete(loop.shutdown_asyncgens())
            import gc
            for _ in range(2):
                gc.collect()
        finally:
            asyncio.set_event_loop(None)
            loop.close()


def explore(factory: Callable[[int], Awaitable], base_seed,
            schedules: int = 8, mode: str = "random") -> list[ScheduleResult]:
    """Run ``factory(i)``'s coroutine under ``schedules`` distinct
    seeded schedules derived from ``base_seed``. Exceptions propagate —
    a scenario that breaks under some interleaving should fail the
    gate, with the failing (seed, schedule index) in the traceback
    context for replay."""
    out = []
    for i in range(schedules):
        seed = f"{base_seed}:{i}"
        value, san = run(factory(i), seed, mode)
        out.append(ScheduleResult(
            schedule=i, seed=seed, fingerprint=san.fingerprint(),
            decisions=san.decisions, value=value))
    return out


def explore_sanitized(factory: Callable[[int], Awaitable], base_seed,
                      schedules: int = 8, mode: str = "dpor",
                      extract: Optional[Callable[[Any], dict]] = None
                      ) -> dict:
    """:func:`explore` with the cluster-invariant sanitizer armed for
    each schedule: every store built during a run self-attaches, the
    run must end violation-free (AssertionError names the failing
    (base_seed, schedule) pair for replay), and per-invariant check
    counts are aggregated — the shared driver behind the chaos and
    queueing tpusan gates. ``extract(value)`` adds scenario-specific
    fields to each schedule's report row."""
    from . import invariants

    rows = []
    checks_total: dict = {}
    for i in range(schedules):
        sanitizer = invariants.arm(invariants.InvariantRegistry())
        try:
            value, san = run(factory(i), f"{base_seed}:{i}", mode)
        finally:
            invariants.disarm()
        sanitizer.check_final()
        sanitizer.assert_clean()
        for name, n in sanitizer.checks.items():
            checks_total[name] = checks_total.get(name, 0) + n
        row = {"schedule": i, "fingerprint": san.fingerprint(),
               "decisions": san.decisions}
        if extract is not None:
            row.update(extract(value))
        rows.append(row)
    return {
        "mode": mode,
        "schedules": rows,
        "distinct_fingerprints": len({r["fingerprint"] for r in rows}),
        "invariant_checks": checks_total,
    }


# -- env arming -------------------------------------------------------------


def from_env() -> Optional[str]:
    """The ``TPU_SAN`` seed, or None when disarmed. Like TPU_CHAOS,
    any non-empty string is a valid seed (the rng hashes it)."""
    raw = os.environ.get(ENV_VAR, "")
    return raw or None


def mode_from_env() -> str:
    mode = os.environ.get(ENV_MODE, "") or "random"
    if mode not in MODES:
        raise ValueError(
            f"{ENV_MODE}={mode!r}: must be one of {', '.join(MODES)}")
    return mode


def schedules_from_env(default: int = 8) -> int:
    raw = os.environ.get(ENV_SCHEDULES, "")
    return int(raw) if raw else default
