"""tpusan invariants — cluster properties checked on every store write.

The sanitizer half of tpusan (:mod:`.interleave` is the schedule half):
a registry of always-on cluster invariants evaluated at the MVCC write
seam, so ANY interleaving the explorer produces is judged step by step
instead of only at scenario end. The registered invariants are the
ones whose violations this repo has actually paid for (chaos findings,
PR-review windows):

``chip-double-book``
    No TPU chip is assigned to two live pods on one node. (The chaos
    harness asserts this once, at convergence; the sanitizer asserts it
    on every bind so the transient double-book a converging run hides
    is still caught.)
``quota-conservation``
    Per borrowing cohort, admitted usage never exceeds the cohort's
    nominal quota: sum(usage) <= sum(nominal) per governed resource —
    the fairshare conservation invariant, now checked against the
    durable store instead of the controller's own accounting.
``gang-atomicity``
    A gang is never *partially* bound past the quorum grace — measured
    in STORE REVISIONS, not wall seconds, so the verdict is a pure
    function of the write stream and replays by seed: 0 < bound <
    min_member must be a transient state, not one the cluster keeps
    making progress around (a stuck partial gang holds chips no one
    can use).
``admission-monotonicity``
    ``status.admitted`` never silently flips back to False: the only
    legal unadmit is an announced reclaim (:func:`note_reclaim`, wired
    into QueueController._unadmit) or object deletion.
``wal-replay``
    Replaying the write stream reproduces the live store exactly: a
    shadow copy is maintained from the same records the WAL sees, and
    :meth:`InvariantRegistry.check_final` compares it byte-for-byte
    against ``store.state()`` — state mutated behind the log's back
    (the bug class WAL recovery cannot survive) is a violation.
``checkpoint-monotonic``
    A gang's recorded graceful-preemption resume point
    (``status.preemption.checkpoint_step``) never decreases — a
    rewind would make the next incarnation redo or skip training
    steps (the torn-marker bug class).
``election-safety``
    At most one replica leads any replication term (split-brain means
    two apiservers acking writes the other never sees); announced by
    every ReplicaNode election win via :func:`note_leader`.
``committed-never-lost``
    Every quorum-committed — i.e. client-ackable — write
    (:func:`note_commit`) is present, byte-identical at its committed
    revision, on every CONVERGED replica of the group at final check.

Violations are RECORDED (``log.error`` + ``violations`` list), not
raised mid-write: raising inside the store would turn a sanitizer
verdict into an apiserver 500 that retry-tolerant clients swallow.
Harnesses call :meth:`~InvariantRegistry.assert_clean` at the end.

Arming::

    from kubernetes_tpu.analysis import invariants
    reg = invariants.arm(invariants.InvariantRegistry())
    ...  # every MVCCStore constructed while armed self-attaches
    reg.check_final(); reg.assert_clean()
    invariants.disarm()
"""
from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger("tpusan")

#: Kept literal (mirrors api.types.RESOURCE_TPU) so the store can import
#: this module without pulling the full API scheme in.
RESOURCE_TPU = "google.com/tpu"

CHIP_DOUBLE_BOOK = "chip-double-book"
QUOTA_CONSERVATION = "quota-conservation"
GANG_ATOMICITY = "gang-atomicity"
ADMISSION_MONOTONICITY = "admission-monotonicity"
WAL_REPLAY = "wal-replay"
#: ``status.preemption.checkpoint_step`` never decreases for a live
#: group: a graceful-preemption round (or a torn/stale marker replay)
#: that REWINDS the recorded resume point would make the next
#: incarnation silently redo — or worse, skip — training steps.
#: Evaluated on every podgroup write (trivially when no preemption
#: state exists), so the check counter moves with ordinary traffic.
CHECKPOINT_MONOTONIC = "checkpoint-monotonic"
#: A gang with an OPEN migration round (status.migration.phase in
#: Reserved/Moving) always holds its source placement OR its target
#: reservation — a controller that evicted the gang and lost (or
#: released) the reserved box has stranded it: the "migration" was an
#: eviction in disguise. And never BOTH charged on the same chips: the
#: target reservation overlapping the gang's own bound chips would
#: double-count capacity. Reservations reach the sanitizer through
#: the cache seams (:func:`note_reservation` /
#: :func:`note_reservation_gone`); like gang-atomicity the strand
#: verdict is revision-graced, since the scheduler legally releases
#: the reservation a few writes before the binds land. Evaluated on
#: every podgroup write (trivially when no migration state exists), so
#: the check counter moves with ordinary traffic.
MIGRATION_NO_STRAND = "migration-no-strand"
#: At most ONE replica leads any raft term (storage/replication.py
#: announces every election win via :func:`note_leader`): two leaders
#: in one term means split-brain — both would accept and ack writes
#: the other never sees.
ELECTION_SAFETY = "election-safety"
#: Every quorum-committed (client-ackable) write is present on every
#: CONVERGED replica at final check: committed entries announced via
#: :func:`note_commit` must appear — key, value, and mod revision —
#: in each caught-up replica store of the group. A committed entry
#: missing from a converged replica is an acknowledged write the
#: cluster lost.
COMMITTED_NEVER_LOST = "committed-never-lost"

#: Invariants only exercised when a replicated control plane runs
#: (the HA harness / race.sh stage 5); the chaos/queueing gates assert
#: coverage of the CORE set only.
REPLICATION_INVARIANTS = (ELECTION_SAFETY, COMMITTED_NEVER_LOST)

CORE_INVARIANTS = (CHIP_DOUBLE_BOOK, QUOTA_CONSERVATION, GANG_ATOMICITY,
                   ADMISSION_MONOTONICITY, WAL_REPLAY, CHECKPOINT_MONOTONIC,
                   MIGRATION_NO_STRAND)

INVARIANTS = CORE_INVARIANTS + REPLICATION_INVARIANTS

#: Store revisions the cluster may advance while a gang sits partially
#: bound before gang-atomicity fires. Revision-counted (not wall-clock)
#: so a loaded machine cannot flip the verdict — same write stream,
#: same verdict. Generous by default: a live bind-in-progress finishes
#: within a handful of writes; negative tests shrink it.
DEFAULT_PARTIAL_GRACE_REVS = 500


@dataclass(frozen=True)
class Violation:
    invariant: str
    key: str
    message: str
    revision: int = 0

    def __str__(self) -> str:
        return f"[{self.invariant}] {self.key}@r{self.revision}: {self.message}"


def _canon(value: dict) -> str:
    """Canonical serialization for shadow-vs-live comparison."""
    return json.dumps(value, sort_keys=True, separators=(",", ":"))


def _demand(group_value: dict) -> dict:
    """Gang demand as admission charges it (controllers/queue.py
    group_demand — keep the two in sync): explicit spec.resources,
    chips defaulted from the slice shape, scaled by the elastic target
    (status.replicas / spec.max_replicas) when GracefulPreemption is
    on."""
    spec = group_value.get("spec", {}) or {}
    demand = dict(spec.get("resources", {}) or {})
    shape = spec.get("slice_shape") or []
    if RESOURCE_TPU not in demand and shape:
        chips = 1.0
        for d in shape:
            chips *= d
        demand[RESOURCE_TPU] = float(chips)
    mx = int(spec.get("max_replicas", 0) or 0)
    if mx:
        from ..util.features import GATES
        if GATES.enabled("GracefulPreemption"):
            status = group_value.get("status", {}) or {}
            r = int(status.get("replicas", 0) or 0) or mx
            r = max(int(spec.get("min_replicas", 0) or 0), min(r, mx))
            demand = {res: amt * r / mx for res, amt in demand.items()}
    return demand


def _pod_chips(pod_value: dict) -> set:
    """Chips a pod HOLDS for double-book purposes. A pod with a
    deletion timestamp has logically released its chips — the scheduler
    cache frees them at that instant ("terminal pods free their chips")
    and the remaining teardown overlap is the node runtime's to
    serialize, so counting a deleting pod would flag every graceful
    eviction-rebind as a violation."""
    if (pod_value.get("metadata", {}) or {}).get("deletion_timestamp"):
        return set()
    spec = pod_value.get("spec", {}) or {}
    node = spec.get("node_name", "")
    if not node:
        return set()
    pairs = set()
    for claim in spec.get("tpu_resources", []) or []:
        for cid in claim.get("assigned", []) or []:
            pairs.add((node, cid))
    return pairs


class _StoreState:
    """Incremental indexes for one attached store — per-write checks
    stay O(write), not O(cluster)."""

    def __init__(self, store):
        self.store = store
        #: (node, chip_id) -> pod key holding it.
        self.chips: dict = {}
        self.pod_chips: dict = {}       # pod key -> set[(node, chip)]
        self.bound_by_gang: dict = {}   # gang key -> set[pod key]
        self.pod_gang: dict = {}        # pod key -> gang key
        #: group key -> {"admitted", "cq", "demand", "min_member"}
        self.groups: dict = {}
        self.cqs: dict = {}             # name -> {"cohort", "nominal"}
        self.lqs: dict = {}             # "ns/name" -> cluster queue name
        self.usage: dict = {}           # cq name -> {resource: charged}
        self.partial_since: dict = {}   # gang key -> revision when partial
        #: gang key -> revision when its open migration round first held
        #: NEITHER a placement nor a reservation (migration-no-strand).
        self.strand_since: dict = {}
        #: The write-stream replay: key -> (canonical value JSON,
        #: mod_rev, create_rev). Serialized at write time so a later
        #: in-place mutation of the stored dict cannot drag the shadow
        #: along with it (the exact bug class wal-replay exists for).
        self.shadow: dict = {}
        self.shadow_rev = 0


class _ReplicaGroup:
    """Replication-group bookkeeping for the two HA invariants."""

    def __init__(self):
        #: node_id -> live MVCCStore (re-registered on rebuild).
        self.stores: dict = {}
        #: Crashed node_ids: a dead replica may legitimately hold a
        #: DIVERGENT uncommitted tail (it was the minority holder of
        #: entries that never committed — raft snapshots it away on
        #: rejoin). Its frozen store can be AHEAD of the acked prefix,
        #: so the lag filter alone does not skip it.
        self.down: set = set()
        #: term -> node_id that won it.
        self.leaders: dict[int, str] = {}
        #: key -> (rev, op, canonical value) of the LATEST committed
        #: write per key — what every converged replica must hold.
        self.acked: dict[str, tuple] = {}
        self.max_acked_rev = 0


class InvariantRegistry:
    """The armed sanitizer: attach stores, collect violations."""

    def __init__(self, partial_grace_revs: int = DEFAULT_PARTIAL_GRACE_REVS):
        self.partial_grace_revs = partial_grace_revs
        self.violations: list[Violation] = []
        #: invariant -> number of evaluations (the "exercised" artifact
        #: hack/race.sh asserts on).
        self.checks: dict[str, int] = {name: 0 for name in INVARIANTS}
        self._stores: list[_StoreState] = []
        #: Announced reclaims: unadmits these keys may legally perform.
        self._reclaim_ok: set = set()
        #: Live scheduler-cache reservations: owner (gang key) ->
        #: set[(node, chip_id)]. Fed by the cache reserve/release seams
        #: (TTL expiry flows through release_reservation, so one seam
        #: covers it); registry-level because reservations are cache
        #: state, not store state.
        self._reservations: dict[str, set] = {}
        #: (invariant, key) already reported — one violation per site,
        #: not one per write that re-observes it.
        self._reported: set = set()
        #: Replication groups (storage/replication.py registers every
        #: ReplicaNode's store and announces leaders/commits).
        self._replica_groups: dict[str, _ReplicaGroup] = {}

    # -- wiring -----------------------------------------------------------

    def attach_store(self, store) -> None:
        """Seed indexes from the store's current contents and subscribe
        to its event stream (MVCCStore.__init__ calls this on every
        store built while the registry is armed — including recovery
        replays, whose loaded state arrives via the seed walk)."""
        st = _StoreState(store)
        self._stores.append(st)
        for key, obj in list(store._data.items()):
            st.shadow[key] = (_canon(obj.value), obj.mod_revision,
                              obj.create_revision)
            self._index(st, key, obj.value, revision=obj.mod_revision,
                        seeding=True)
        st.shadow_rev = store._rev
        store.add_event_hook(lambda ev, st=st: self._on_event(st, ev))

    def note_reclaim(self, group_key: str) -> None:
        """QueueController._unadmit announces a reclaim: the next
        admitted->pending flip of ``group_key`` is legal."""
        self._reclaim_ok.add(group_key)

    def note_reservation(self, owner: str, pairs) -> None:
        """SchedulerCache.reserve announces a reservation (owner is a
        gang key, pairs are (node, chip_id) tuples): re-evaluate the
        owner's migration hold set in every attached store."""
        self._reservations[owner] = {tuple(p) for p in pairs}
        for st in self._stores:
            self._update_strand(st, owner, st.store.revision)

    def note_reservation_gone(self, owner: str) -> None:
        """SchedulerCache.release_reservation (explicit release AND
        TTL expiry — both flow through the one seam)."""
        if self._reservations.pop(owner, None) is not None:
            for st in self._stores:
                self._update_strand(st, owner, st.store.revision)

    def reseed_store(self, store) -> None:
        """A snapshot install (MVCCStore.reset_from_state) replaced the
        store's contents wholesale, outside the event stream: rebuild
        the shadow and the per-object indexes from the new state, or
        wal-replay would flag the install itself as divergence."""
        for st in self._stores:
            if st.store is not store:
                continue
            st.chips.clear()
            st.pod_chips.clear()
            st.bound_by_gang.clear()
            st.pod_gang.clear()
            st.groups.clear()
            st.cqs.clear()
            st.lqs.clear()
            st.usage.clear()
            st.partial_since.clear()
            st.strand_since.clear()
            st.shadow.clear()
            for key, obj in list(store._data.items()):
                st.shadow[key] = (_canon(obj.value), obj.mod_revision,
                                  obj.create_revision)
                self._index(st, key, obj.value, revision=obj.mod_revision,
                            seeding=True)
            st.shadow_rev = store._rev

    # -- replication group seams (storage/replication.py) -----------------

    def register_replica_store(self, group: str, node_id: str,
                               store) -> None:
        g = self._replica_groups.setdefault(group, _ReplicaGroup())
        g.stores[node_id] = store
        # A rebuilt member (same id, fresh store recovered from its
        # WAL + snapshot install) is live again and re-enters the
        # final sweep.
        g.down.discard(node_id)

    def note_replica_down(self, group: str, node_id: str) -> None:
        """A replica crashed: its frozen store may hold a divergent
        uncommitted tail and is excluded from the committed-never-lost
        sweep until it re-registers (rebuild)."""
        g = self._replica_groups.setdefault(group, _ReplicaGroup())
        g.down.add(node_id)

    def note_leader(self, group: str, node_id: str, term: int) -> None:
        """A replica won an election: election safety demands no OTHER
        replica ever claims the same term."""
        self.checks[ELECTION_SAFETY] += 1
        g = self._replica_groups.setdefault(group, _ReplicaGroup())
        prev = g.leaders.get(term)
        if prev is not None and prev != node_id:
            self._violate(
                ELECTION_SAFETY, f"{group}/term-{term}", 0,
                f"two leaders in term {term}: {prev} and {node_id} "
                f"(split-brain — both would ack writes)")
        else:
            g.leaders[term] = node_id

    def note_commit(self, group: str, rev: int, op: str, key: str,
                    value) -> None:
        """A write reached quorum (is client-ackable): record the
        latest committed write per key for the final
        committed-never-lost sweep."""
        g = self._replica_groups.setdefault(group, _ReplicaGroup())
        prev = g.acked.get(key)
        if prev is None or rev >= prev[0]:
            g.acked[key] = (rev, op,
                            _canon(value) if value is not None else None)
        g.max_acked_rev = max(g.max_acked_rev, rev)

    def _check_replica_groups(self) -> None:
        from ..storage.mvcc import DELETED
        for group, g in self._replica_groups.items():
            for node_id, store in g.stores.items():
                if node_id in g.down:
                    # Crashed: may hold a divergent uncommitted tail
                    # AHEAD of the acked prefix (the minority-holder
                    # case raft snapshots away on rejoin) — the lag
                    # filter below would not catch it.
                    continue
                if store.revision < g.max_acked_rev:
                    continue  # not converged (dead/lagging): the
                    # harness's own convergence asserts cover liveness
                self.checks[COMMITTED_NEVER_LOST] += 1
                live = store.state()["data"]
                for key, (rev, op, canon) in g.acked.items():
                    cur = live.get(key)
                    if op == DELETED:
                        if cur is not None and cur["mod_revision"] <= rev:
                            self._violate(
                                COMMITTED_NEVER_LOST, key, rev,
                                f"replica {node_id}: committed delete at "
                                f"rev {rev} vanished (key live at rev "
                                f"{cur['mod_revision']})")
                        continue
                    if cur is None or cur["mod_revision"] < rev:
                        self._violate(
                            COMMITTED_NEVER_LOST, key, rev,
                            f"replica {node_id}: committed write at rev "
                            f"{rev} missing (have "
                            f"{cur['mod_revision'] if cur else 'nothing'})"
                            f" — an acknowledged write was lost")
                    elif cur["mod_revision"] == rev \
                            and _canon(cur["value"]) != canon:
                        self._violate(
                            COMMITTED_NEVER_LOST, key, rev,
                            f"replica {node_id}: committed write at rev "
                            f"{rev} has different content than was "
                            f"acknowledged")

    # -- event dispatch ---------------------------------------------------

    def _on_event(self, st: _StoreState, ev) -> None:
        # Runs under the store lock on the write path: record-only, and
        # never let a sanitizer bug break a product write.
        try:
            self._dispatch(st, ev)
        except Exception:  # noqa: BLE001 — sanitizer must not take down writes
            log.exception("tpusan: invariant evaluation failed for %s", ev.key)

    def _dispatch(self, st: _StoreState, ev) -> None:
        deleted = ev.type == "DELETED"
        # wal-replay shadow: apply exactly what the WAL saw.
        if deleted:
            st.shadow.pop(ev.key, None)
        else:
            prev = st.shadow.get(ev.key)
            st.shadow[ev.key] = (_canon(ev.value), ev.revision,
                                 prev[2] if prev else ev.revision)
        st.shadow_rev = ev.revision
        parts = ev.key.split("/")
        plural = parts[2] if len(parts) > 2 else ""
        if plural == "pods":
            self._on_pod(st, ev, deleted)
        elif plural == "podgroups":
            self._on_group(st, ev, deleted)
        elif plural == "clusterqueues":
            name = parts[3]
            if deleted:
                st.cqs.pop(name, None)
            else:
                spec = ev.value.get("spec", {}) or {}
                st.cqs[name] = {
                    "cohort": spec.get("cohort", "") or "",
                    "nominal": dict(spec.get("nominal_quota", {}) or {})}
        elif plural == "localqueues":
            lq_key = f"{parts[3]}/{parts[4]}"
            if deleted:
                st.lqs.pop(lq_key, None)
            else:
                st.lqs[lq_key] = (ev.value.get("spec", {}) or {}).get(
                    "cluster_queue", "")
        if plural in ("pods", "podgroups"):
            self._check_partials(st, ev.revision)

    # -- per-object indexing (shared by seeding and live events) ----------

    def _index(self, st: _StoreState, key: str, value: dict,
               revision: int, seeding: bool) -> None:
        parts = key.split("/")
        plural = parts[2] if len(parts) > 2 else ""
        if plural == "pods":
            self._apply_pod(st, f"{parts[3]}/{parts[4]}", parts[3], value,
                            revision, check=not seeding)
        elif plural == "podgroups":
            self._apply_group(st, f"{parts[3]}/{parts[4]}", value,
                              revision, check=not seeding)
        elif plural == "clusterqueues":
            spec = value.get("spec", {}) or {}
            st.cqs[parts[3]] = {
                "cohort": spec.get("cohort", "") or "",
                "nominal": dict(spec.get("nominal_quota", {}) or {})}
        elif plural == "localqueues":
            st.lqs[f"{parts[3]}/{parts[4]}"] = (
                value.get("spec", {}) or {}).get("cluster_queue", "")

    # -- pods: chip ledger + gang bind tracking ---------------------------

    def _on_pod(self, st: _StoreState, ev, deleted: bool) -> None:
        parts = ev.key.split("/")
        pk = f"{parts[3]}/{parts[4]}"
        if deleted:
            for pair in st.pod_chips.pop(pk, set()):
                if st.chips.get(pair) == pk:
                    del st.chips[pair]
            gk = st.pod_gang.pop(pk, None)
            if gk is not None:
                st.bound_by_gang.get(gk, set()).discard(pk)
                self._update_partial(st, gk, ev.revision)
            return
        self._apply_pod(st, pk, parts[3], ev.value, ev.revision, check=True)

    def _apply_pod(self, st: _StoreState, pk: str, ns: str, value: dict,
                   revision: int, check: bool) -> None:
        new_pairs = _pod_chips(value)
        old_pairs = st.pod_chips.get(pk, set())
        for pair in old_pairs - new_pairs:
            if st.chips.get(pair) == pk:
                del st.chips[pair]
        if check:
            self.checks[CHIP_DOUBLE_BOOK] += 1
        for pair in new_pairs:
            holder = st.chips.get(pair)
            if holder is not None and holder != pk:
                self._violate(
                    CHIP_DOUBLE_BOOK, pk, revision,
                    f"chip {pair[1]} on node {pair[0]} already assigned "
                    f"to {holder}")
            else:
                st.chips[pair] = pk
        st.pod_chips[pk] = new_pairs
        spec = value.get("spec", {}) or {}
        gang = spec.get("gang", "")
        gk = f"{ns}/{gang}" if gang else None
        prev_gk = st.pod_gang.get(pk)
        if prev_gk and prev_gk != gk:
            st.bound_by_gang.get(prev_gk, set()).discard(pk)
        if gk is not None:
            st.pod_gang[pk] = gk
            bound = st.bound_by_gang.setdefault(gk, set())
            deleting = (value.get("metadata", {}) or {}).get(
                "deletion_timestamp")
            if spec.get("node_name") and not deleting:
                bound.add(pk)
            else:
                bound.discard(pk)
            self._update_partial(st, gk, revision)
        elif prev_gk:
            st.pod_gang.pop(pk, None)
            self._update_partial(st, prev_gk, revision)

    # -- podgroups: quota conservation + admission monotonicity -----------

    def _on_group(self, st: _StoreState, ev, deleted: bool) -> None:
        parts = ev.key.split("/")
        gk = f"{parts[3]}/{parts[4]}"
        if deleted:
            prev = st.groups.pop(gk, None)
            if prev and prev["admitted"] and prev["cq"]:
                self._uncharge(st, prev["cq"], prev["demand"])
            self._reclaim_ok.discard(gk)
            st.partial_since.pop(gk, None)
            st.strand_since.pop(gk, None)
            return
        self._apply_group(st, gk, ev.value, ev.revision, check=True)

    def _apply_group(self, st: _StoreState, gk: str, value: dict,
                     revision: int, check: bool) -> None:
        spec = value.get("spec", {}) or {}
        status = value.get("status", {}) or {}
        admitted = bool(status.get("admitted"))
        queue = spec.get("queue", "") or ""
        ns = gk.split("/", 1)[0]
        cq = ""
        if queue:
            cq = (status.get("admission_cluster_queue", "")
                  or st.lqs.get(f"{ns}/{queue}", ""))
        preempt = status.get("preemption") or {}
        step_raw = preempt.get("checkpoint_step", -1)
        # No falsy coercion: step 0 is a REAL checkpoint (a gang
        # preempted on its first step) and must stay distinguishable
        # from "never recorded" (-1), or a rewind from 0 goes unseen.
        step = int(step_raw) if isinstance(step_raw, (int, float)) else -1
        mig = status.get("migration") or {}
        cur = {"admitted": admitted, "cq": cq, "demand": _demand(value),
               "min_member": int(spec.get("min_member", 0) or 0),
               "ckpt_step": step,
               "migration_open": mig.get("phase") in ("Reserved", "Moving")}
        prev = st.groups.get(gk)
        st.groups[gk] = cur
        if check:
            self.checks[CHECKPOINT_MONOTONIC] += 1
            if prev is not None and step < prev.get("ckpt_step", -1):
                self._violate(
                    CHECKPOINT_MONOTONIC, gk, revision,
                    f"status.preemption.checkpoint_step rewound "
                    f"{prev.get('ckpt_step')} -> {step}: the gang's "
                    f"recorded resume point must only ever rise")
            self.checks[MIGRATION_NO_STRAND] += 1
        self._update_partial(st, gk, revision)
        if prev is None:
            if admitted and cq:
                self._charge(st, gk, cq, cur["demand"], revision,
                             check=check)
            return
        if check:
            self.checks[ADMISSION_MONOTONICITY] += 1
        if prev["admitted"] and not admitted:
            if prev["cq"]:
                self._uncharge(st, prev["cq"], prev["demand"])
            if check and gk not in self._reclaim_ok:
                self._violate(
                    ADMISSION_MONOTONICITY, gk, revision,
                    "status.admitted flipped to False outside an "
                    "announced reclaim (note_reclaim) or deletion")
            self._reclaim_ok.discard(gk)
        elif not prev["admitted"] and admitted:
            self._charge(st, gk, cq, cur["demand"], revision, check=check)
        elif admitted and (prev["cq"] != cq or prev["demand"] != cur["demand"]):
            if prev["cq"]:
                self._uncharge(st, prev["cq"], prev["demand"])
            self._charge(st, gk, cq, cur["demand"], revision, check=check)

    def _charge(self, st: _StoreState, gk: str, cq: str, demand: dict,
                revision: int, check: bool) -> None:
        if not cq:
            return
        nominal = st.cqs.get(cq, {}).get("nominal", {})
        usage = st.usage.setdefault(cq, {})
        for res, amt in demand.items():
            if res in nominal:  # ungoverned resources are not charged
                usage[res] = usage.get(res, 0.0) + amt
        if not check:
            return
        self.checks[QUOTA_CONSERVATION] += 1
        cohort = st.cqs.get(cq, {}).get("cohort", "")
        members = ([n for n, c in st.cqs.items() if c["cohort"] == cohort]
                   if cohort else [cq])
        totals: dict = {}
        used: dict = {}
        for name in members:
            for res, cap in st.cqs.get(name, {}).get("nominal", {}).items():
                totals[res] = totals.get(res, 0.0) + cap
            for res, amt in st.usage.get(name, {}).items():
                used[res] = used.get(res, 0.0) + amt
        for res, amt in used.items():
            if amt > totals.get(res, 0.0) + 1e-6:
                self._violate(
                    QUOTA_CONSERVATION, gk, revision,
                    f"cohort {cohort or cq}: admitted {res} usage {amt} "
                    f"exceeds cohort nominal {totals.get(res, 0.0)} "
                    f"(admitting {gk} broke conservation)")

    @staticmethod
    def _uncharge(st: _StoreState, cq: str, demand: dict) -> None:
        nominal = st.cqs.get(cq, {}).get("nominal", {})
        usage = st.usage.setdefault(cq, {})
        for res, amt in demand.items():
            if res in nominal:
                usage[res] = max(0.0, usage.get(res, 0.0) - amt)

    # -- gang atomicity ---------------------------------------------------

    def _update_partial(self, st: _StoreState, gk: str,
                        revision: int) -> None:
        bound = len(st.bound_by_gang.get(gk, ()))
        need = st.groups.get(gk, {}).get("min_member", 0)
        if need and 0 < bound < need:
            st.partial_since.setdefault(gk, revision)
        else:
            st.partial_since.pop(gk, None)
        self._update_strand(st, gk, revision)

    # -- migration-no-strand ----------------------------------------------

    def _update_strand(self, st: _StoreState, gk: str,
                       revision: int) -> None:
        """Re-evaluate the migrating gang's hold set after any change
        to its bound members, its migration phase, or its reservation.
        BOTH-charged (reservation overlapping the gang's own bound
        chips) fires immediately; holding NEITHER starts the
        revision-graced strand clock (the scheduler releases the
        reservation a few writes before the binds land)."""
        info = st.groups.get(gk)
        if not info or not info.get("migration_open"):
            st.strand_since.pop(gk, None)
            return
        res_pairs = self._reservations.get(gk) or set()
        bound = st.bound_by_gang.get(gk) or set()
        if res_pairs:
            held = set()
            for pk in bound:
                held |= st.pod_chips.get(pk, set())
            overlap = held & res_pairs
            if overlap:
                node, cid = sorted(overlap)[0]
                self._violate(
                    MIGRATION_NO_STRAND, gk, revision,
                    f"migration round holds BOTH: target reservation "
                    f"overlaps {len(overlap)} chip(s) the gang is "
                    f"still bound to (e.g. {cid} on {node}) — the "
                    f"same capacity is charged twice")
        if not bound and not res_pairs:
            st.strand_since.setdefault(gk, revision)
        else:
            st.strand_since.pop(gk, None)

    def _check_partials(self, st: _StoreState, revision: int) -> None:
        self.checks[GANG_ATOMICITY] += 1
        for gk, since in list(st.partial_since.items()):
            if revision - since > self.partial_grace_revs:
                bound = len(st.bound_by_gang.get(gk, ()))
                need = st.groups.get(gk, {}).get("min_member", 0)
                self._violate(
                    GANG_ATOMICITY, gk, revision,
                    f"gang partially bound ({bound}/{need}) while the "
                    f"store advanced {revision - since} revisions "
                    f"(> {self.partial_grace_revs} quorum grace)")
        for gk, since in list(st.strand_since.items()):
            if revision - since > self.partial_grace_revs:
                self._violate(
                    MIGRATION_NO_STRAND, gk, revision,
                    f"gang with an open migration round holds NEITHER "
                    f"its source placement nor its target reservation "
                    f"for {revision - since} revisions "
                    f"(> {self.partial_grace_revs} grace) — stranded: "
                    f"the migration degraded to an eviction")

    # -- final checks -----------------------------------------------------

    def check_final(self) -> None:
        """End-of-scenario checks: WAL-replay equivalence per attached
        store, any still-partial gangs, and — when replication ran —
        committed-entry durability on every converged replica."""
        self._check_replica_groups()
        for st in self._stores:
            self._check_partials(st, st.store.revision)
            self.checks[WAL_REPLAY] += 1
            live = st.store.state()
            live_flat = {k: (_canon(v["value"]), v["mod_revision"],
                             v["create_revision"])
                         for k, v in live["data"].items()}
            if live["rev"] == st.shadow_rev and live_flat == st.shadow:
                continue
            detail = ("revision skew" if live["rev"] != st.shadow_rev
                      else "content skew")
            for k in sorted(set(live_flat) | set(st.shadow)):
                if live_flat.get(k) != st.shadow.get(k):
                    detail = f"first divergent key: {k}"
                    break
            self._violate(
                WAL_REPLAY, "<store>", live["rev"],
                f"live store diverged from its own write stream "
                f"({detail}) — state was mutated behind the log's back")

    # -- verdicts ---------------------------------------------------------

    def _violate(self, invariant: str, key: str, revision: int,
                 message: str) -> None:
        if (invariant, key) in self._reported:
            return
        self._reported.add((invariant, key))
        v = Violation(invariant, key, message, revision)
        self.violations.append(v)
        log.error("tpusan violation: %s", v)

    def report(self) -> dict:
        return {"checks": dict(self.checks),
                "violations": [str(v) for v in self.violations]}

    def assert_clean(self) -> None:
        if self.violations:
            lines = "\n  ".join(str(v) for v in self.violations)
            raise AssertionError(
                f"tpusan: {len(self.violations)} invariant violation(s):\n"
                f"  {lines}")


#: Process-global registry new stores self-attach to; None = disarmed.
SANITIZER: Optional[InvariantRegistry] = None


def arm(registry: Optional[InvariantRegistry] = None) -> InvariantRegistry:
    global SANITIZER
    SANITIZER = registry or InvariantRegistry()
    return SANITIZER


def disarm() -> None:
    global SANITIZER
    SANITIZER = None


def note_reclaim(group_key: str) -> None:
    """Module-level seam for QueueController._unadmit: no-op unless a
    sanitizer is armed."""
    if SANITIZER is not None:
        SANITIZER.note_reclaim(group_key)


def note_reservation(owner: str, pairs) -> None:
    """Seam for SchedulerCache.reserve; no-op unless armed."""
    if SANITIZER is not None:
        SANITIZER.note_reservation(owner, pairs)


def note_reservation_gone(owner: str) -> None:
    """Seam for SchedulerCache.release_reservation (covers TTL expiry
    too — _live_reservations expires through release); no-op unless
    armed."""
    if SANITIZER is not None:
        SANITIZER.note_reservation_gone(owner)


def note_store_reset(store) -> None:
    """Seam for MVCCStore.reset_from_state (snapshot install): rebuild
    the attached shadow/indexes; no-op unless armed."""
    if SANITIZER is not None:
        SANITIZER.reseed_store(store)


def register_replica_store(group: str, node_id: str, store) -> None:
    """Seam for ReplicaNode construction; no-op unless armed."""
    if SANITIZER is not None:
        SANITIZER.register_replica_store(group, node_id, store)


def note_leader(group: str, node_id: str, term: int) -> None:
    """Seam for ReplicaNode._become_leader; no-op unless armed."""
    if SANITIZER is not None:
        SANITIZER.note_leader(group, node_id, term)


def note_replica_down(group: str, node_id: str) -> None:
    """Seam for ReplicaNode.crash; no-op unless armed."""
    if SANITIZER is not None:
        SANITIZER.note_replica_down(group, node_id)


def note_commit(group: str, rev: int, op: str, key: str, value) -> None:
    """Seam for ReplicaNode._set_commit; no-op unless armed."""
    if SANITIZER is not None:
        SANITIZER.note_commit(group, rev, op, key, value)
