"""CLI: ``python -m kubernetes_tpu.analysis [--json] [--check name]... [path]...``

Exit status 0 when the tree is clean, 1 when any finding survives
suppression — the contract ``hack/verify.sh`` builds on. ``--json``
emits one machine-readable document (``{"findings": [...], "count"}``
with file/line/col/pass/message records) so CI and tooling consume
findings without parsing the human table; the exit-code contract is
identical.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from . import REGISTRY, run_tree


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpuvet", description="repo-specific static analysis suite")
    ap.add_argument("paths", nargs="*", help="files/dirs to scan "
                    "(default: the kubernetes_tpu package)")
    ap.add_argument("--check", action="append", dest="checks", metavar="NAME",
                    help="run only this pass (repeatable); default: all")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output: one JSON document with "
                    "file/line/col/pass/message records")
    args = ap.parse_args(argv)

    if args.list:
        for name in sorted(REGISTRY):
            print(f"{name}: {REGISTRY[name]().description}")
        return 0

    if args.checks:
        unknown = [c for c in args.checks if c not in REGISTRY]
        if unknown:
            print(f"tpuvet: unknown pass(es): {', '.join(unknown)} "
                  f"(--list shows all)", file=sys.stderr)
            return 2

    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    findings = run_tree(*paths, checks=args.checks)
    if args.as_json:
        print(json.dumps({
            "findings": [
                {"file": f.path, "line": f.line, "col": f.col,
                 "pass": f.check, "message": f.message}
                for f in findings],
            "count": len(findings),
        }, indent=1))
        return 1 if findings else 0
    for f in findings:
        print(f)
    if findings:
        print(f"tpuvet: {len(findings)} finding(s) in "
              f"{len(set(f.path for f in findings))} file(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
