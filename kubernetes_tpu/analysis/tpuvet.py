"""tpuvet framework: file walking, pass registry, findings, suppression.

Design mirrors ``go vet``: each pass is a named analyzer over one
module's AST, with an optional ``finalize`` hook that runs after every
module has been visited (for cross-file properties like metric-name
collisions). A finding on a physical line carrying a
``# tpuvet: ignore`` or ``# tpuvet: ignore[pass-name]`` comment is
suppressed — the escape hatch for the rare legitimate exception, meant
to be visible and greppable, not routine.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

#: Generated / vendored files the suite never inspects.
SKIP_FILE_RE = re.compile(r"(_pb2\.py|_pb2_grpc\.py)$")
_IGNORE_RE = re.compile(r"#\s*tpuvet:\s*ignore(?:\[([A-Za-z0-9_,\- ]+)\])?")


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    check: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.check}] {self.message}"


@dataclass
class Module:
    """One parsed source file handed to every pass."""

    path: str
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    @classmethod
    def parse(cls, path: str, source: str) -> "Module":
        return cls(path=path, source=source, tree=ast.parse(source),
                   lines=source.splitlines())


class Context:
    """Shared state across passes and modules within one run."""

    def __init__(self) -> None:
        self.modules: list[Module] = []
        #: Free-form per-pass scratch space keyed by pass name.
        self.state: dict[str, dict] = {}

    def scratch(self, pass_name: str) -> dict:
        return self.state.setdefault(pass_name, {})


class Pass:
    """Base analyzer. Subclass, set ``name``/``description``, register."""

    name = "pass"
    description = ""

    def check_module(self, ctx: Context, mod: Module) -> Iterable[Finding]:
        return ()

    def finalize(self, ctx: Context) -> Iterable[Finding]:
        return ()


#: pass name -> pass class (populated by @register at import time).
REGISTRY: dict[str, type[Pass]] = {}


def register(cls: type[Pass]) -> type[Pass]:
    if cls.name in REGISTRY and REGISTRY[cls.name] is not cls:
        raise ValueError(f"duplicate tpuvet pass name {cls.name!r}")
    REGISTRY[cls.name] = cls
    return cls


def _suppressed(mod: Module, f: Finding) -> bool:
    if not 1 <= f.line <= len(mod.lines):
        return False
    m = _IGNORE_RE.search(mod.lines[f.line - 1])
    if m is None:
        return False
    names = m.group(1)
    if names is None:
        return True  # blanket ignore
    return f.check in {n.strip() for n in names.split(",")}


def iter_py_files(root: str) -> Iterable[str]:
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if not d.startswith(".") and d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py") and not SKIP_FILE_RE.search(fn):
                yield os.path.join(dirpath, fn)


def _run_modules(modules: list[Module],
                 checks: Optional[Iterable[str]] = None) -> list[Finding]:
    ctx = Context()
    ctx.modules = modules
    names = list(checks) if checks is not None else sorted(REGISTRY)
    passes = [REGISTRY[n]() for n in names]
    by_path = {m.path: m for m in modules}
    findings: list[Finding] = []
    for p in passes:
        for mod in modules:
            findings.extend(p.check_module(ctx, mod))
        findings.extend(p.finalize(ctx))
    findings = [f for f in findings
                if f.path not in by_path or not _suppressed(by_path[f.path], f)]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.check))
    return findings


def run_tree(*roots: str, checks: Optional[Iterable[str]] = None
             ) -> list[Finding]:
    """Run the (selected) passes over every .py file under ``roots``."""
    modules = []
    seen: set = set()
    for root in roots:
        for path in iter_py_files(root):
            # Overlapping roots (e.g. an explicit path plus the default
            # package) must not double-parse a file — the metric-name
            # collision pass would see every site twice.
            real = os.path.realpath(path)
            if real in seen:
                continue
            seen.add(real)
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            try:
                modules.append(Module.parse(path, src))
            except SyntaxError as e:
                # Fail fast: an unparseable file is finding #1.
                return [Finding(path, e.lineno or 0, e.offset or 0,
                                "syntax", f"does not parse: {e.msg}")]
    return _run_modules(modules, checks)


def run_source(source: str, path: str = "<string>",
               checks: Optional[Iterable[str]] = None) -> list[Finding]:
    """Run passes over one in-memory snippet (the test-fixture entry)."""
    return _run_modules([Module.parse(path, source)], checks)
