"""kubernetes_tpu — a TPU-native cluster-orchestration framework.

A ground-up re-design of the capabilities of the reference NVIDIA-GPU
Kubernetes fork (see SURVEY.md) with a TPU-first resource model:

- Devices are *chips with ICI mesh coordinates*, not opaque counters
  (cf. reference ``staging/src/k8s.io/api/core/v1/types.go:4018-4056``).
- Pod requests are *slice shapes* (e.g. ``2x2x4``) with attribute affinity.
- Placement is *gang + contiguous sub-mesh allocation* on the 3D torus
  (the reference's extended-resource matcher is flat:
  ``plugin/pkg/scheduler/core/extended_resources.go:113-150``).
- Architecture invariants kept from the reference: all state in a
  strongly-consistent MVCC store, watch-based level-triggered reconcile,
  declarative desired-state objects, hub-and-spoke through the API
  server, vendor-neutral node<->device gRPC seam.

Layer map (mirrors SURVEY.md section 1):

- L0/L1  ``api/``            object model, scheme/codec, validation
- L3     ``storage/``        MVCC store w/ revisions + watch (etcd3 semantics)
-        ``apiserver/``      REST+watch server, registry, admission
- L2     ``client/``         REST client, informers, workqueue, leader election
- L4b    ``scheduler/``      gang + sub-mesh TPU placement
- L4a    ``controllers/``    workload + node-lifecycle reconcile loops
- L5     ``node/``           node agent (kubelet equivalent), device manager
-        ``deviceplugin/``   TPU device plugin (gRPC, libtpu-backed)
- L6     ``cli/``            ktl command-line client
- X      ``metrics/``        prometheus-style registries
-        ``workloads/``      JAX payloads the orchestrator schedules
"""

__version__ = "0.1.0"
