import os as _os
import sys as _sys

# Generated protobuf module references itself as top-level `api_pb2`.
_sys.path.insert(0, _os.path.dirname(_os.path.abspath(__file__)))

from . import api_pb2  # noqa: E402,F401
from .service import (TpuDevicePluginClient, TpuDevicePluginServicer,  # noqa: E402,F401
                      add_servicer_to_server)
