"""gRPC service plumbing for the device-plugin API.

grpc_tools (the protoc gRPC python plugin) is not in the image, so the
service/stub layer is written against grpc's generic handler API with
protoc-generated message classes — functionally identical to generated
``*_pb2_grpc.py`` code (method paths follow the same
``/package.Service/Method`` convention, so foreign gRPC clients
interoperate).
"""
from __future__ import annotations

from typing import Iterator

import grpc

from . import api_pb2 as pb

SERVICE = "tpudeviceplugin.v1.TpuDevicePlugin"


class TpuDevicePluginServicer:
    """Subclass and override; default implementations reject."""

    def GetPluginInfo(self, request: pb.Empty, context) -> pb.PluginInfo:
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "GetPluginInfo")

    def ListAndWatch(self, request: pb.Empty, context) -> Iterator[pb.TopologyUpdate]:
        context.abort(grpc.StatusCode.UNIMPLEMENTED, "ListAndWatch")

    def AdmitPod(self, request: pb.AdmitPodRequest, context) -> pb.AdmitPodResponse:
        return pb.AdmitPodResponse(allowed=True)

    def InitContainer(self, request: pb.InitContainerRequest,
                      context) -> pb.InitContainerResponse:
        return pb.InitContainerResponse()


def add_servicer_to_server(servicer: TpuDevicePluginServicer, server: grpc.Server) -> None:
    handlers = {
        "GetPluginInfo": grpc.unary_unary_rpc_method_handler(
            servicer.GetPluginInfo,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.PluginInfo.SerializeToString),
        "ListAndWatch": grpc.unary_stream_rpc_method_handler(
            servicer.ListAndWatch,
            request_deserializer=pb.Empty.FromString,
            response_serializer=pb.TopologyUpdate.SerializeToString),
        "AdmitPod": grpc.unary_unary_rpc_method_handler(
            servicer.AdmitPod,
            request_deserializer=pb.AdmitPodRequest.FromString,
            response_serializer=pb.AdmitPodResponse.SerializeToString),
        "InitContainer": grpc.unary_unary_rpc_method_handler(
            servicer.InitContainer,
            request_deserializer=pb.InitContainerRequest.FromString,
            response_serializer=pb.InitContainerResponse.SerializeToString),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),))


class TpuDevicePluginClient:
    """Blocking client over a unix socket (callers wrap in to_thread)."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._channel = grpc.insecure_channel(f"unix://{socket_path}")
        p = f"/{SERVICE}/"
        self._get_info = self._channel.unary_unary(
            p + "GetPluginInfo", request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.PluginInfo.FromString)
        self._law = self._channel.unary_stream(
            p + "ListAndWatch", request_serializer=pb.Empty.SerializeToString,
            response_deserializer=pb.TopologyUpdate.FromString)
        self._admit = self._channel.unary_unary(
            p + "AdmitPod", request_serializer=pb.AdmitPodRequest.SerializeToString,
            response_deserializer=pb.AdmitPodResponse.FromString)
        self._init = self._channel.unary_unary(
            p + "InitContainer",
            request_serializer=pb.InitContainerRequest.SerializeToString,
            response_deserializer=pb.InitContainerResponse.FromString)

    def get_plugin_info(self, timeout: float = 5.0) -> pb.PluginInfo:
        return self._get_info(pb.Empty(), timeout=timeout)

    def list_and_watch(self) -> Iterator[pb.TopologyUpdate]:
        return self._law(pb.Empty())

    def admit_pod(self, namespace: str, name: str, uid: str,
                  chip_ids: list[str], timeout: float = 5.0) -> pb.AdmitPodResponse:
        return self._admit(pb.AdmitPodRequest(
            pod_namespace=namespace, pod_name=name, pod_uid=uid,
            chip_ids=chip_ids), timeout=timeout)

    def init_container(self, namespace: str, name: str, uid: str,
                       container: str, chip_ids: list[str],
                       timeout: float = 5.0) -> pb.InitContainerResponse:
        return self._init(pb.InitContainerRequest(
            pod_namespace=namespace, pod_name=name, pod_uid=uid,
            container_name=container, chip_ids=chip_ids), timeout=timeout)

    def close(self) -> None:
        self._channel.close()
