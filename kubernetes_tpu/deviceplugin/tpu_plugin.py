"""Real TPU device plugin — enumerates the host's actual chips.

TPU-native analog of the out-of-tree nvidia-gpu-device-plugin the
reference deploys (``cluster/addons/device-plugins/nvidia-gpu/
daemonset.yaml:39-41``) serving the device-plugin gRPC service
(``pkg/kubelet/apis/deviceplugin/v1alpha/api.proto:17-31``) over NVML.

Design difference forced by the hardware: NVML is a side-channel query
library, but libtpu is the *compute* runtime and a chip is owned by one
process. A plugin that imported jax/libtpu in-process would hold the
very chips its pods need. So enumeration runs in a short-lived probe
subprocess (crash-isolated, like the reference's dlopen shim keeps NVML
faults out of the kubelet — ``vendor/github.com/mindprince/gonvml/
bindings.go:19-30``), and the plugin process itself never initializes a
TPU backend.

``InitContainer`` injects the env a JAX workload needs to find its
assigned chips (the analog of the NVIDIA runtime's device injection):
``JAX_PLATFORMS`` (the platform spec the probe validated),
``TPU_VISIBLE_DEVICES``/``TPU_VISIBLE_CHIPS`` and topology env.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Optional

from . import api_pb2 as pb
from .stub import StubTpuPlugin

RESOURCE_TPU = "google.com/tpu"

#: Runs under the *real* platform env; prints one JSON line.
_PROBE_SRC = r"""
import json, sys
try:
    import jax
    devices = jax.local_devices()
    backend = jax.default_backend()
except Exception as e:  # noqa: BLE001
    print(json.dumps({"tpu": False, "error": str(e)}))
    sys.exit(0)
if backend != "tpu" or not devices:
    print(json.dumps({"tpu": False, "backend": backend}))
    sys.exit(0)
out = {"tpu": True, "backend": backend,
       "process_index": devices[0].process_index, "devices": []}
for d in devices:
    coords = list(getattr(d, "coords", None) or (d.id, 0, 0))
    entry = {
        "index": d.id,
        "kind": d.device_kind,
        "coords": coords,
        "core_on_chip": getattr(d, "core_on_chip", 0),
    }
    try:
        ms = d.memory_stats() or {}
        entry["memory"] = {"hbm_used_bytes": ms.get("bytes_in_use", 0),
                           "hbm_total_bytes": ms.get("bytes_limit", 0)}
    except Exception:  # noqa: BLE001 — not exposed on every backend
        pass
    out["devices"].append(entry)
print(json.dumps(out))
"""


def _probe_env() -> dict[str, str]:
    """The env the probe (and TPU pods) should run under: the session's
    real platform spec, undoing any test-harness CPU forcing."""
    env = dict(os.environ)
    orig = env.pop("KTPU_JAX_PLATFORMS_ORIG", None)
    if env.get("JAX_PLATFORMS") == "cpu":
        if orig:
            env["JAX_PLATFORMS"] = orig
        else:
            env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)  # virtual-device forcing breaks real probes
    return env


def _find_libtpu() -> Optional[str]:
    """Locate libtpu.so without importing it (the jax wheel vendors it
    as the ``libtpu`` package)."""
    if os.environ.get("TPU_LIBRARY_PATH"):
        return os.environ["TPU_LIBRARY_PATH"]
    try:
        import importlib.util
        spec = importlib.util.find_spec("libtpu")
        if spec and spec.submodule_search_locations:
            path = os.path.join(
                list(spec.submodule_search_locations)[0], "libtpu.so")
            if os.path.exists(path):
                return path
    except (ImportError, ValueError, AttributeError, OSError):
        # libtpu absent or its spec unreadable: no shared object to
        # advertise; the stub backend takes over.
        pass
    return None


def _run_probe(cmd: list[str], timeout: float) -> Optional[dict]:
    try:
        proc = subprocess.run(cmd, env=_probe_env(), capture_output=True,
                              text=True, timeout=timeout)
    except (OSError, subprocess.TimeoutExpired):
        return None
    line = proc.stdout.strip().splitlines()
    if not line:
        return None
    try:
        probe = json.loads(line[-1])
    except json.JSONDecodeError:
        return None
    return probe if probe.get("tpu") else None


def detect_topology(timeout: float = 120.0) -> Optional[dict]:
    """Probe the host's TPUs in a crash-isolated subprocess. Returns
    the probe dict or None when the host has no usable TPU.

    Two probes, same JSON contract: the native PJRT binary
    (``native/libtpu_probe.cpp``, the gonvml-analog dlopen shim) is
    tried first — it enumerates local chips without paying a Python/
    jax startup; the jax subprocess is the fallback and also covers
    non-local backends (e.g. tunneled TPU-VMs) that only the installed
    jax plugin can reach.

    ``timeout`` is a total budget for the whole chain (first call may
    additionally pay a one-time g++ build of the native probe, itself
    bounded at 300s)."""
    import time

    from kubernetes_tpu.native import build_libtpu_probe
    native = build_libtpu_probe()  # one-time compile outside the budget
    deadline = time.monotonic() + timeout
    if native:
        cmd = [native]
        lib = _find_libtpu()
        if lib:
            cmd.append(lib)
        # The native probe is near-instant when there's no local TPU;
        # cap it at half the budget so the jax fallback always gets a
        # usable share.
        probe = _run_probe(cmd, max(1.0, (deadline - time.monotonic()) / 2))
        if probe is not None:
            return probe
    remaining = deadline - time.monotonic()
    if remaining <= 0:
        return None
    return _run_probe([sys.executable, "-c", _PROBE_SRC], remaining)


def _chip_type_of(kind: str) -> str:
    """'TPU v5 lite' -> 'v5e', 'TPU v5p chip' -> 'v5p', else slug."""
    k = kind.lower()
    if "v5 lite" in k or "v5e" in k:
        return "v5e"
    for tag in ("v5p", "v4", "v3", "v2", "v6e", "v6"):
        if tag in k:
            return tag
    return kind.replace(" ", "-").lower()


def topology_from_probe(probe: dict, slice_id: str = "",
                        id_prefix: str = "tpu") -> pb.TopologyUpdate:
    devices = probe["devices"]
    dims = 3
    bounds = [1] * dims
    for d in devices:
        for i, c in enumerate(d["coords"][:dims]):
            bounds[i] = max(bounds[i], c + 1)
    update = pb.TopologyUpdate(
        chip_type=_chip_type_of(devices[0]["kind"]),
        slice_id=slice_id or f"slice-{os.uname().nodename}",
        mesh_shape=bounds,
        worker_index=int(probe.get("process_index", 0)))
    for d in devices:
        update.chips.add(
            id=f"{id_prefix}-{d['index']}", health="Healthy",
            coords=list(d["coords"][:dims]),
            attributes={"chip_type": update.chip_type,
                        "device_kind": d["kind"],
                        "device_index": str(d["index"])})
    return update


class TpuDevicePlugin(StubTpuPlugin):
    """The production plugin: real topology from the probe, and
    InitContainer env that points a JAX pod at its assigned chips."""

    #: Real hardware behind this plugin: the chaos driver must not
    #: inject health faults here (see StubTpuPlugin.chaos_drivable).
    chaos_drivable = False

    def __init__(self, probe: Optional[dict] = None,
                 resource: str = RESOURCE_TPU, slice_id: str = ""):
        probe = probe or detect_topology()
        if probe is None:
            raise RuntimeError("no TPU found on this host (probe failed)")
        super().__init__(topology_from_probe(probe, slice_id=slice_id),
                         resource=resource)
        self._probe = probe
        self._platform_spec = _probe_env().get("JAX_PLATFORMS", "")

    def chip_metrics(self) -> dict:
        """Per-chip HBM stats from the startup probe — the
        AcceleratorStats/DCGM seam (``node/stats.py chip_metrics``).
        Values are a snapshot (the plugin process must not own libtpu;
        the probe pays a full jax init, too heavy per scrape) and {} on
        backends that expose no memory stats (e.g. tunneled TPU-VMs)."""
        out = {}
        for d in self._probe.get("devices", []):
            mem = d.get("memory")
            if mem and mem.get("hbm_total_bytes"):
                # 'used' at probe time (before any workload owns the
                # chip) is NOT live utilization — publish it under a
                # name that says so; total is static and trustworthy.
                out[f"tpu-{d['index']}"] = {
                    "hbm_total_bytes": mem["hbm_total_bytes"],
                    "hbm_used_at_probe_bytes": mem.get("hbm_used_bytes", 0),
                }
        return out

    def InitContainer(self, request, context) -> pb.InitContainerResponse:
        resp = super().InitContainer(request, context)
        index_of = {c.id: c.attributes.get("device_index", "")
                    for c in self._topology.chips}
        indices = [index_of[cid] for cid in request.chip_ids if cid in index_of]
        resp.envs["TPU_VISIBLE_DEVICES"] = ",".join(indices)
        if self._platform_spec:
            resp.envs["JAX_PLATFORMS"] = self._platform_spec
        else:
            # Pods under a CPU-forced harness must still see the chip.
            resp.envs["JAX_PLATFORMS"] = ""
        return resp


def main() -> None:
    """Run the plugin standalone against a node agent's plugin dir:
    ``python -m kubernetes_tpu.deviceplugin.tpu_plugin <plugin-dir>``."""
    import signal
    import threading

    plugin_dir = sys.argv[1] if len(sys.argv) > 1 else "/var/lib/ktpu/device-plugins"
    plugin = TpuDevicePlugin()
    sock = os.path.join(plugin_dir, "tpu.sock")
    plugin.serve(sock)
    print(json.dumps({"serving": sock,
                      "chips": len(plugin._topology.chips),
                      "chip_type": plugin._topology.chip_type}), flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    plugin.stop()


if __name__ == "__main__":
    main()
