"""Stub TPU device plugin — the hardware-free test double.

Reference: ``pkg/kubelet/cm/devicemanager/device_plugin_stub.go:57
NewDevicePluginStub`` — an in-process fake vendor plugin serving the
real gRPC API over a temp socket; the pattern for exercising the whole
device flow (registration, ListAndWatch, admit, init) without chips.
Used by unit tests, node e2e, and kubemark hollow TPU nodes.
"""
from __future__ import annotations

import itertools
import os
import queue
import time
from concurrent import futures
from typing import Iterator, Optional

import grpc

from ..util.lockdep import make_lock
from . import api_pb2 as pb
from .service import TpuDevicePluginServicer, add_servicer_to_server


def make_topology(chip_type: str = "v5p", slice_id: str = "stub-slice",
                  mesh_shape: tuple = (2, 2, 1), worker_index: int = 0,
                  host_chips: Optional[list[tuple]] = None,
                  id_prefix: str = "chip") -> pb.TopologyUpdate:
    """Build a TopologyUpdate; ``host_chips``: list of coord tuples this
    host owns (default: the whole mesh)."""
    if host_chips is None:
        host_chips = list(itertools.product(*(range(d) for d in mesh_shape)))
    u = pb.TopologyUpdate(chip_type=chip_type, slice_id=slice_id,
                          mesh_shape=list(mesh_shape), worker_index=worker_index)
    for i, coords in enumerate(host_chips):
        u.chips.add(id=f"{id_prefix}-{i}", health="Healthy",
                    coords=list(coords),
                    attributes={"chip_type": chip_type})
    return u


class StubTpuPlugin(TpuDevicePluginServicer):
    #: Chaos (chaos/driver.py) may flip this plugin's chip health: the
    #: topology is synthetic. Subclasses fronting REAL hardware
    #: (TpuDevicePlugin) override to False — chaos must never write to
    #: production device state.
    chaos_drivable = True

    def __init__(self, topology: pb.TopologyUpdate, resource: str = "google.com/tpu"):
        self.resource = resource
        self._topology = topology
        self._subscribers: list[queue.Queue] = []
        self._lock = make_lock("deviceplugin.Stub")
        self.admit_calls: list[pb.AdmitPodRequest] = []
        self.init_calls: list[pb.InitContainerRequest] = []
        #: Set to a reason string to make AdmitPod reject.
        self.reject_reason: Optional[str] = None
        self._server: Optional[grpc.Server] = None
        self.socket_path: Optional[str] = None
        #: Simulated per-chip HBM capacity (v5p-ish 95GiB is overkill
        #: for a sim; 16GiB keeps the arithmetic readable).
        self.sim_hbm_total = 16 * 2**30
        #: Driver-sim state for :meth:`chip_metrics` — ICI byte
        #: counters advance with wall time so scrapes see motion.
        self._sim_ici: dict[str, dict[str, float]] = {}
        self._sim_last = time.monotonic()

    def chip_metrics(self) -> dict:
        """Per-chip telemetry from the DRIVER SIM — the DCGM/nvml
        analog of the reference's accelerator stats, hardware-free:
        duty cycle + HBM occupancy derived deterministically from the
        chip index (same chip -> same load profile across runs), ICI
        link tx/rx counters advancing with wall time at a rate
        proportional to the duty cycle. Unhealthy chips read 0% duty
        and 0 B/s ICI — exactly what a wedged chip looks like from the
        host. Feeds ``node/stats.py`` (``chip_metrics`` seam) and the
        ``tpu_*`` gauge family (node/telemetry.py)."""
        now = time.monotonic()
        with self._lock:
            dt = max(now - self._sim_last, 0.0)
            self._sim_last = now
            out: dict = {}
            for i, chip in enumerate(self._topology.chips):
                healthy = chip.health == "Healthy"
                # Deterministic per-chip duty profile: spread across
                # 35-90% so aggregation has real variance to report.
                duty = (35.0 + (i * 17) % 56) if healthy else 0.0
                ici = self._sim_ici.setdefault(
                    chip.id, {"tx_bytes": 0.0, "rx_bytes": 0.0})
                # ICI moves proportionally to duty (~1.2 GB/s per 100%
                # duty per direction — sim scale, not hardware claims).
                ici["tx_bytes"] += duty / 100.0 * 1.2e9 * dt
                ici["rx_bytes"] += duty / 100.0 * 1.1e9 * dt
                out[chip.id] = {
                    "duty_cycle_pct": duty,
                    "hbm_total_bytes": self.sim_hbm_total,
                    "hbm_used_bytes": int(self.sim_hbm_total
                                          * duty / 100.0 * 0.7),
                    "ici_tx_bytes": int(ici["tx_bytes"]),
                    "ici_rx_bytes": int(ici["rx_bytes"]),
                    "ici_links": 6 if healthy else 0,  # 3D torus degree
                }
            return out

    # -- service ----------------------------------------------------------

    def GetPluginInfo(self, request, context) -> pb.PluginInfo:
        return pb.PluginInfo(resource=self.resource, version="v1")

    def ListAndWatch(self, request, context) -> Iterator[pb.TopologyUpdate]:
        q: queue.Queue = queue.Queue()
        with self._lock:
            self._subscribers.append(q)
            snapshot = pb.TopologyUpdate()
            snapshot.CopyFrom(self._topology)
        yield snapshot
        try:
            while True:
                update = q.get()
                if update is None:
                    return
                yield update
        finally:
            with self._lock:
                if q in self._subscribers:
                    self._subscribers.remove(q)

    def AdmitPod(self, request, context) -> pb.AdmitPodResponse:
        self.admit_calls.append(request)
        if self.reject_reason:
            return pb.AdmitPodResponse(allowed=False, reason=self.reject_reason)
        known = {c.id for c in self._topology.chips}
        missing = [c for c in request.chip_ids if c not in known]
        if missing:
            return pb.AdmitPodResponse(allowed=False,
                                       reason=f"unknown chips {missing}")
        return pb.AdmitPodResponse(allowed=True)

    def InitContainer(self, request, context) -> pb.InitContainerResponse:
        self.init_calls.append(request)
        resp = pb.InitContainerResponse()
        topo = self._topology
        resp.envs["TPU_VISIBLE_CHIPS"] = ",".join(request.chip_ids)
        resp.envs["TPU_CHIP_TYPE"] = topo.chip_type
        resp.envs["TPU_SLICE_ID"] = topo.slice_id
        resp.envs["TPU_WORKER_ID"] = str(topo.worker_index)
        resp.envs["TPU_MESH_SHAPE"] = "x".join(str(d) for d in topo.mesh_shape)
        coords = {c.id: c.coords for c in topo.chips}
        resp.envs["TPU_CHIP_COORDS"] = ";".join(
            ",".join(map(str, coords[cid])) for cid in request.chip_ids
            if cid in coords)
        resp.annotations["tpu.dev/chips"] = ",".join(request.chip_ids)
        return resp

    # -- mutation from tests ----------------------------------------------

    def set_chip_health(self, chip_id: str, health: str) -> None:
        with self._lock:
            for c in self._topology.chips:
                if c.id == chip_id:
                    c.health = health
            update = pb.TopologyUpdate()
            update.CopyFrom(self._topology)
            for q in self._subscribers:
                q.put(update)

    # -- lifecycle --------------------------------------------------------

    def serve(self, socket_path: str) -> None:
        if os.path.exists(socket_path):
            os.unlink(socket_path)
        os.makedirs(os.path.dirname(socket_path), exist_ok=True)
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        add_servicer_to_server(self, self._server)
        self._server.add_insecure_port(f"unix://{socket_path}")
        self._server.start()
        self.socket_path = socket_path

    def stop(self) -> None:
        with self._lock:
            for q in self._subscribers:
                q.put(None)
        if self._server:
            self._server.stop(grace=0.2)
            self._server = None
        if self.socket_path and os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
