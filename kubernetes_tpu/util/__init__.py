"""Shared utilities (reference: apimachinery pkg/util + pkg/features)."""
