"""5-field cron schedule parser.

Reference: the subset of robfig/cron the reference's CronJob controller
uses (``pkg/controller/cronjob/utils.go getRecentUnmetScheduleTimes``).
Lives in util so BOTH the controller and admission-time validation
(``api/validation.py validate_cronjob``) parse schedules with the same
rules — the reference validates the schedule string at admission too
(``pkg/apis/batch/validation/validation.go ValidateCronJobSpec``).
"""
from __future__ import annotations

import datetime
from typing import Optional


class CronSchedule:
    """5-field cron (min hour dom mon dow) supporting ``*``, ``*/n``,
    lists, and ranges — the subset the reference's robfig/cron use needs."""

    _RANGES = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))
    #: robfig/cron's @-macros (reference cronjob controller accepts
    #: these; "@every" is deliberately unsupported — the reference
    #: controller's schedule spec doesn't use it either).
    _MACROS = {
        "@yearly": "0 0 1 1 *", "@annually": "0 0 1 1 *",
        "@monthly": "0 0 1 * *", "@weekly": "0 0 * * 0",
        "@daily": "0 0 * * *", "@midnight": "0 0 * * *",
        "@hourly": "0 * * * *",
    }
    _MON_NAMES = {n: i + 1 for i, n in enumerate(
        "JAN FEB MAR APR MAY JUN JUL AUG SEP OCT NOV DEC".split())}
    _DOW_NAMES = {n: i for i, n in enumerate(
        "SUN MON TUE WED THU FRI SAT".split())}

    def __init__(self, expr: str):
        expr = expr.strip()
        if expr.startswith("@"):
            try:
                expr = self._MACROS[expr.lower()]
            except KeyError:
                raise ValueError(f"unknown cron macro {expr!r}") from None
        fields = expr.split()
        if len(fields) != 5:
            raise ValueError(f"cron needs 5 fields, got {expr!r}")
        fields[3] = self._subst_names(fields[3], self._MON_NAMES)
        fields[4] = self._subst_names(fields[4], self._DOW_NAMES)
        self.sets = [self._parse(f, lo, hi)
                     for f, (lo, hi) in zip(fields, self._RANGES)]
        # Standard cron: when BOTH dom and dow are restricted, a day
        # matches if EITHER does (OR); a lone restriction is an AND.
        self.dom_star = fields[2].startswith("*")
        self.dow_star = fields[4].startswith("*")

    @staticmethod
    def _subst_names(field: str, names: dict[str, int]) -> str:
        """MON/JAN-style aliases -> numbers (robfig accepts both)."""
        def repl(tok: str) -> str:
            return str(names.get(tok.upper(), tok))
        import re as _re
        return _re.sub(r"[A-Za-z]+", lambda m: repl(m.group()), field)

    @staticmethod
    def _parse(field: str, lo: int, hi: int) -> frozenset:
        out: set[int] = set()
        for part in field.split(","):
            step = 1
            stepped = "/" in part
            if stepped:
                part, step_s = part.split("/", 1)
                step = int(step_s)
                if step < 1:
                    raise ValueError(f"cron step must be >= 1 in {part!r}")
            if part in ("*", ""):
                start, end = lo, hi
            elif "-" in part:
                a, b = part.split("-", 1)
                start, end = int(a), int(b)
            elif stepped:
                # robfig: "30/10" = range from 30 to the field max
                # stepped by 10 (30,40,50), NOT the single value 30.
                start, end = int(part), hi
            else:
                start = end = int(part)
            # Out-of-range or inverted bounds raise instead of silently
            # yielding a schedule that never fires ("60 * * * *" must
            # fail at admission, not wedge the controller's scans).
            if not (lo <= start <= hi and lo <= end <= hi):
                raise ValueError(
                    f"cron value {part!r} outside {lo}-{hi}")
            if start > end:
                raise ValueError(f"inverted cron range {part!r}")
            out.update(range(start, end + 1, step))
        return frozenset(out)

    def matches(self, dt: datetime.datetime) -> bool:
        m, h = self.sets[0], self.sets[1]
        return dt.minute in m and dt.hour in h and self._day_matches(dt.date())

    def _day_matches(self, day: datetime.date) -> bool:
        _, _, dom, mon, dow = self.sets
        if day.month not in mon:
            return False
        dom_ok = day.day in dom
        # cron dow: 0=Sunday; datetime.weekday(): 0=Monday.
        dow_ok = ((day.weekday() + 1) % 7) in dow
        if not self.dom_star and not self.dow_star:
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def prev_at_or_before(self, dt: datetime.datetime
                          ) -> Optional[datetime.datetime]:
        """Latest matching minute <= dt. O(days scanned), not O(minutes):
        walk days backward, then pick the largest in-day (hour, minute)."""
        minutes = sorted(self.sets[0], reverse=True)
        hours = sorted(self.sets[1], reverse=True)
        end = dt.replace(second=0, microsecond=0)
        day = end.date()
        for i in range(4 * 366):  # a full leap cycle bounds any schedule
            if self._day_matches(day):
                for hour in hours:
                    if i == 0 and hour > end.hour:
                        continue
                    for minute in minutes:
                        if i == 0 and hour == end.hour and minute > end.minute:
                            continue
                        return datetime.datetime.combine(
                            day, datetime.time(hour, minute), tzinfo=dt.tzinfo)
            day -= datetime.timedelta(days=1)
        return None

    def most_recent(self, since: datetime.datetime,
                    until: datetime.datetime) -> Optional[datetime.datetime]:
        """Latest matching minute in (since, until]."""
        got = self.prev_at_or_before(until)
        if got is not None and got > since.replace(second=0, microsecond=0):
            return got
        return None
