"""Background-task spawning that never swallows a crash.

A bare ``loop.create_task(coro())`` whose result is dropped is a task
leak twice over: the loop holds tasks only weakly, so an unreferenced
task can be garbage-collected mid-flight, and an exception it raises is
reported only at GC time (or never) instead of when it happened — the
async analog of the swallowed-exception sites tpuvet's first pass
cleaned out. The ``task-leak`` tpuvet pass flags such sites;
:func:`spawn` is the remediation: it retains the task until done and
logs any crash with the task name attached.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Coroutine, Optional

log = logging.getLogger("tasks")

#: Default strong-ref holder for fire-and-forget tasks.
_BACKGROUND: set = set()


def spawn(coro: Coroutine, name: Optional[str] = None,
          store: Optional[set] = None) -> asyncio.Task:
    """``create_task`` with the two fire-and-forget obligations handled:
    the task is strongly referenced until it finishes (``store``
    defaults to a module-global set) and a crash is logged instead of
    vanishing. Returns the task so callers CAN still await/cancel it."""
    task = asyncio.get_running_loop().create_task(coro, name=name)
    keep = _BACKGROUND if store is None else store
    keep.add(task)

    def _done(t: asyncio.Task, keep=keep) -> None:
        keep.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            log.error("background task %r crashed: %s",
                      t.get_name(), exc, exc_info=exc)

    task.add_done_callback(_done)
    return task
