"""Background-task spawning that never swallows a crash.

A bare ``loop.create_task(coro())`` whose result is dropped is a task
leak twice over: the loop holds tasks only weakly, so an unreferenced
task can be garbage-collected mid-flight, and an exception it raises is
reported only at GC time (or never) instead of when it happened — the
async analog of the swallowed-exception sites tpuvet's first pass
cleaned out. The ``task-leak`` tpuvet pass flags such sites;
:func:`spawn` is the remediation: it retains the task until done and
logs any crash with the task name attached.
"""
from __future__ import annotations

import asyncio
import logging
from typing import Coroutine, Optional

log = logging.getLogger("tasks")

#: Default strong-ref holder for fire-and-forget tasks.
_BACKGROUND: set = set()


def spawn(coro: Coroutine, name: Optional[str] = None,
          store: Optional[set] = None) -> asyncio.Task:
    """``create_task`` with the two fire-and-forget obligations handled:
    the task is strongly referenced until it finishes (``store``
    defaults to a module-global set) and a crash is logged instead of
    vanishing. Returns the task so callers CAN still await/cancel it."""
    task = asyncio.get_running_loop().create_task(coro, name=name)
    keep = _BACKGROUND if store is None else store
    keep.add(task)

    def _done(t: asyncio.Task, keep=keep) -> None:
        keep.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            log.error("background task %r crashed: %s",
                      t.get_name(), exc, exc_info=exc)

    task.add_done_callback(_done)
    return task


async def cancel_task(task: Optional[asyncio.Task], grace: float = 30.0,
                      name: str = "") -> bool:
    """Cancel ``task`` and wait for it to actually finish, bounded by a
    real deadline. Returns True when the task ended inside ``grace``.

    A single ``task.cancel()`` + ``await task`` is NOT enough on
    CPython ≤3.11: ``asyncio.wait_for`` swallows a cancellation that
    lands in the same window its watched future completes (CPython
    GH-86296). Concretely: cancelling a controller-manager mid-startup
    while it sits in ``informer.wait_for_sync()`` — ``wait_for`` around
    an Event — eats the CancelledError when the sync fires, and the
    manager sails on to its run-forever wait with the cancellation
    consumed; the plain await then hangs until someone cancels again.
    That was the LocalCluster.stop() "~2min teardown drain" e2e smokes
    used to dodge by composing components manually. This helper
    re-cancels on a short tick until the task is genuinely done, so
    teardown is bounded by ``grace`` instead of by luck.
    """
    if task is None or task.done():
        return True
    loop = asyncio.get_running_loop()
    deadline = loop.time() + grace
    task.cancel()
    while True:
        try:
            await asyncio.wait_for(asyncio.shield(task), 0.5)
            return True
        except asyncio.CancelledError:
            if task.done():
                return True
            raise  # the CALLER was cancelled; don't absorb it
        except asyncio.TimeoutError:
            if loop.time() >= deadline:
                log.error("task %r still running %0.0fs after cancel; "
                          "abandoning the wait (teardown stays bounded)",
                          name or task.get_name(), grace)
                return False
            # A swallowed cancellation (GH-86296) leaves the task
            # healthy and uncancelled: ask again.
            task.cancel()
        except Exception:  # noqa: BLE001 — the task's own crash
            log.exception("task %r raised during cancellation",
                          name or task.get_name())
            return True
